"""Hadoop SequenceFile IO (≙ the reference's ImageNet storage format:
dataset/image/BGRImgToLocalSeqFile.scala writes Text->Text sequence files,
LocalSeqFileToBytes.scala reads them back; utils/File SequenceFile
helpers).

Pure-python implementation of the uncompressed SequenceFile v6 layout:

    "SEQ" 0x06 | keyClass (Text) | valueClass (Text) | compressed=0 |
    blockCompressed=0 | metadata count=0 (int32 BE) | sync (16 bytes)
    then records: recordLen (int32 BE) | keyLen (int32 BE) | key | value
    with `-1 | sync` escapes every ~SYNC_INTERVAL bytes.

Keys/values are Hadoop Writables; Text serializes as vint length + bytes
(Hadoop WritableUtils VInt encoding).
"""
from __future__ import annotations

import os
import struct
from typing import Iterator, List, Tuple

SEQ_MAGIC = b"SEQ"
VERSION = 6
SYNC_INTERVAL = 2000
TEXT_CLASS = "org.apache.hadoop.io.Text"
BYTES_CLASS = "org.apache.hadoop.io.BytesWritable"


# ---- Hadoop WritableUtils VInt ---------------------------------------- #
def write_vint(value: int) -> bytes:
    if -112 <= value <= 127:
        return struct.pack("b", value)
    length = -112
    if value < 0:
        value ^= -1  # ~value
        length = -120
    tmp = value
    size = 0
    while tmp:
        tmp >>= 8
        size += 1
    out = struct.pack("b", length - size)
    return out + value.to_bytes(size, "big")


def read_vint(buf: bytes, pos: int) -> Tuple[int, int]:
    (first,) = struct.unpack_from("b", buf, pos)
    pos += 1
    if first >= -112:
        return first, pos
    negative = first < -120
    size = (-120 - first) if negative else (-112 - first)
    value = int.from_bytes(buf[pos:pos + size], "big")
    pos += size
    return (value ^ -1) if negative else value, pos


def _text(data: bytes) -> bytes:
    """Serialize as org.apache.hadoop.io.Text (vint length + raw bytes)."""
    return write_vint(len(data)) + data


def _read_text(buf: bytes, pos: int = 0) -> bytes:
    n, pos = read_vint(buf, pos)
    return buf[pos:pos + n]


def _bytes_writable(data: bytes) -> bytes:
    """BytesWritable: 4-byte BE length + raw bytes."""
    return struct.pack(">i", len(data)) + data


def _read_bytes_writable(buf: bytes) -> bytes:
    (n,) = struct.unpack_from(">i", buf, 0)
    return buf[4:4 + n]


class SequenceFileWriter:
    def __init__(self, path: str, key_class: str = TEXT_CLASS,
                 value_class: str = TEXT_CLASS, sync_seed: int = 0):
        import hashlib
        self._f = open(path, "wb")
        self.key_class = key_class
        self.value_class = value_class
        self.sync = hashlib.md5(
            f"bigdl_tpu-seq-{sync_seed}-{path}".encode()).digest()
        self._since_sync = 0
        self._write_header()

    def _write_string(self, s: str):
        b = s.encode("utf-8")
        self._f.write(write_vint(len(b)) + b)

    def _write_header(self):
        self._f.write(SEQ_MAGIC + bytes([VERSION]))
        self._write_string(self.key_class)
        self._write_string(self.value_class)
        self._f.write(b"\x00\x00")               # compressed, blockCompressed
        self._f.write(struct.pack(">i", 0))      # metadata entries
        self._f.write(self.sync)

    def _serialize(self, data: bytes, cls: str) -> bytes:
        if cls == BYTES_CLASS:
            return _bytes_writable(data)
        return _text(data)

    def append(self, key: bytes, value: bytes):
        if self._since_sync >= SYNC_INTERVAL:
            self._f.write(struct.pack(">i", -1))
            self._f.write(self.sync)
            self._since_sync = 0
        k = self._serialize(key, self.key_class)
        v = self._serialize(value, self.value_class)
        rec = (struct.pack(">i", len(k) + len(v))
               + struct.pack(">i", len(k)) + k + v)
        self._f.write(rec)
        self._since_sync += len(rec)

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SequenceFileReader:
    """Iterates (key_bytes, value_bytes)."""

    def __init__(self, path: str):
        with open(path, "rb") as f:
            self.data = f.read()
        if self.data[:3] != SEQ_MAGIC:
            raise ValueError(f"{path}: not a SequenceFile")
        if self.data[3] != VERSION:
            raise ValueError(f"{path}: unsupported SequenceFile version "
                             f"{self.data[3]}")
        pos = 4
        n, pos = read_vint(self.data, pos)
        self.key_class = self.data[pos:pos + n].decode()
        pos += n
        n, pos = read_vint(self.data, pos)
        self.value_class = self.data[pos:pos + n].decode()
        pos += n
        compressed, block = self.data[pos], self.data[pos + 1]
        if compressed or block:
            raise ValueError("compressed SequenceFiles unsupported")
        pos += 2
        (meta_count,) = struct.unpack_from(">i", self.data, pos)
        pos += 4
        for _ in range(meta_count):
            for _kv in range(2):
                n, pos = read_vint(self.data, pos)
                pos += n
        self.sync = self.data[pos:pos + 16]
        self._start = pos + 16

    def _deserialize(self, buf: bytes, cls: str) -> bytes:
        if cls == BYTES_CLASS:
            return _read_bytes_writable(buf)
        return _read_text(buf)

    def __iter__(self) -> Iterator[Tuple[bytes, bytes]]:
        pos = self._start
        data = self.data
        while pos + 4 <= len(data):
            (rec_len,) = struct.unpack_from(">i", data, pos)
            pos += 4
            if rec_len == -1:          # sync escape
                pos += 16
                continue
            (key_len,) = struct.unpack_from(">i", data, pos)
            pos += 4
            key = self._deserialize(data[pos:pos + key_len], self.key_class)
            value = self._deserialize(data[pos + key_len:pos + rec_len],
                                      self.value_class)
            pos += rec_len
            yield key, value


def read_seq_pairs(path: str) -> List[Tuple[bytes, bytes]]:
    return list(SequenceFileReader(path))
