"""Shape types (≙ utils/Shape.scala: SingleShape, MultiShape)."""
from __future__ import annotations

from typing import List, Sequence, Union


class Shape:
    @staticmethod
    def of(*dims):
        if len(dims) == 1 and isinstance(dims[0], (list, tuple)):
            inner = dims[0]
            if inner and isinstance(inner[0], (Shape, list, tuple)):
                return MultiShape([Shape.of(s) if not isinstance(s, Shape)
                                   else s for s in inner])
            return SingleShape(list(inner))
        return SingleShape(list(dims))

    def to_single(self) -> "SingleShape":
        raise NotImplementedError

    def to_multi(self) -> List["Shape"]:
        raise NotImplementedError


class SingleShape(Shape):
    def __init__(self, dims: Sequence[int]):
        self._dims = list(dims)

    def to_single(self):
        return self

    def to_multi(self):
        return [self]

    def to_tuple(self):
        return tuple(self._dims)

    def __getitem__(self, i):
        return self._dims[i]

    def __len__(self):
        return len(self._dims)

    def __eq__(self, other):
        return isinstance(other, SingleShape) and other._dims == self._dims \
            or isinstance(other, (list, tuple)) and list(other) == self._dims

    def __repr__(self):
        return f"SingleShape({self._dims})"


class MultiShape(Shape):
    def __init__(self, shapes: Sequence[Shape]):
        self._shapes = list(shapes)

    def to_single(self):
        raise ValueError("MultiShape holds several shapes")

    def to_multi(self):
        return list(self._shapes)

    def __getitem__(self, i):
        return self._shapes[i]

    def __len__(self):
        return len(self._shapes)

    def __eq__(self, other):
        return isinstance(other, MultiShape) and other._shapes == self._shapes

    def __repr__(self):
        return f"MultiShape({self._shapes})"
