"""Torch7 .t7 serialization (≙ utils/TorchFile.scala).

Binary little-endian format: each value is (type_tag:int32, payload).
Tags: 0 nil, 1 number (f64), 2 string, 3 table, 4 torch object (class name
+ payload), 5 boolean, 6/7 functions (unsupported).  Tables and torch
objects are reference-counted by an index so shared objects round-trip.

Tensors map to numpy: torch.FloatTensor/DoubleTensor/LongTensor/ByteTensor
<-> float32/float64/int64/uint8 arrays (contiguous on write).  Tables with
dense 1..n integer keys load as lists, otherwise dicts.
"""
from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict

import numpy as np

TYPE_NIL = 0
TYPE_NUMBER = 1
TYPE_STRING = 2
TYPE_TABLE = 3
TYPE_TORCH = 4
TYPE_BOOLEAN = 5

_TENSOR_CLASSES = {
    "torch.FloatTensor": np.float32,
    "torch.DoubleTensor": np.float64,
    "torch.LongTensor": np.int64,
    "torch.IntTensor": np.int32,
    "torch.ByteTensor": np.uint8,
}
_STORAGE_CLASSES = {
    "torch.FloatStorage": np.float32,
    "torch.DoubleStorage": np.float64,
    "torch.LongStorage": np.int64,
    "torch.IntStorage": np.int32,
    "torch.ByteStorage": np.uint8,
}
_DTYPE_TO_TENSOR = {np.dtype(np.float32): "torch.FloatTensor",
                    np.dtype(np.float64): "torch.DoubleTensor",
                    np.dtype(np.int64): "torch.LongTensor",
                    np.dtype(np.int32): "torch.IntTensor",
                    np.dtype(np.uint8): "torch.ByteTensor"}
_TENSOR_TO_STORAGE = {"torch.FloatTensor": "torch.FloatStorage",
                      "torch.DoubleTensor": "torch.DoubleStorage",
                      "torch.LongTensor": "torch.LongStorage",
                      "torch.IntTensor": "torch.IntStorage",
                      "torch.ByteTensor": "torch.ByteStorage"}


class _Reader:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.memo: Dict[int, Any] = {}

    def i32(self):
        return struct.unpack("<i", self.f.read(4))[0]

    def i64(self):
        return struct.unpack("<q", self.f.read(8))[0]

    def f64(self):
        return struct.unpack("<d", self.f.read(8))[0]

    def string(self):
        n = self.i32()
        return self.f.read(n).decode("utf-8", "replace")

    def read(self):
        tag = self.i32()
        if tag == TYPE_NIL:
            return None
        if tag == TYPE_NUMBER:
            v = self.f64()
            return int(v) if v == int(v) else v
        if tag == TYPE_STRING:
            return self.string()
        if tag == TYPE_BOOLEAN:
            return self.i32() == 1
        if tag == TYPE_TABLE:
            return self._table()
        if tag == TYPE_TORCH:
            return self._torch()
        raise ValueError(f"unsupported t7 type tag {tag}")

    def _table(self):
        index = self.i32()
        if index in self.memo:
            return self.memo[index]
        out: Dict[Any, Any] = {}
        self.memo[index] = out
        n = self.i32()
        for _ in range(n):
            k = self.read()
            v = self.read()
            out[k] = v
        # dense 1..n integer keys -> list
        if out and all(isinstance(k, int) for k in out) \
                and sorted(out) == list(range(1, len(out) + 1)):
            lst = [out[i] for i in range(1, len(out) + 1)]
            self.memo[index] = lst
            return lst
        return out

    def _torch(self):
        index = self.i32()
        if index in self.memo:
            return self.memo[index]
        version = self.string()  # e.g. "V 1"
        if not version.startswith("V"):
            # older files: the 'version' IS the class name
            cls = version
        else:
            cls = self.string()
        if cls in _TENSOR_CLASSES:
            t = self._tensor(cls)
            self.memo[index] = t
            return t
        if cls in _STORAGE_CLASSES:
            s = self._storage(cls)
            self.memo[index] = s
            return s
        # generic torch object: payload is a table (module fields)
        obj = {"__torch_class__": cls}
        self.memo[index] = obj
        payload = self.read()
        if isinstance(payload, dict):
            obj.update(payload)
        else:
            obj["__payload__"] = payload
        return obj

    def _tensor(self, cls):
        nd = self.i32()
        sizes = [self.i64() for _ in range(nd)]
        strides = [self.i64() for _ in range(nd)]
        offset = self.i64() - 1  # 1-based
        storage = self.read()
        if storage is None:
            return np.zeros(sizes, _TENSOR_CLASSES[cls])
        itemsize = storage.dtype.itemsize
        return np.lib.stride_tricks.as_strided(
            storage[offset:], shape=sizes,
            strides=[s * itemsize for s in strides]).copy()

    def _storage(self, cls):
        n = self.i64()
        dtype = _STORAGE_CLASSES[cls]
        return np.frombuffer(self.f.read(n * np.dtype(dtype).itemsize),
                             dtype=dtype).copy()


class _Writer:
    def __init__(self, f: BinaryIO):
        self.f = f
        self._next_index = 1

    def i32(self, v):
        self.f.write(struct.pack("<i", v))

    def i64(self, v):
        self.f.write(struct.pack("<q", v))

    def f64(self, v):
        self.f.write(struct.pack("<d", v))

    def string(self, s: str):
        b = s.encode("utf-8")
        self.i32(len(b))
        self.f.write(b)

    def _index(self):
        i = self._next_index
        self._next_index += 1
        return i

    def write(self, obj):
        if obj is None:
            self.i32(TYPE_NIL)
        elif isinstance(obj, bool):
            self.i32(TYPE_BOOLEAN)
            self.i32(1 if obj else 0)
        elif isinstance(obj, (int, float)):
            self.i32(TYPE_NUMBER)
            self.f64(float(obj))
        elif isinstance(obj, str):
            self.i32(TYPE_STRING)
            self.string(obj)
        elif isinstance(obj, np.ndarray):
            self._tensor(obj)
        elif isinstance(obj, (list, tuple)):
            self.write({i + 1: v for i, v in enumerate(obj)})
        elif isinstance(obj, dict):
            self.i32(TYPE_TABLE)
            self.i32(self._index())
            self.i32(len(obj))
            for k, v in obj.items():
                self.write(k)
                self.write(v)
        else:
            raise TypeError(f"cannot write {type(obj).__name__} to .t7")

    def _tensor(self, arr: np.ndarray):
        arr = np.ascontiguousarray(arr)
        cls = _DTYPE_TO_TENSOR.get(arr.dtype)
        if cls is None:
            arr = arr.astype(np.float32)
            cls = "torch.FloatTensor"
        self.i32(TYPE_TORCH)
        self.i32(self._index())
        self.string("V 1")
        self.string(cls)
        self.i32(arr.ndim)
        for s in arr.shape:
            self.i64(s)
        stride = 1
        strides = []
        for s in reversed(arr.shape):
            strides.append(stride)
            stride *= s
        for s in reversed(strides):
            self.i64(s)
        self.i64(1)  # storage offset (1-based)
        # storage
        self.i32(TYPE_TORCH)
        self.i32(self._index())
        self.string("V 1")
        self.string(_TENSOR_TO_STORAGE[cls])
        self.i64(arr.size)
        self.f.write(arr.tobytes())


def load(path: str):
    """≙ TorchFile.load."""
    with open(path, "rb") as f:
        return _Reader(f).read()


def save(obj, path: str):
    """≙ TorchFile.save."""
    with open(path, "wb") as f:
        _Writer(f).write(obj)
