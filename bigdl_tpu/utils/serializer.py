"""Module persistence (≙ utils/serializer/ModuleSerializer.scala + utils/File.scala).

The reference persists modules as a versioned protobuf container: per layer a
``BigDLModule`` message holding class name, attributes, and child modules,
assembled by per-class converters (ModuleSerializer.scala SerializeContext /
DataConverter.scala).  The TPU rebuild does the same thing as *data, not
pickle*: a saved model is a zip archive holding

- ``manifest.json``   — format tag + version,
- ``topology.json``   — a flat object table: every distinct Module appears
  once as ``{class, module, name, config, children?, graph?, attrs?}``; config
  values use a small tagged JSON encoding (tuples, dtypes, array refs,
  module refs by table index — preserving shared submodules),
- ``arrays/a*.npy``   — every ndarray (params, state, config constants) as a
  plain .npy entry.

Loading rebuilds each module by calling its constructor with the decoded
config (captured automatically at construction time — see
``nn.module._capture_config``), so no live object graph is ever unpickled:
only classes inside the ``bigdl_tpu`` package (or explicitly registered ones)
are instantiated, and the zip CRC catches truncation/corruption.  The old
round-1 pickle format is still readable (``MAGIC``/version 1).
"""
from __future__ import annotations

import io
import json
import os
import zipfile

import jax
import numpy as np

MAGIC = b"BIGDLTPU"          # legacy round-1 pickle container
VERSION = 2
_FORMAT = "bigdl_tpu.module"

# classes outside bigdl_tpu.* that load_module may instantiate
_CLASS_REGISTRY = {}


def register_class(cls):
    """Allow a user-defined Module/helper class to be (de)serialized."""
    _CLASS_REGISTRY[f"{cls.__module__}:{cls.__qualname__}"] = cls
    return cls


class SerializationError(ValueError):
    pass


def _to_host(tree):
    # convert only array-like leaves; str/int/float/bool pass through (a
    # blanket np.asarray would turn strings into U-dtype arrays)
    return jax.tree_util.tree_map(
        lambda v: np.asarray(v) if _is_array(v) else v, tree)


def _to_device(tree):
    import jax.numpy as jnp
    return jax.tree_util.tree_map(jnp.asarray, tree)


def _is_array(v):
    return isinstance(v, (np.ndarray, np.generic)) or (
        hasattr(v, "__array__") and hasattr(v, "dtype") and hasattr(v, "shape")
        and not np.isscalar(v))


def _is_dtype(v):
    if isinstance(v, np.dtype):
        return True
    try:
        return isinstance(v, type) and issubclass(v, np.generic)
    except TypeError:
        return False


# --------------------------------------------------------------------- #
# encoding                                                              #
# --------------------------------------------------------------------- #
class _Encoder:
    def __init__(self):
        self.nodes = []            # module table entries (JSON dicts)
        self.index = {}            # id(module) -> table index
        self.arrays = {}           # "arrays/aN.npy" -> np.ndarray

    def array_ref(self, v, where=""):
        arr = np.asarray(v)
        if arr.dtype.kind not in "biufc":   # matches what jnp can restore
            raise SerializationError(
                f"{where}: array dtype {arr.dtype} is not serializable "
                "(numeric/bool arrays only)")
        key = f"arrays/a{len(self.arrays)}.npy"
        self.arrays[key] = arr
        return {"$a": key}

    def value(self, v, where=""):
        from ..nn.module import Module, Criterion
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        if isinstance(v, (bytes, bytearray, set, frozenset, complex)):
            raise SerializationError(
                f"{where}: {type(v).__name__} values are not serializable")
        if isinstance(v, Module):
            return {"$m": self.module(v)}
        if _is_dtype(v):
            return {"$dtype": np.dtype(v).name}
        if _is_array(v):
            return self.array_ref(v, where)
        if isinstance(v, tuple):
            return {"$t": [self.value(e, where) for e in v]}
        if isinstance(v, list):
            return [self.value(e, where) for e in v]
        if isinstance(v, dict):
            bad = [k for k in v if not isinstance(k, str)]
            if bad:
                raise SerializationError(
                    f"{where}: dict key {bad[0]!r} is not a string")
            return {"$dict": {k: self.value(e, where) for k, e in v.items()}}
        if inspect_isfunction(v):
            raise SerializationError(
                f"{where}: cannot serialize function {v!r}; use a registered "
                "class with a no-arg or captured-config constructor instead")
        # helper object (Criterion, Regularizer, init method, LR schedule…):
        # persist as class + captured ctor config, or attribute dict
        return {"$obj": self.object(v, where)}

    def object(self, v, where):
        cls = type(v)
        key = f"{cls.__module__}:{cls.__qualname__}"
        # mirror _Decoder.resolve_class at ENCODE time: a file that cannot
        # be loaded back must not be writable in the first place
        if not (key in _CLASS_REGISTRY
                or cls.__module__ == "bigdl_tpu"
                or cls.__module__.startswith("bigdl_tpu.")):
            raise SerializationError(
                f"{where}: cannot serialize {key!r}; only bigdl_tpu classes "
                "and serializer.register_class'd classes are loadable")
        entry = {"module": cls.__module__, "class": cls.__qualname__}
        serde = getattr(v, "_serde", None)
        if serde is not None and serde.get("config") is not None:
            cfg = dict(serde["config"])
            if "name" in cfg and getattr(v, "name", None) is not None:
                cfg["name"] = v.name
            entry["config"] = {k: self.value(x, f"{where}.{k}")
                               for k, x in cfg.items()}
            if serde.get("varargs"):
                entry["varargs"] = serde["varargs"]
        else:
            try:
                attrs = vars(v)
            except TypeError:
                raise SerializationError(
                    f"{where}: {type(v).__name__} has no inspectable state")
            state = {k: x for k, x in attrs.items()
                     if k not in ("output", "grad_input", "_serde")
                     and not callable(x)}
            entry["state"] = {k: self.value(x, f"{where}.{k}")
                              for k, x in state.items()}
        return entry

    def module(self, m):
        from ..nn.module import Module
        from ..nn.graph import Graph
        if id(m) in self.index:
            return self.index[id(m)]
        idx = len(self.nodes)
        self.index[id(m)] = idx
        entry = {}
        self.nodes.append(entry)   # reserve slot (cycles via children refs)
        cls = type(m)
        entry["module"] = cls.__module__
        entry["class"] = cls.__qualname__
        entry["name"] = m.name

        custom_build = (cls._serde_build.__func__
                        is not Module._serde_build.__func__)
        cfg = m._serde_config()
        if cfg is None and not (isinstance(m, Graph) or custom_build):
            # layers with kwargs-only or unbindable ctors: last resort refusal
            # (better a loud save-time error than a silent bad load)
            raise SerializationError(
                f"{m.name} ({cls.__qualname__}): constructor args were not "
                "captured; give the class an inspectable __init__ or a "
                "_serde_build classmethod")
        if isinstance(m, Graph):
            entry["graph"] = self.graph(m)
        else:
            if cfg is not None:
                if "name" in cfg:
                    cfg["name"] = m.name
                entry["config"] = {k: self.value(v, f"{m.name}.{k}")
                                   for k, v in cfg.items()}
                serde = getattr(m, "_serde", None)
                if serde and serde.get("varargs"):
                    entry["varargs"] = serde["varargs"]
            # persist children only when the class re-attaches them on load
            # (default restore is a no-op: ctor replay rebuilds its children)
            restores = (cls._serde_restore_children
                        is not Module._serde_restore_children)
            if restores or custom_build:
                kids = m._serde_children()
                if any(c is not None for c in kids):
                    entry["children"] = [None if c is None else self.module(c)
                                         for c in kids]
            extra = {}
            for k in cls._serde_extra_attrs:
                extra[k] = self.value(getattr(m, k, None), f"{m.name}.{k}")
            if extra:
                entry["extra"] = extra
        attrs = {}
        for k in ("weight_init", "bias_init", "w_regularizer",
                  "b_regularizer"):
            if getattr(m, k, None) is not None:
                attrs[k] = self.value(getattr(m, k), f"{m.name}.{k}")
        for k in ("scale_w", "scale_b"):
            if getattr(m, k, 1.0) != 1.0:
                attrs[k] = getattr(m, k)
        if attrs:
            entry["attrs"] = attrs
        return idx

    def graph(self, g):
        """Node DAG of a Graph container: modules by table ref + edges."""
        gnodes = list(g._topo)
        gidx = {id(n): i for i, n in enumerate(gnodes)}
        return {
            "nodes": [{"m": None if n.module is None else self.module(n.module),
                       "prev": [gidx[id(p)] for p in n.prev_nodes]}
                      for n in gnodes],
            "inputs": [gidx[id(n)] for n in g.input_nodes],
            "outputs": [gidx[id(n)] for n in g.output_nodes],
        }


def inspect_isfunction(v):
    import types
    return isinstance(v, (types.FunctionType, types.LambdaType,
                          types.BuiltinFunctionType, types.MethodType))


# --------------------------------------------------------------------- #
# decoding                                                              #
# --------------------------------------------------------------------- #
class _Decoder:
    def __init__(self, topo, read_array):
        self.nodes = topo["nodes"]
        self.read_array = read_array
        self.built = {}

    def resolve_class(self, modname, qualname):
        key = f"{modname}:{qualname}"
        if key in _CLASS_REGISTRY:
            return _CLASS_REGISTRY[key]
        if not (modname.startswith("bigdl_tpu.") or modname == "bigdl_tpu"):
            raise SerializationError(
                f"refusing to import {key!r}: only bigdl_tpu classes and "
                "serializer.register_class'd classes are loadable")
        import importlib
        mod = importlib.import_module(modname)
        obj = mod
        for part in qualname.split("."):
            obj = getattr(obj, part)
        return obj

    def value(self, v):
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        if isinstance(v, list):
            return [self.value(e) for e in v]
        if isinstance(v, dict):
            if "$m" in v:
                return self.module(v["$m"])
            if "$a" in v:
                return self.read_array(v["$a"])
            if "$t" in v:
                return tuple(self.value(e) for e in v["$t"])
            if "$dtype" in v:
                try:
                    return np.dtype(v["$dtype"]).type
                except TypeError as e:
                    raise SerializationError(
                        f"bad $dtype tag {v['$dtype']!r}") from e
            if "$dict" in v:
                return {k: self.value(e) for k, e in v["$dict"].items()}
            if "$obj" in v:
                return self.object(v["$obj"])
        raise SerializationError(f"undecodable value {v!r}")

    @staticmethod
    def _user_code(fn, *a, **kw):
        """Run reconstructed-class code (ctor/setattr); mark its errors so
        loaders re-raise them untouched instead of as file corruption."""
        try:
            return fn(*a, **kw)
        except Exception as e:
            try:
                e._bigdl_user_error = True
            except Exception:
                pass
            raise

    def construct(self, cls, entry):
        cfg = {k: self.value(v) for k, v in entry.get("config", {}).items()}
        varargs = entry.get("varargs")
        if varargs and varargs in cfg:
            import inspect
            pos, va = [], cfg.pop(varargs)
            for p in inspect.signature(cls.__init__).parameters.values():
                if p.name == "self":
                    continue
                if p.kind is p.VAR_POSITIONAL:
                    break
                if p.name in cfg:
                    pos.append(cfg.pop(p.name))
            return self._user_code(cls, *pos, *va, **cfg)
        return self._user_code(cls, **cfg)

    def object(self, entry):
        cls = self.resolve_class(entry["module"], entry["class"])
        if "config" in entry:
            return self.construct(cls, entry)
        obj = cls.__new__(cls)
        for k, v in entry.get("state", {}).items():
            decoded = self.value(v)
            self._user_code(setattr, obj, k, decoded)
        return obj

    def module(self, idx):
        from ..nn.module import Module
        if idx in self.built:
            return self.built[idx]
        if not isinstance(idx, int) or not 0 <= idx < len(self.nodes):
            raise SerializationError(f"dangling module reference {idx!r} "
                                     f"(file has {len(self.nodes)} nodes)")
        entry = self.nodes[idx]
        cls = self.resolve_class(entry["module"], entry["class"])
        custom_build = (cls._serde_build.__func__
                        is not Module._serde_build.__func__) \
            if hasattr(cls, "_serde_build") else False
        if "graph" in entry:
            m = self.graph(cls, entry["graph"])
        elif custom_build:
            children = self._children_of(entry)
            cfg = {k: self.value(v)
                   for k, v in entry.get("config", {}).items()}
            m = cls._serde_build(cfg, children)
            if m is None:           # documented fallback: ctor replay
                m = self.construct(cls, entry)
        else:
            m = self.construct(cls, entry)
        if m.name != entry["name"]:
            m.set_name(entry["name"])
        self.built[idx] = m
        if not custom_build and "children" in entry:
            m._serde_restore_children(self._children_of(entry))
        for k, v in entry.get("extra", {}).items():
            setattr(m, k, self.value(v))
        for k, v in entry.get("attrs", {}).items():
            setattr(m, k, self.value(v) if isinstance(v, (dict, list)) else v)
        return m

    def _children_of(self, entry):
        return [None if i is None else self.module(i)
                for i in entry.get("children", [])]

    def graph(self, cls, g):
        from ..nn.graph import Node
        nodes = []
        for spec in g["nodes"]:
            mod = None if spec["m"] is None else self.module(spec["m"])
            nodes.append(Node(mod, [nodes[i] for i in spec["prev"]]))
        return cls([nodes[i] for i in g["inputs"]],
                   [nodes[i] for i in g["outputs"]])


# --------------------------------------------------------------------- #
# public API                                                            #
# --------------------------------------------------------------------- #
def save_module(module, path, overwrite=True):
    import os
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(path)
    enc = _Encoder()
    root = enc.module(module)
    topo = {
        "root": root,
        "nodes": enc.nodes,
        "params": None if module._params is None
        else enc.value(_to_host(module._params), "params"),
        "state": enc.value(_to_host(module._state or {}), "state"),
    }
    _write_payload_zip(path, _FORMAT, "topology.json", topo, enc.arrays)


def load_module(path):
    with open(path, "rb") as f:
        head = f.read(len(MAGIC))
    if head == MAGIC:
        return _load_module_v1(path)
    try:
        with zipfile.ZipFile(path) as z:
            # header parsing: malformed JSON / missing entries => bad file
            try:
                manifest = json.loads(z.read("manifest.json"))
                if manifest.get("format") != _FORMAT:
                    raise SerializationError(
                        f"{path}: not a bigdl_tpu module file")
                if manifest.get("version", 0) > VERSION:
                    raise SerializationError(
                        f"{path}: unsupported version {manifest['version']}")
                topo = json.loads(z.read("topology.json"))
                root = topo["root"]
            except (json.JSONDecodeError, KeyError) as e:
                raise SerializationError(
                    f"{path}: malformed module file "
                    f"({type(e).__name__}: {e})") from e

            def read_array(key):
                import jax.numpy as jnp
                buf = io.BytesIO(z.read(key))   # zip CRC checked here
                return jnp.asarray(np.load(buf, allow_pickle=False))

            # reconstruction: constructor errors propagate untouched so a
            # user's module bug isn't misreported as file corruption
            dec = _Decoder(topo, read_array)
            module = dec.module(root)
            if topo.get("params") is not None:
                module._params = dec.value(topo["params"])
            module._state = dec.value(topo.get("state", {}))
            return module
    except zipfile.BadZipFile as e:
        raise SerializationError(
            f"{path}: corrupt or truncated module file ({e})") from e


def _payload_zip_bytes(fmt, payload_name, payload, arrays) -> bytes:
    """The zip container as bytes (the checkpoint writer streams these
    through its CRC + fault-injection path)."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("manifest.json",
                   json.dumps({"format": fmt, "version": VERSION}))
        z.writestr(payload_name, json.dumps(payload))
        for key, arr in arrays.items():
            abuf = io.BytesIO()
            np.save(abuf, arr, allow_pickle=False)
            z.writestr(key, abuf.getvalue())
    return buf.getvalue()


def _write_payload_zip(path, fmt, payload_name, payload, arrays):
    # tmp + fsync + os.replace: a crash mid-write must never corrupt a
    # pre-existing file being overwritten, and a crash mid-RENAME must
    # never surface a short file as committed (same contract as
    # utils/file.save)
    data = _payload_zip_bytes(fmt, payload_name, payload, arrays)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _read_payload_zip(path, fmt, payload_name, desc, build):
    """Manifest-checked zip read shared by weights/state loaders.

    ``build(payload, read_array)`` runs inside the open-zip context so
    arrays stream on demand (no checkpoint-sized blob dict).  Structural
    corruption (bad zip/json/manifest, dangling refs, broken arrays)
    surfaces as SerializationError; errors raised by reconstructed user
    classes propagate untouched, mirroring load_module's contract.
    """
    if not zipfile.is_zipfile(path):
        raise SerializationError(f"{path}: not a bigdl_tpu {desc} file")
    try:
        z = zipfile.ZipFile(path)
    except zipfile.BadZipFile as e:
        raise SerializationError(
            f"{path}: corrupt or truncated {desc} file ({e})") from e
    with z:
        try:
            manifest = json.loads(z.read("manifest.json"))
            if manifest.get("format") != fmt:
                raise SerializationError(
                    f"{path}: manifest says {manifest.get('format')!r}, "
                    f"expected a {desc} file")
            if manifest.get("version", 0) > VERSION:
                raise SerializationError(
                    f"{path}: unsupported version {manifest['version']}")
            payload = json.loads(z.read(payload_name))
        except (zipfile.BadZipFile, json.JSONDecodeError, KeyError) as e:
            raise SerializationError(
                f"{path}: corrupt or truncated {desc} file ({e})") from e

        def read_array(key):
            import jax.numpy as jnp
            try:  # zip CRC + npy header are both checked here
                return jnp.asarray(np.load(io.BytesIO(z.read(key)),
                                           allow_pickle=False))
            except Exception as e:
                raise SerializationError(
                    f"{path}: broken array {key!r} ({e})") from e

        try:
            return build(payload, read_array)
        except SerializationError:
            raise
        except Exception as e:
            # structural decode failures become SerializationError with
            # the file path; exceptions raised by reconstructed user
            # classes (marked at the raise site) propagate untouched
            if getattr(e, "_bigdl_user_error", False):
                raise
            raise SerializationError(
                f"{path}: corrupt {desc} payload "
                f"({type(e).__name__}: {e})") from e


def save_weights_file(module, path):
    """Params+state only (no topology), same tagged-JSON + .npy zip format."""
    enc = _Encoder()
    payload = {
        "params": None if module._params is None
        else enc.value(_to_host(module._params), "params"),
        "state": enc.value(_to_host(module._state or {}), "state"),
    }
    _write_payload_zip(path, _FORMAT + ".weights", "weights.json", payload,
                       enc.arrays)


def save_state_file(tree, path):
    """Arbitrary training-state pytree (dicts/tuples/lists/arrays/scalars
    plus registered helper objects) as a tagged-JSON + .npy zip — the
    no-pickle counterpart of the reference's OptimMethod/state snapshots
    (optim/OptimMethod.scala save).  Raises SerializationError for values
    the format cannot hold (so callers can fall back) BEFORE any bytes are
    written."""
    enc = _Encoder()
    payload = enc.value(_to_host(tree), "state")
    if enc.nodes:
        raise SerializationError(
            "state tree contains Module instances; save them with "
            "save_module / Module.save instead")
    _write_payload_zip(path, _FORMAT + ".state", "state.json", payload,
                       enc.arrays)


def state_file_bytes(tree) -> bytes:
    """save_state_file's container as in-memory bytes — the checkpoint
    subsystem serializes shards on its writer thread and pushes the
    bytes through CRC32C + fault injection before they reach disk."""
    enc = _Encoder()
    payload = enc.value(_to_host(tree), "state")
    if enc.nodes:
        raise SerializationError(
            "state tree contains Module instances; save them with "
            "save_module / Module.save instead")
    return _payload_zip_bytes(_FORMAT + ".state", "state.json", payload,
                              enc.arrays)


def load_state_file(path):
    """Inverse of save_state_file; raises SerializationError on corrupt,
    truncated, or non-state files instead of unpickling anything."""
    return _read_payload_zip(
        path, _FORMAT + ".state", "state.json", "state",
        lambda payload, ra: _Decoder({"nodes": []}, ra).value(payload))


def load_weights_file(path):
    """Return (params, state) written by save_weights_file (or the legacy
    pickle pair written by round-1 Module.save_weights — recognized by the
    pickle protocol-2+ marker only; anything else is rejected rather than
    blindly unpickled)."""
    if not zipfile.is_zipfile(path):
        with open(path, "rb") as f:
            head = f.read(2)
        if len(head) == 2 and head[0] == 0x80 and 2 <= head[1] <= 5:
            import pickle
            with open(path, "rb") as f:
                try:
                    return pickle.load(f)     # legacy round-1 format
                except Exception as e:
                    raise SerializationError(
                        f"{path}: broken legacy weights pickle ({e})") from e
        raise SerializationError(
            f"{path}: not a bigdl_tpu weights file (neither v2 zip nor "
            "legacy pickle)")
    def build(payload, read_array):
        if "params" not in payload or "state" not in payload:
            raise SerializationError(
                f"{path}: weights payload is missing params/state")
        dec = _Decoder({"nodes": []}, read_array)
        return dec.value(payload["params"]), dec.value(payload["state"])
    return _read_payload_zip(path, _FORMAT + ".weights", "weights.json",
                             "weights", build)


def _load_module_v1(path):
    """Legacy round-1 container: versioned header + pickle payload.

    Kept one release for migration.  Only load files you wrote yourself —
    pickle executes arbitrary code by design, which is exactly why v2
    replaced it.
    """
    import pickle
    with open(path, "rb") as f:
        f.read(len(MAGIC))
        version = int.from_bytes(f.read(2), "little")
        if version != 1:
            raise SerializationError(f"{path}: unsupported legacy version")
        blob = pickle.load(f)
    module = blob["module"]
    if blob["params"] is not None:
        module._params = _to_device(blob["params"])
    module._state = _to_device(blob["state"])
    return module


# --------------------------------------------------------------------- #
# orbax-compatible checkpoints (≙ the reference's HDFS checkpoint dir   #
# interop story: checkpoints readable by the ecosystem's standard tool) #
# --------------------------------------------------------------------- #
def save_pytree(tree, path, to_host=True):
    """Write a pytree checkpoint readable by any orbax StandardCheckpointer.

    ``to_host=False`` hands jax Arrays to orbax directly — sharded (fsdp)
    state is then written shard-by-shard without ever materialising an
    unsharded host copy."""
    import os
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), _to_host(tree) if to_host else tree,
               force=True)
    ckptr.wait_until_finished()


def load_pytree(path, template=None):
    import os
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    if template is not None:
        return ckptr.restore(os.path.abspath(path), target=_to_host(template))
    return ckptr.restore(os.path.abspath(path))


def save_module_orbax(module, path):
    """Params+state as an orbax checkpoint; topology goes alongside as
    JSON (≙ serializer's protobuf topology + weights split)."""
    import os
    module.ensure_initialized()
    save_pytree({"params": module._params, "state": module._state or {}},
                os.path.join(path, "weights"))
    with open(os.path.join(path, "topology.json"), "w") as f:
        json.dump(topology_dict(module), f, indent=1)


def load_module_orbax(module, path):
    """Restore weights saved by save_module_orbax into a compatible module
    instance (topology must match; names are validated)."""
    import os
    with open(os.path.join(path, "topology.json")) as f:
        topo = json.load(f)
    mine = topology_dict(module)
    if topo["class"] != mine["class"]:
        raise ValueError(f"topology mismatch: checkpoint is {topo['class']},"
                         f" module is {mine['class']}")
    module.ensure_initialized()
    restored = load_pytree(os.path.join(path, "weights"),
                           template={"params": module._params,
                                     "state": module._state or {}})
    module.set_params(_to_device(restored["params"]),
                      _to_device(restored["state"]))
    return module


def topology_dict(module, params=None):
    """JSON-able structural summary (class, name, children, param shapes).
    Containers hold the flat params tree for the whole model, so it is
    threaded down and sliced by child name."""
    if params is None:
        params = module._params
    entry = {"class": type(module).__name__, "name": module.name}
    if params and module.name in params:
        entry["params"] = {k: list(np.shape(v))
                           for k, v in params[module.name].items()}
    children = module.children() if hasattr(module, "children") else []
    if children:
        entry["children"] = [topology_dict(c, params) for c in children]
    return entry
