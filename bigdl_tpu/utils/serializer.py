"""Module persistence (≙ utils/serializer/ModuleSerializer.scala + utils/File.scala).

The reference serializes module topology + weights to a protobuf container.
Here the topology is plain Python (module classes are importable), so
save_module pickles the module object with all device arrays converted to
host numpy; load_module restores and re-uploads lazily on first use.
A versioned header guards format drift.
"""
from __future__ import annotations

import pickle

import jax
import numpy as np

MAGIC = b"BIGDLTPU"
VERSION = 1


def _to_host(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


def _to_device(tree):
    import jax.numpy as jnp
    return jax.tree_util.tree_map(jnp.asarray, tree)


def save_module(module, path, overwrite=True):
    import os
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(path)
    params = module._params
    state = module._state
    # detach device arrays before pickling the object graph
    module._params, module._state = None, {}
    try:
        blob = {
            "module": module,
            "params": None if params is None else _to_host(params),
            "state": _to_host(state or {}),
        }
        with open(path, "wb") as f:
            f.write(MAGIC)
            f.write(VERSION.to_bytes(2, "little"))
            pickle.dump(blob, f, protocol=pickle.HIGHEST_PROTOCOL)
    finally:
        module._params, module._state = params, state


def load_module(path):
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise ValueError(f"{path}: not a bigdl_tpu module file")
        version = int.from_bytes(f.read(2), "little")
        if version > VERSION:
            raise ValueError(f"{path}: unsupported version {version}")
        blob = pickle.load(f)
    module = blob["module"]
    if blob["params"] is not None:
        module._params = _to_device(blob["params"])
    module._state = _to_device(blob["state"])
    return module


# --------------------------------------------------------------------- #
# orbax-compatible checkpoints (≙ the reference's HDFS checkpoint dir   #
# interop story: checkpoints readable by the ecosystem's standard tool) #
# --------------------------------------------------------------------- #
def save_pytree(tree, path):
    """Write a pytree checkpoint readable by any orbax StandardCheckpointer."""
    import os
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), _to_host(tree), force=True)
    ckptr.wait_until_finished()


def load_pytree(path, template=None):
    import os
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    if template is not None:
        return ckptr.restore(os.path.abspath(path), target=_to_host(template))
    return ckptr.restore(os.path.abspath(path))


def save_module_orbax(module, path):
    """Params+state as an orbax checkpoint; topology goes alongside as
    JSON (≙ serializer's protobuf topology + weights split)."""
    import json
    import os
    module.ensure_initialized()
    save_pytree({"params": module._params, "state": module._state or {}},
                os.path.join(path, "weights"))
    with open(os.path.join(path, "topology.json"), "w") as f:
        json.dump(topology_dict(module), f, indent=1)


def load_module_orbax(module, path):
    """Restore weights saved by save_module_orbax into a compatible module
    instance (topology must match; names are validated)."""
    import json
    import os
    with open(os.path.join(path, "topology.json")) as f:
        topo = json.load(f)
    mine = topology_dict(module)
    if topo["class"] != mine["class"]:
        raise ValueError(f"topology mismatch: checkpoint is {topo['class']},"
                         f" module is {mine['class']}")
    module.ensure_initialized()
    restored = load_pytree(os.path.join(path, "weights"),
                           template={"params": module._params,
                                     "state": module._state or {}})
    module.set_params(_to_device(restored["params"]),
                      _to_device(restored["state"]))
    return module


def topology_dict(module, params=None):
    """JSON-able structural summary (class, name, children, param shapes).
    Containers hold the flat params tree for the whole model, so it is
    threaded down and sliced by child name."""
    if params is None:
        params = module._params
    entry = {"class": type(module).__name__, "name": module.name}
    if params and module.name in params:
        entry["params"] = {k: list(np.shape(v))
                           for k, v in params[module.name].items()}
    children = module.children() if hasattr(module, "children") else []
    if children:
        entry["children"] = [topology_dict(c, params) for c in children]
    return entry
