"""Module persistence (≙ utils/serializer/ModuleSerializer.scala + utils/File.scala).

The reference serializes module topology + weights to a protobuf container.
Here the topology is plain Python (module classes are importable), so
save_module pickles the module object with all device arrays converted to
host numpy; load_module restores and re-uploads lazily on first use.
A versioned header guards format drift.
"""
from __future__ import annotations

import pickle

import jax
import numpy as np

MAGIC = b"BIGDLTPU"
VERSION = 1


def _to_host(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


def _to_device(tree):
    import jax.numpy as jnp
    return jax.tree_util.tree_map(jnp.asarray, tree)


def save_module(module, path, overwrite=True):
    import os
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(path)
    params = module._params
    state = module._state
    # detach device arrays before pickling the object graph
    module._params, module._state = None, {}
    try:
        blob = {
            "module": module,
            "params": None if params is None else _to_host(params),
            "state": _to_host(state or {}),
        }
        with open(path, "wb") as f:
            f.write(MAGIC)
            f.write(VERSION.to_bytes(2, "little"))
            pickle.dump(blob, f, protocol=pickle.HIGHEST_PROTOCOL)
    finally:
        module._params, module._state = params, state


def load_module(path):
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise ValueError(f"{path}: not a bigdl_tpu module file")
        version = int.from_bytes(f.read(2), "little")
        if version > VERSION:
            raise ValueError(f"{path}: unsupported version {version}")
        blob = pickle.load(f)
    module = blob["module"]
    if blob["params"] is not None:
        module._params = _to_device(blob["params"])
    module._state = _to_device(blob["state"])
    return module
