"""Random generator (≙ utils/RandomGenerator.scala RNG).

The reference keeps a global mersenne-twister RNG with distribution
helpers; host-side code (data augmentation, init fallbacks) uses this.
Device-side randomness stays with jax.random keys — this is the HOST rng.
"""
from __future__ import annotations

import threading

import numpy as np


class RandomGenerator:
    def __init__(self, seed: int = 1):
        self._rng = np.random.RandomState(seed)
        self._seed = seed

    def set_seed(self, seed: int):
        self._seed = seed
        self._rng = np.random.RandomState(seed)
        return self

    def get_seed(self) -> int:
        return self._seed

    def uniform(self, a: float = 0.0, b: float = 1.0, size=None):
        return self._rng.uniform(a, b, size)

    def normal(self, mean: float = 0.0, stdv: float = 1.0, size=None):
        return self._rng.normal(mean, stdv, size)

    def exponential(self, lam: float = 1.0, size=None):
        return self._rng.exponential(1.0 / lam, size)

    def cauchy(self, median: float = 0.0, sigma: float = 1.0, size=None):
        return median + sigma * np.tan(
            np.pi * (self._rng.uniform(size=size) - 0.5))

    def log_normal(self, mean: float = 1.0, stdv: float = 2.0, size=None):
        return self._rng.lognormal(mean, stdv, size)

    def geometric(self, p: float = 0.5, size=None):
        return self._rng.geometric(p, size)

    def bernoulli(self, p: float = 0.5, size=None):
        return (self._rng.uniform(size=size) < p).astype(np.float64)

    def random(self, size=None):
        return self._rng.randint(0, 2 ** 31 - 1, size)

    def permutation(self, n: int):
        return self._rng.permutation(n)

    def shuffle(self, arr):
        self._rng.shuffle(arr)
        return arr


_local = threading.local()


def RNG() -> RandomGenerator:
    """Thread-local global generator (≙ RandomGenerator.RNG)."""
    if not hasattr(_local, "rng"):
        _local.rng = RandomGenerator()
    return _local.rng
