"""Reference-format `.bigdl` protobuf model reader/writer.

The reference persists models as a `BigDLModule` protobuf
(serialization/bigdl.proto; written/read by utils/serializer/
ModuleSerializer.scala:1, ModuleLoader.scala:48 loadFromFile,
ModulePersister).  Layout facts this module encodes against:

  * the file is the raw BigDLModule message (no magic/header);
  * tensor DATA lives once in the top-level attr map under
    "global_storage" (SerConst.GLOBAL_STORAGE) as a NameAttrList mapping
    tensorId -> AttrValue(tensorValue) whose TensorStorage carries the
    inline float data; parameter tensors elsewhere reference the same
    storage by id (ModuleLoader.scala:119 initTensorStorage);
  * each module's constructor args are attrs keyed by the Scala
    parameter name (ModuleSerializable.scala:214 doSerializeModule
    reflection), e.g. Linear(inputSize, outputSize, withBias);
  * weights ride `parameters` ([weight, bias] order) with
    hasParameters=true (ModuleSerializable.scala:364 copyFromBigDL);
    pre-0.5.0 files use the deprecated weight/bias fields instead
    (ModuleSerializable.scala:336 copyWeightAndBias) — both are read;
  * containers recurse through subModules
    (ModuleSerializable.scala:381 ContainerSerializable).

BatchNorm running stats ride the module's attr map as tensor attrs
``runningMean``/``runningVar`` (+ per-batch ``saveMean``/``saveStd``
temporaries) — nn/BatchNormalization.scala:323 doLoadModule reads all
four unconditionally, :346 doSerializeModule writes them.  Both
directions are handled here: load copies them into the model's BN
state; save emits them (saveMean/saveStd zeroed, as after resize).
"""
from __future__ import annotations

import os
from typing import Dict, List

import numpy as np

from . import proto
from .proto import iter_fields, enc_bytes, enc_string, enc_int64
from .. import nn

_NS = "com.intel.analytics.bigdl.nn."

# DataType enum (bigdl.proto)
_DT_FLOAT, _DT_DOUBLE, _DT_INT32, _DT_INT64, _DT_BOOL = 2, 3, 0, 1, 5
_DT_STRING = 4
_DT_TENSOR, _DT_ARRAY = 10, 15
_DT_NAME_ATTR_LIST = 14
_DT_MODULE = 13   # bigdl.proto DataType.MODULE (12 is INITMETHOD)


# --------------------------------------------------------------------- #
# wire decoding                                                          #
# --------------------------------------------------------------------- #
def _packed_varints(v, wire):
    if wire == 0:
        return [v]
    out, i = [], 0
    while i < len(v):
        n, i = proto._read_varint(v, i)
        out.append(n)
    return out


def _sint(v):
    return v if v < 1 << 62 else v - (1 << 64)


def _decode_storage(buf):
    """TensorStorage -> (np.ndarray | None, storage_id)."""
    dtype = np.float32
    data = None
    sid = 0
    for f, w, v in iter_fields(buf):
        if f == 1 and w == 0:
            dtype = {_DT_FLOAT: np.float32, _DT_DOUBLE: np.float64,
                     _DT_INT32: np.int32, _DT_INT64: np.int64,
                     _DT_BOOL: np.bool_}.get(v, np.float32)
        elif f == 2:  # float_data (packed fixed32 under proto3)
            if w == 2:
                data = np.frombuffer(v, "<f4").astype(np.float32)
            else:   # unpacked single float (iter_fields decodes fixed32)
                data = np.concatenate(
                    [data if data is not None else np.zeros(0, np.float32),
                     [v]]).astype(np.float32)
        elif f == 3 and w == 2:  # double_data
            data = np.frombuffer(v, "<f8")
        elif f == 6:  # int_data packed varints
            data = np.asarray(_packed_varints(v, w), np.int32)
        elif f == 7:  # long_data
            data = np.asarray([_sint(x) for x in _packed_varints(v, w)],
                              np.int64)
        elif f == 9 and w == 0:
            sid = v
    if data is not None:
        data = data.astype(dtype, copy=False)
    return data, sid


def _decode_tensor(buf, storages: Dict[int, np.ndarray]):
    """BigDLTensor -> np.ndarray (resolving shared storage by id)."""
    sizes: List[int] = []
    offset = 0
    tid = None
    data = None
    sid = None
    is_scalar = False
    for f, w, v in iter_fields(buf):
        if f == 2:
            sizes.extend(_packed_varints(v, w))
        elif f == 4 and w == 0:
            offset = v
        elif f == 7 and w == 0:
            is_scalar = bool(v)
        elif f == 8 and w == 2:
            data, sid = _decode_storage(v)
        elif f == 9 and w == 0:
            tid = v
    if data is None and sid is not None:
        data = storages.get(sid)
    if data is None:
        return None
    if sid is not None and sid not in storages:
        storages[sid] = data
    start = max(offset - 1, 0)   # reference storageOffset is 1-based
    n = int(np.prod(sizes)) if sizes else 1
    flat = np.asarray(data).reshape(-1)[start:start + n]
    if is_scalar or not sizes:
        return flat.reshape(())
    return flat.reshape(sizes)


def _decode_attr(buf, storages):
    """AttrValue -> python value (subset used by module files)."""
    dtype = None
    raw = {}
    for f, w, v in iter_fields(buf):
        raw.setdefault(f, []).append((w, v))
        if f == 1 and w == 0:
            dtype = v
    def first(f):
        return raw[f][0][1] if f in raw else None
    if 3 in raw:
        return _sint(first(3))
    if 4 in raw:
        return _sint(first(4))
    if 5 in raw:
        return float(first(5))    # iter_fields already decodes fixed32
    if 6 in raw:
        return float(first(6))    # ... and fixed64
    if 7 in raw:
        return first(7).decode("utf-8")
    if 8 in raw:
        return bool(first(8))
    if 10 in raw:
        return _decode_tensor(first(10), storages)
    if 13 in raw:  # nested BigDLModule (bigDLModuleValue)
        return _decode_module(first(13), storages)
    if 14 in raw:  # NameAttrList
        return _decode_name_attr_list(first(14), storages)
    if 15 in raw:  # ArrayValue
        return _decode_array(first(15), storages)
    if 16 in raw:  # DataFormat enum
        return "NHWC" if first(16) == 1 else "NCHW"
    if dtype is not None and dtype not in (_DT_TENSOR,):
        return None
    return None


def _decode_array(buf, storages):
    out = []
    for f, w, v in iter_fields(buf):
        if f == 3:
            out.extend(_sint(x) for x in _packed_varints(v, w))
        elif f == 4:
            out.extend(_sint(x) for x in _packed_varints(v, w))
        elif f == 5 and w == 2:
            out.extend(np.frombuffer(v, "<f4").tolist())
        elif f == 6 and w == 2:
            out.extend(np.frombuffer(v, "<f8").tolist())
        elif f == 7 and w == 2:
            out.append(v.decode("utf-8"))
        elif f == 8:
            out.extend(bool(x) for x in _packed_varints(v, w))
        elif f == 10 and w == 2:
            out.append(_decode_tensor(v, storages))
        elif f == 13 and w == 2:   # Array(BigDLModule)
            out.append(_decode_module(v, storages))
    return out


def _decode_name_attr_list(buf, storages):
    name = ""
    attrs = {}
    for f, w, v in iter_fields(buf):
        if f == 1 and w == 2:
            name = v.decode("utf-8")
        elif f == 2 and w == 2:
            k = val = None
            for f2, w2, v2 in iter_fields(v):
                if f2 == 1 and w2 == 2:
                    k = v2.decode("utf-8")
                elif f2 == 2 and w2 == 2:
                    val = _decode_attr(v2, storages)
            if k is not None:
                attrs[k] = val
    return {"name": name, "attr": attrs}


def _decode_module(buf, storages):
    m = {"name": "", "type": "", "subs": [], "attr": {}, "params": [],
         "pres": [], "weight": None, "bias": None, "has_params": False}
    # two passes: global_storage (attr map) must be registered before
    # parameter tensors that reference it — attrs can appear after
    # subModules on the wire, so collect first
    raw_attrs = []
    for f, w, v in iter_fields(buf):
        if f == 1 and w == 2:
            m["name"] = v.decode("utf-8")
        elif f == 7 and w == 2:
            m["type"] = v.decode("utf-8")
        elif f == 8 and w == 2:
            raw_attrs.append(v)
    # attr map: key=1, value=2
    pending = []
    for v in raw_attrs:
        k = raw = None
        for f2, w2, v2 in iter_fields(v):
            if f2 == 1 and w2 == 2:
                k = v2.decode("utf-8")
            elif f2 == 2 and w2 == 2:
                raw = v2
        if k == "global_storage" and raw is not None:
            m["attr"][k] = _decode_attr(raw, storages)  # registers storages
        elif k is not None:
            pending.append((k, raw))
    for k, raw in pending:
        m["attr"][k] = _decode_attr(raw, storages) if raw is not None \
            else None
    for f, w, v in iter_fields(buf):
        if f == 2 and w == 2:
            m["subs"].append(_decode_module(v, storages))
        elif f == 3 and w == 2:
            m["weight"] = _decode_tensor(v, storages)
        elif f == 4 and w == 2:
            m["bias"] = _decode_tensor(v, storages)
        elif f == 5 and w == 2:       # preModules (graph wiring)
            m["pres"].append(v.decode("utf-8"))
        elif f == 15 and w == 0:
            m["has_params"] = bool(v)
        elif f == 16 and w == 2:
            m["params"].append(_decode_tensor(v, storages))
    return m


# --------------------------------------------------------------------- #
# module factory (≙ ModuleSerializer's registered deserializers)         #
# --------------------------------------------------------------------- #
def _mk_linear(a):
    return nn.Linear(int(a["inputSize"]), int(a["outputSize"]),
                     with_bias=a.get("withBias", True))


def _mk_conv(a):
    return nn.SpatialConvolution(
        int(a["nInputPlane"]), int(a["nOutputPlane"]),
        int(a["kernelW"]), int(a["kernelH"]),
        int(a.get("strideW", 1)), int(a.get("strideH", 1)),
        int(a.get("padW", 0)), int(a.get("padH", 0)),
        n_group=int(a.get("nGroup", 1)),
        with_bias=a.get("withBias", True))


def _mk_maxpool(a):
    return nn.SpatialMaxPooling(
        int(a["kW"]), int(a["kH"]), int(a.get("dW", 1)), int(a.get("dH", 1)),
        int(a.get("padW", 0)), int(a.get("padH", 0)))


def _mk_avgpool(a):
    return nn.SpatialAveragePooling(
        int(a["kW"]), int(a["kH"]), int(a.get("dW", 1)), int(a.get("dH", 1)),
        int(a.get("padW", 0)), int(a.get("padH", 0)),
        count_include_pad=a.get("countIncludePad", True))


def _mk_bn(a):
    return nn.SpatialBatchNormalization(
        int(a["nOutput"]), eps=float(a.get("eps", 1e-5)),
        momentum=float(a.get("momentum", 0.1)),
        affine=a.get("affine", True))


def _mk_bn1d(a):
    return nn.BatchNormalization(
        int(a["nOutput"]), eps=float(a.get("eps", 1e-5)),
        momentum=float(a.get("momentum", 0.1)),
        affine=a.get("affine", True))


# --------------------------------------------------------------------- #
# recurrent modules — one-way READ transform (VERDICT r3 item 3).        #
# nn/Recurrent.scala:604 serializes topology/preTopology as module       #
# attrs; cells go through Cell.scala:242 CellSerializer (ctor attrs +    #
# the internal Linear-graph under the "cell" attr + flat parameters).    #
# We rebuild our fused cells from the Linear weights instead of          #
# executing the reference graph.                                         #
# --------------------------------------------------------------------- #
_CELL_TYPES = {"LSTM", "GRU", "RnnCell"}


def _checked_cell_p(tree):
    """The cell's dropout p, raising for types whose p>0 wire layout
    (per-gate Linear graphs) the reader does not rebuild."""
    t = _short_type(tree["type"])
    p = float(tree["attr"].get("p") or 0.0)
    if p != 0.0 and t not in ("LSTM", "GRU"):
        raise ValueError(
            f".bigdl {t} with dropout p={p} serializes per-gate Linear "
            "graphs; only LSTM/GRU read the p>0 layout")
    return p


def _build_activation(tree, where):
    """Build a cell activation module; only stateless ones are usable
    inside our fused cells (a PReLU's weight would have no params slot)."""
    mod = _build(tree)
    import jax
    if mod.init(jax.random.PRNGKey(0)):
        raise ValueError(
            f".bigdl {where}: parameterized activation "
            f"{_short_type(tree['type'])} is not supported in fused cells")
    return mod


def _cell_activation(a, key, default_type, where):
    """Return the non-default activation module from attr `key`, or
    None when absent / the reference default (ctor fills defaults in,
    so the attr is present even for untouched cells)."""
    tr = a.get(key)
    if not isinstance(tr, dict) or _short_type(tr["type"]) == default_type:
        return None
    return _build_activation(tr, where)


def _build_cell(tree):
    t = _short_type(tree["type"])
    a = tree["attr"]
    cell_p = _checked_cell_p(tree)
    if t == "LSTM":
        cell = nn.LSTM(
            int(a["inputSize"]), int(a["hiddenSize"]), p=cell_p,
            activation=_cell_activation(a, "activation", "Tanh", t),
            inner_activation=_cell_activation(
                a, "innerActivation", "Sigmoid", t))
    elif t == "GRU":
        cell = nn.GRU(
            int(a["inputSize"]), int(a["outputSize"]), p=cell_p,
            activation=_cell_activation(a, "activation", "Tanh", t),
            inner_activation=_cell_activation(
                a, "innerActivation", "Sigmoid", t))
    elif t == "RnnCell":
        act_tree = a.get("activation")
        act = _build_activation(act_tree, t) \
            if isinstance(act_tree, dict) else None
        cell = nn.RnnCell(int(a["inputSize"]), int(a["hiddenSize"]),
                          activation=act)
    elif t == "MultiRNNCell":
        cells = a.get("cells") or []
        if not cells:
            raise ValueError(
                ".bigdl MultiRNNCell: missing or empty 'cells' attr")
        cell = nn.MultiRNNCell([_build_cell(c) for c in cells])
    else:
        raise ValueError(f"unsupported recurrent cell {tree['type']!r}")
    if tree["name"]:
        cell.set_name(tree["name"])
    return cell


def _hidden_shapes_ok(t, a, own):
    """Would `own` still satisfy the cell's hidden-weight shape scan?
    Used to validate the lead-match drop when includePreTopology is
    absent from the wire (older files)."""
    mats = [m for m in own if m.ndim == 2]
    if t == "LSTM":
        h = int(a["hiddenSize"])
        return any(m.shape[0] == 4 * h for m in mats)
    if t == "GRU":
        h = int(a["outputSize"])
        return (any(m.shape[0] == 2 * h for m in mats)
                and any(m.shape == (h, h) for m in mats))
    if t == "RnnCell":
        h = int(a["hiddenSize"])
        return any(m.shape == (h, h) for m in mats)
    return True


def _split_gate_linears(own, what):
    """Classify a p>0 cell's flat params into (input-Linear (w, b)
    pairs, hidden-Linear weights): with dropout the reference builds
    per-gate Sequential(Dropout, Linear) stacks where every
    input-to-gate Linear carries a bias and every hidden-to-gate Linear
    is withBias=false (LSTM.scala:88-116, GRU.scala:90-105) — the bias
    adjacency disambiguates even when inputSize == hiddenSize."""
    pairs, hmats = [], []
    i = 0
    while i < len(own):
        m = own[i]
        if m.ndim == 2 and i + 1 < len(own) and own[i + 1].ndim == 1 \
                and own[i + 1].shape == (m.shape[0],):
            pairs.append((m, own[i + 1]))
            i += 2
        elif m.ndim == 2:
            hmats.append(m)
            i += 1
        else:
            raise ValueError(
                f".bigdl {what} (p>0): unexpected rank-{m.ndim} entry "
                "in the cell's flat params")
    return pairs, hmats


def _cell_weights_dropout(tree, t, a):
    """p>0 wire layout (no preTopology; per-gate Linears in the cell's
    own flat params) -> our fused weight dicts."""
    own = [np.asarray(q, np.float32) for q in tree["params"]]
    pairs, hmats = _split_gate_linears(own, t)
    if t == "LSTM":
        h = int(a["hiddenSize"])
        if len(pairs) != 4 or len(hmats) != 4 \
                or any(w.shape[0] != h for w, _ in pairs) \
                or any(m.shape != (h, h) for m in hmats):
            raise ValueError(
                f".bigdl LSTM(p>0): expected 4 biased input Linears + "
                f"4 hidden mats of width {h}, got "
                f"{[w.shape for w, _ in pairs]} / "
                f"{[m.shape for m in hmats]}")
        # reference per-gate order is [i, g, f, o] (JoinTable of the
        # buildGates Linears); fused order is [i, f, g, o]
        perm = (0, 2, 1, 3)
        w_pre = np.concatenate([pairs[k][0] for k in perm], 0)
        bias = np.concatenate([pairs[k][1] for k in perm], 0)
        w_h = np.concatenate([hmats[k] for k in perm], 0)
        return tree["name"], {"weight_i": w_pre.T.copy(),
                              "weight_h": w_h.T.copy(), "bias": bias}
    # GRU: i2g [r, z] + candidate f2g carry biases; h2g [r, z] +
    # candidate linear2 don't (GRU.scala:90-105, :132-146)
    h = int(a["outputSize"])
    if len(pairs) != 3 or len(hmats) != 3 \
            or any(w.shape[0] != h for w, _ in pairs) \
            or any(m.shape != (h, h) for m in hmats):
        raise ValueError(
            f".bigdl GRU(p>0): expected 3 biased input Linears + 3 "
            f"hidden mats of width {h}, got "
            f"{[w.shape for w, _ in pairs]} / {[m.shape for m in hmats]}")
    (w_r, b_r), (w_z, b_z), (w_n, b_n) = pairs
    h_r, h_z, h_n = hmats
    return tree["name"], {
        "gates": {"weight_i": np.concatenate([w_r, w_z], 0).T.copy(),
                  "weight_h": np.concatenate([h_r, h_z], 0).T.copy(),
                  "bias": np.concatenate([b_r, b_z], 0)},
        "new": {"weight_i": w_n.T.copy(), "weight_h": h_n.T.copy(),
                "bias": b_n}}


def _pick_mat(mats, pred, what, t):
    for m in mats:
        if pred(m):
            return m
    raise ValueError(f".bigdl {t}: no {what} weight in cell parameters")


def _cell_weights(tree, split_pre_bias=False):
    """Reference cell wire tree -> (cell_name, our fused weight dict).

    The Linear weights live in two places: the input-to-gate Linear
    under the cell's "preTopology" module attr (LSTM.scala:77-81,
    GRU.scala:80-83, RNN.scala:62-67), and the hidden-to-gate Linears
    in the cell module's own flat parameter list (Cell.parameters() =
    the internal graph's Linears in topo order).  Reference Linear
    weights are (out, in); our fused layout is (in, out).

    ``split_pre_bias=True`` (the Recurrent(BatchNormParams) load path)
    keeps the preTopology Linear bias OUT of the fused step bias and
    returns a 4-tuple (name, weights, pre_bias, perm) instead — the
    pre-bias is applied BEFORE the BatchNorm (Recurrent.scala:119), and
    ``perm`` re-orders any per-feature vector of the projection (BN
    gamma/beta/running stats) from the reference's gate order onto our
    fused one.
    """
    t = _short_type(tree["type"])
    a = tree["attr"]
    if _checked_cell_p(tree) != 0.0:
        # dropout form: no preTopology, per-gate Linears in flat params
        if split_pre_bias:
            raise ValueError(
                f".bigdl {t}: BatchNormParams with p > 0 has no wire "
                "form (the reference's p > 0 cells have no preTopology)")
        return _cell_weights_dropout(tree, t, a)
    pre = a.get("preTopology")
    pre_params = (pre or {}).get("params") or []
    if not pre_params:
        raise ValueError(
            f".bigdl {t}: preTopology input Linear weights are missing")
    w_pre = np.asarray(pre_params[0], np.float32)
    b_pre = np.asarray(pre_params[1], np.float32) \
        if len(pre_params) > 1 else None
    # a cell with includePreTopology=true (RecurrentDecoder) carries the
    # preTopology Linear FIRST in its own flat params (Cell.parameters =
    # Sequential(pre, cell)) — drop them positionally so the shape-driven
    # hidden-weight scan can't pick the input Linear when input size ==
    # hidden size (the decoder's feedback case).  Keyed on the cell's
    # serialized includePreTopology attr (CellSerializer writes it).
    # When the attr is ABSENT (older files) the lead-match heuristic is
    # only trusted if the remaining params still carry the expected
    # hidden-weight shapes — a plain cell with genuinely tied input
    # weights (lead matches by value, but those ARE its hidden weights)
    # keeps its full list instead of being mis-dropped.
    own = [np.asarray(q, np.float32) for q in tree["params"]]
    n_pre = len(pre_params)
    inc = a.get("includePreTopology")
    lead_matches = (
        len(own) > n_pre
        and all(own[i].shape == np.shape(pre_params[i])
                for i in range(n_pre))
        and all(np.array_equal(own[i],
                               np.asarray(pre_params[i], np.float32))
                for i in range(n_pre)))
    if inc:
        if not lead_matches:
            raise ValueError(
                f".bigdl {t}: includePreTopology=true but the flat "
                "params do not lead with the preTopology weights")
        own = own[n_pre:]
    elif inc is None and lead_matches \
            and _hidden_shapes_ok(t, a, own[n_pre:]):
        own = own[n_pre:]
    if t == "LSTM":
        h = int(a["hiddenSize"])
        w_h = _pick_mat(own, lambda m: m.ndim == 2 and m.shape[0] == 4 * h,
                        "hidden-to-gate", t)
        # reference gate chunks are [i, g, f, o] (LSTM.scala:134-147
        # buildGates Select order); our fused order is [i, f, g, o]
        perm = (0, 2, 1, 3)

        def reorder(m):
            return np.concatenate([m[k * h:(k + 1) * h] for k in perm], 0)

        bias = reorder(b_pre) if b_pre is not None \
            else np.zeros(4 * h, np.float32)
        wd = {"weight_i": reorder(w_pre).T.copy(),
              "weight_h": reorder(w_h).T.copy(), "bias": bias}
        if split_pre_bias:
            wd["bias"] = np.zeros(4 * h, np.float32)
            return tree["name"], wd, bias, reorder
        return tree["name"], wd
    if t == "GRU":
        h = int(a["outputSize"])
        # pre chunks are [r, z, n] (GRU.scala:107 Narrow + :137 f2g)
        w_h2g = _pick_mat(own, lambda m: m.ndim == 2 and m.shape[0] == 2 * h,
                          "hidden-to-rz", t)
        w_new = _pick_mat(own, lambda m: m.ndim == 2 and m.shape == (h, h),
                          "hidden-to-new", t)
        bias = b_pre if b_pre is not None else np.zeros(3 * h, np.float32)
        wd = {
            "gates": {"weight_i": w_pre[:2 * h].T.copy(),
                      "weight_h": w_h2g.T.copy(), "bias": bias[:2 * h]},
            "new": {"weight_i": w_pre[2 * h:].T.copy(),
                    "weight_h": w_new.T.copy(), "bias": bias[2 * h:]}}
        if split_pre_bias:
            # projection order [r, z, n] == our [gates(2h), new(h)] concat
            wd["gates"]["bias"] = np.zeros(2 * h, np.float32)
            wd["new"]["bias"] = np.zeros(h, np.float32)
            return tree["name"], wd, bias, lambda v: v
        return tree["name"], wd
    if t == "RnnCell":
        h = int(a["hiddenSize"])
        w_h = _pick_mat(own, lambda m: m.ndim == 2 and m.shape == (h, h),
                        "hidden-to-hidden", t)
        # reference has separate input/hidden biases; ours is one sum
        b_h = next((m for m in own if m.ndim == 1 and m.shape == (h,)), None)
        wd = {"weight_i": w_pre.T.copy(), "weight_h": w_h.T.copy()}
        if split_pre_bias:
            wd["bias"] = b_h if b_h is not None else np.zeros(h, np.float32)
            pre = b_pre if b_pre is not None else np.zeros(h, np.float32)
            return tree["name"], wd, pre, lambda v: v
        bias = np.zeros(h, np.float32)
        if b_pre is not None:
            bias = bias + b_pre
        if b_h is not None:
            bias = bias + b_h
        wd["bias"] = bias
        return tree["name"], wd
    raise ValueError(f"unsupported recurrent cell {tree['type']!r}")


def _build_recurrent_decoder(tree):
    a = tree["attr"]
    if a.get("bnorm"):
        raise ValueError(
            ".bigdl RecurrentDecoder(BatchNormParams) is not supported")
    topo = a.get("topology")
    if not isinstance(topo, dict):
        raise ValueError(".bigdl RecurrentDecoder: missing topology attr")
    dec = nn.RecurrentDecoder(int(a["seqLength"]), _build_cell(topo))
    if tree["name"]:
        dec.set_name(tree["name"])
    return dec


def _bn_params_from_attrs(a):
    """Recurrent/BiRecurrent bnorm attrs -> nn.BatchNormParams
    (Recurrent.scala:738-768 doLoadModule reads bnormEps/bnormMomentum/
    bnormAffine; gamma/beta come from the serialized BN module itself,
    so init_weight/init_bias are not needed here)."""
    eps = a.get("bnormEps")
    mom = a.get("bnormMomentum")
    aff = a.get("bnormAffine")
    # None-checks, not `or`: momentum=0.0 (frozen running stats) and
    # affine=False are legitimate serialized values
    return nn.BatchNormParams(
        eps=1e-5 if eps is None else float(eps),
        momentum=0.1 if mom is None else float(mom),
        affine=True if aff is None else bool(aff))


def _recurrent_bn_tree(rec_tree):
    """Find the BatchNormalization module tree under a bnorm=true
    Recurrent's preTopology attr (Recurrent.scala:111-119 wraps it as
    Sequential[TimeDistributed(pre), TimeDistributed(BN)])."""
    stack = [rec_tree["attr"].get("preTopology")]
    while stack:
        t = stack.pop()
        if not isinstance(t, dict):
            continue
        st = _short_type(t["type"])
        if st in ("BatchNormalization", "SpatialBatchNormalization"):
            return t
        inner = t["attr"].get("layer") if st == "TimeDistributed" else None
        if inner is not None:
            stack.append(inner)
        stack.extend(t.get("subs") or [])
    raise ValueError(
        ".bigdl Recurrent(bnorm): no BatchNormalization module found "
        "under the preTopology attr")


def _build_recurrent(tree):
    a = tree["attr"]
    topo = a.get("topology")
    if not isinstance(topo, dict):
        raise ValueError(".bigdl Recurrent: missing topology cell attr")
    bn = _bn_params_from_attrs(a) if a.get("bnorm") else None
    rec = nn.Recurrent(_build_cell(topo), batch_norm_params=bn,
                       mask_zero=bool(a.get("maskZero")))
    if tree["name"]:
        rec.set_name(tree["name"])
    return rec


def _birnn_recurrents(birnn):
    """BiRecurrent's internal Sequential (BiRecurrent.scala:48-66):
    [input-fanout, ParallelTable[fwd Recurrent, Sequential[Reverse,
    rev Recurrent, Reverse]], merge] -> (fwd tree, rev tree)."""
    for sub in birnn.get("subs", []):
        if _short_type(sub["type"]) == "ParallelTable" \
                and len(sub["subs"]) == 2:
            fwd = sub["subs"][0]
            rev = next((x for x in sub["subs"][1].get("subs", [])
                        if _short_type(x["type"]) == "Recurrent"), None)
            if _short_type(fwd["type"]) == "Recurrent" and rev is not None:
                return fwd, rev
    raise ValueError(
        ".bigdl BiRecurrent: unrecognized birnn layout (expected "
        "ParallelTable of forward Recurrent + Reverse/Recurrent/Reverse)")


def _build_birecurrent(tree):
    a = tree["attr"]
    birnn = a.get("birnn")
    if not isinstance(birnn, dict):
        raise ValueError(".bigdl BiRecurrent: missing birnn attr")
    fwd_t, _ = _birnn_recurrents(birnn)
    subs = birnn.get("subs", [])
    merge_t = subs[-1] if subs else None
    merge = None
    if merge_t is not None and _short_type(merge_t["type"]) not in (
            "CAddTable",):
        merge = _build(merge_t)
    # isSplitInput rides the ctor attr when present; older files show it
    # structurally as a leading BifurcateSplitTable (BiRecurrent.scala:50)
    split = bool(a.get("isSplitInput")) or any(
        _short_type(s["type"]) == "BifurcateSplitTable"
        for s in subs[:1])
    # bnorm: each direction's internal Recurrent carries its own
    # BatchNorm (BiRecurrent.scala:45-46); config attrs ride the
    # BiRecurrent node (bnormEps/bnormMomentum, BiRecurrent.scala:178-193)
    bn = _bn_params_from_attrs(a) if a.get("bnorm") else None
    m = nn.BiRecurrent(merge=merge, cell=_build_cell(
        fwd_t["attr"]["topology"]), is_split_input=split,
        batch_norm_params=bn)
    if tree["name"]:
        m.set_name(tree["name"])
    return m


def _assign_cell_weights(params, cell_tree, target=None,
                         target_tree=None):
    """Assign a serialized cell's weights into `params`.  `target`
    renames the destination slot (BiRecurrent's backward cell is a
    "<fwd>_bwd" rename of the forward one); for a MultiRNNCell the
    renames apply per sub-cell, so `target_tree` carries the FORWARD
    topology whose sub-cell names the built model used."""
    import jax
    if _short_type(cell_tree["type"]) == "MultiRNNCell":
        subs = cell_tree["attr"].get("cells") or []
        if target is None:
            for sub in subs:
                _assign_cell_weights(params, sub)
            return
        fwd_subs = (target_tree or {}).get("attr", {}).get("cells") or []
        if len(fwd_subs) != len(subs):
            raise ValueError(
                ".bigdl BiRecurrent over MultiRNNCell: forward/backward "
                f"stacks differ ({len(fwd_subs)} vs {len(subs)} cells)")
        for sub, fsub in zip(subs, fwd_subs):
            _assign_cell_weights(params, sub,
                                 target=f"{fsub['name']}_bwd")
        return
    cname, wd = _cell_weights(cell_tree)
    if target is not None:
        cname = target
    if cname not in params:
        raise ValueError(
            f".bigdl recurrent cell {cname!r} has no params slot in the "
            "built model")
    want = jax.tree_util.tree_map(np.shape, params[cname])
    got = jax.tree_util.tree_map(np.shape, wd)
    if want != got:
        raise ValueError(
            f".bigdl cell {cname!r}: weight shapes {got} do not match "
            f"the built cell {want}")
    params[cname] = wd


def _assign_recurrent_bn(params, state, rec_tree, rec_slot,
                         cell_slot=None):
    """bnorm=true Recurrent tree -> cell weights (preTopology bias split
    OUT of the fused step bias: it applies BEFORE the BatchNorm,
    Recurrent.scala:119), the built Recurrent's own ``bias_pre``, and
    the BN's gamma/beta + running stats — all per-feature vectors of the
    projection permuted from the reference's gate order onto our fused
    one.  ``rec_slot`` names the built Recurrent's own params slot
    (BiRecurrent runners are '<bi>_f'/'<bi>_b'); ``cell_slot`` renames
    the cell slot (the backward direction's '<fwd>_bwd')."""
    import jax
    topo = rec_tree["attr"]["topology"]
    cname, wd, pre_bias, perm = _cell_weights(topo, split_pre_bias=True)
    if cell_slot is not None:
        cname = cell_slot
    for slot in (cname, rec_slot):
        if slot not in params:
            raise ValueError(
                f".bigdl Recurrent(bnorm): no params slot {slot!r} in "
                "the built model")
    want = jax.tree_util.tree_map(np.shape, params[cname])
    got = jax.tree_util.tree_map(np.shape, wd)
    if want != got:
        raise ValueError(
            f".bigdl cell {cname!r}: weight shapes {got} do not match "
            f"the built cell {want}")
    params[cname] = wd
    own = dict(params[rec_slot])
    own["bias_pre"] = np.asarray(pre_bias, np.float32).reshape(
        np.shape(own["bias_pre"]))
    params[rec_slot] = own
    bn_tree = _recurrent_bn_tree(rec_tree)
    bn_slot = f"{rec_slot}_bn"
    arrs = bn_tree["params"] if bn_tree["has_params"] else \
        [t for t in (bn_tree["weight"], bn_tree["bias"]) if t is not None]
    if arrs and bn_slot in params:
        own_bn = dict(params[bn_slot])
        keys = nn.Module._weights_order(own_bn)
        for k, arr in zip(keys, arrs):
            own_bn[k] = perm(np.asarray(arr, np.float32).reshape(
                np.shape(own_bn[k])))
        params[bn_slot] = own_bn
    st = state.get(bn_slot)
    if isinstance(st, dict):
        st = dict(st)
        for ak, sk in (("runningMean", "running_mean"),
                       ("runningVar", "running_var")):
            val = bn_tree["attr"].get(ak)
            if val is not None and sk in st:
                st[sk] = perm(np.asarray(val, np.float32).reshape(
                    np.shape(st[sk])))
        state[bn_slot] = st


_FACTORY = {
    "Linear": _mk_linear,
    "SpatialConvolution": _mk_conv,
    "SpatialMaxPooling": _mk_maxpool,
    "SpatialAveragePooling": _mk_avgpool,
    "SpatialBatchNormalization": _mk_bn,
    "BatchNormalization": _mk_bn1d,
    "TimeDistributed": lambda a: nn.TimeDistributed(
        _build(a["layer"]), mask_zero=bool(a.get("maskZero"))),
    "LookupTable": lambda a: nn.LookupTable(
        int(a["nIndex"]), int(a["nOutput"]),
        padding_value=float(a.get("paddingValue", 0.0) or 0.0),
        # reference reflection always writes maxNorm; its default is
        # Double.MaxValue == "no renorm" — map to None or every forward
        # pays a useless per-row norm
        max_norm=(None if a.get("maxNorm") is None
                  or float(a["maxNorm"]) >= 1e300 else
                  float(a["maxNorm"])),
        norm_type=float(a.get("normType") or 2.0),
        mask_zero=bool(a.get("maskZero", False))),
    "SpatialFullConvolution": lambda a: nn.SpatialFullConvolution(
        int(a["nInputPlane"]), int(a["nOutputPlane"]),
        int(a["kW"]), int(a["kH"]),
        int(a.get("dW", 1)), int(a.get("dH", 1)),
        int(a.get("padW", 0)), int(a.get("padH", 0)),
        int(a.get("adjW", 0)), int(a.get("adjH", 0)),
        n_group=int(a.get("nGroup", 1)),
        no_bias=bool(a.get("noBias", False))),
    "SpatialDilatedConvolution": lambda a: nn.SpatialDilatedConvolution(
        int(a["nInputPlane"]), int(a["nOutputPlane"]),
        int(a["kW"]), int(a["kH"]),
        int(a.get("dW", 1)), int(a.get("dH", 1)),
        int(a.get("padW", 0)), int(a.get("padH", 0)),
        int(a.get("dilationW", 1)), int(a.get("dilationH", 1))),
    "TemporalConvolution": lambda a: nn.TemporalConvolution(
        int(a["inputFrameSize"]), int(a["outputFrameSize"]),
        int(a["kernelW"]), int(a.get("strideW", 1))),
    "SpatialZeroPadding": lambda a: nn.SpatialZeroPadding(
        int(a.get("padLeft", 0)), int(a.get("padRight", 0)),
        int(a.get("padTop", 0)), int(a.get("padBottom", 0))),
    "Padding": lambda a: (
        (_ for _ in ()).throw(ValueError(
            ".bigdl Padding with nIndex != 1 is not supported"))
        if int(a.get("nIndex", 1) or 1) != 1 else nn.Padding(
            int(a["dim"]), int(a["pad"]), int(a.get("nInputDim", 0)),
            float(a.get("value", 0.0) or 0.0))),
    "SpatialCrossMapLRN": lambda a: nn.SpatialCrossMapLRN(
        int(a.get("size", 5)), float(a.get("alpha", 1.0)),
        float(a.get("beta", 0.75)), float(a.get("k", 1.0))),
    "ReLU": lambda a: nn.ReLU(),
    "Tanh": lambda a: nn.Tanh(),
    "Sigmoid": lambda a: nn.Sigmoid(),
    "SoftMax": lambda a: nn.SoftMax(),
    "LogSoftMax": lambda a: nn.LogSoftMax(),
    "Identity": lambda a: nn.Identity(),
    "Dropout": lambda a: nn.Dropout(float(a.get("initP", 0.5))),
    "Reshape": lambda a: nn.Reshape(
        [int(s) for s in a.get("size", [])],
        batch_mode=a.get("batchMode")),
    "View": lambda a: nn.View([int(s) for s in a.get("sizes", [])]),
    "JoinTable": lambda a: nn.JoinTable(
        int(a.get("dimension", 1)), int(a.get("nInputDims", -1))),
    "CAddTable": lambda a: nn.CAddTable(),
    "CMulTable": lambda a: nn.CMulTable(),
    "ELU": lambda a: nn.ELU(float(a.get("alpha", 1.0))),
    "PReLU": lambda a: nn.PReLU(int(a.get("nOutputPlane", 0))),
    "Abs": lambda a: nn.Abs(),
    "Power": lambda a: nn.Power(float(a.get("power", 1.0)),
                                float(a.get("scale", 1.0)),
                                float(a.get("shift", 0.0))),
    "Exp": lambda a: nn.Exp(),
    "Log": lambda a: nn.Log(),
    "HardTanh": lambda a: nn.HardTanh(float(a.get("minValue", -1.0)),
                                      float(a.get("maxValue", 1.0))),
    "Clamp": lambda a: nn.Clamp(float(a.get("min", -1.0)),
                                float(a.get("max", 1.0))),
    "SoftPlus": lambda a: nn.SoftPlus(float(a.get("beta", 1.0))),
    "SoftSign": lambda a: nn.SoftSign(),
    "LeakyReLU": lambda a: nn.LeakyReLU(float(a.get("negval", 0.01))),
    "ReLU6": lambda a: nn.ReLU6(),
    "Threshold": lambda a: nn.Threshold(float(a.get("th", 1e-6)),
                                        float(a.get("v", 0.0))),
    "MulConstant": lambda a: nn.MulConstant(float(a.get("scalar", 1.0))),
    "AddConstant": lambda a: nn.AddConstant(
        float(a.get("constant_scalar", 0.0))),
    "Squeeze": lambda a: nn.Squeeze(a.get("dim")),
    "Unsqueeze": lambda a: nn.Unsqueeze(int(a.get("pos", 1))),
    "Select": lambda a: nn.Select(int(a.get("dimension", a.get("dim", 1))),
                                  int(a.get("index", 1))),
    "Narrow": lambda a: nn.Narrow(int(a.get("dimension", 1)),
                                  int(a.get("offset", 1)),
                                  int(a.get("length", 1))),
    "Mean": lambda a: nn.Mean(int(a.get("dimension", 1)),
                              int(a.get("nInputDims", -1)),
                              a.get("squeeze", True)),
    "CMul": lambda a: nn.CMul([int(s) for s in a.get("size", [])]),
    "CAdd": lambda a: nn.CAdd([int(s) for s in a.get("size", [])]),
    "Mul": lambda a: nn.Mul(),
    "Normalize": lambda a: nn.Normalize(float(a.get("p", 2.0)),
                                        float(a.get("eps", 1e-10))),
    "GaussianDropout": lambda a: nn.GaussianDropout(
        float(a.get("rate", 0.5))),
    "GaussianNoise": lambda a: nn.GaussianNoise(
        float(a.get("stddev", 1.0))),
    "SoftMin": lambda a: nn.SoftMin(),
    "LogSigmoid": lambda a: nn.LogSigmoid(),
    "HardSigmoid": lambda a: nn.HardSigmoid(),
    "Echo": lambda a: nn.Echo(),
    "FlattenTable": lambda a: nn.FlattenTable(),
    "SelectTable": lambda a: nn.SelectTable(int(a.get("index", 1))),
    "NarrowTable": lambda a: nn.NarrowTable(int(a.get("offset", 1)),
                                            int(a.get("length", 1))),
    "MaskedSelect": lambda a: nn.MaskedSelect(),
    "Index": lambda a: nn.Index(int(a.get("dimension", 1))),
    "Sequential": lambda a: nn.Sequential(),
    "ConcatTable": lambda a: nn.ConcatTable(),
    "ParallelTable": lambda a: nn.ParallelTable(),
    "Concat": lambda a: nn.Concat(int(a.get("dimension", 1))),
}

_CONTAINERS = {"Sequential", "ConcatTable", "ParallelTable", "Concat"}


_GRAPHS = {"StaticGraph", "Graph", "DynamicGraph"}


def _short_type(full: str) -> str:
    return full.rsplit(".", 1)[-1]


def _build_graph(tree):
    """DAG module (nn/Graph.scala GraphSerializable: subModules carry
    preModules wiring; inputNames/outputNames attrs name the
    endpoints)."""
    from ..nn.graph import Graph as NNGraph, Node

    by_name = {sub["name"]: sub for sub in tree["subs"]}
    if len(by_name) != len(tree["subs"]):
        raise ValueError(
            ".bigdl graph: duplicate node names (shared-module graphs "
            "are not supported)")
    nodes = {}
    visiting = set()

    def node_of(nm):
        if nm in nodes:
            return nodes[nm]
        if nm in visiting:
            raise ValueError(f".bigdl graph: wiring cycle through {nm!r}")
        visiting.add(nm)
        sub = by_name[nm]
        pres = [node_of(p) for p in sub["pres"] if p in by_name]
        if _short_type(sub["type"]) == "Input":
            nodes[nm] = Node(None, [])
        else:
            nodes[nm] = Node(_build(sub), pres)
        visiting.discard(nm)
        return nodes[nm]

    for sub in tree["subs"]:
        node_of(sub["name"])
    in_names = tree["attr"].get("inputNames") or []
    out_names = tree["attr"].get("outputNames") or []
    if not in_names or not out_names:
        raise ValueError(".bigdl graph: missing inputNames/outputNames")
    g = NNGraph([nodes[n] for n in in_names],
                [nodes[n] for n in out_names])
    if tree["name"]:
        g.set_name(tree["name"])
    return g


def _fix_temporal_conv(mod, arrs):
    """Reference TemporalConvolution weight is (out, in*kW) with column
    k*inputFrameSize + i (TemporalConvolution.scala:63 unfold layout);
    ours is (out, in, kW)."""
    out = []
    for a in arrs:
        a = np.asarray(a, np.float32)
        if a.ndim == 2:         # the weight; bias passes through
            a = a.reshape(mod.output_frame_size, mod.kernel_w,
                          mod.input_frame_size).transpose(0, 2, 1)
        out.append(a)
    return out


def _unfix_temporal_conv(mod, arrs):
    """Inverse of :func:`_fix_temporal_conv` for the writer: our
    (out, in, kW) -> reference (out, in*kW) with column k*fin + i."""
    out = []
    for a in arrs:
        a = np.asarray(a, np.float32)
        if a.ndim == 3:
            a = a.transpose(0, 2, 1).reshape(a.shape[0], -1)
        out.append(a)
    return out


_WEIGHT_FIX = {"TemporalConvolution": _fix_temporal_conv}
_WEIGHT_UNFIX = {"TemporalConvolution": _unfix_temporal_conv}


def _build(tree):
    t = _short_type(tree["type"])
    if t in _GRAPHS:
        return _build_graph(tree)
    if t == "Recurrent":
        return _build_recurrent(tree)
    if t == "RecurrentDecoder":
        return _build_recurrent_decoder(tree)
    if t == "BiRecurrent":
        return _build_birecurrent(tree)
    if t in _CELL_TYPES or t == "MultiRNNCell":
        return _build_cell(tree)
    fac = _FACTORY.get(t)
    if fac is None:
        raise ValueError(
            f".bigdl module type {tree['type']!r} is not mapped; "
            f"supported: {sorted(_FACTORY) + sorted(_GRAPHS)}")
    mod = fac(tree["attr"])
    if tree["name"]:
        mod.set_name(tree["name"])
    if t in _CONTAINERS:
        for sub in tree["subs"]:
            mod.add(_build(sub))
    return mod


def _leaf_modules(tree):
    t = _short_type(tree["type"])
    if t in _CONTAINERS or t in _GRAPHS:
        for s in tree["subs"]:
            yield from _leaf_modules(s)
    elif t != "Input":
        yield tree


def load_bigdl(path: str):
    """Read a reference `.bigdl` model file into a bigdl_tpu Module
    (≙ Module.loadModule / ModuleLoader.loadFromFile)."""
    with open(path, "rb") as f:
        data = f.read()
    storages: Dict[int, np.ndarray] = {}
    tree = _decode_module(data, storages)
    model = _build(tree)
    params, state = model.init_params(0)
    # assign by MODULE NAME (params are keyed by it, and _build preserved
    # every serialized name) — robust to container vs graph traversal order
    _by_name = {m.name: m for m in model.modules()}

    def assign_leaf(sub):
        st = _short_type(sub["type"])
        if st in ("Recurrent", "RecurrentDecoder"):
            if sub["attr"].get("bnorm") and st == "Recurrent":
                _assign_recurrent_bn(params, state, sub,
                                     rec_slot=sub["name"])
                return
            # cell weights come from the topology attr's Linear layout,
            # not the Recurrent's own flat parameter list
            _assign_cell_weights(params, sub["attr"]["topology"])
            return

        if st == "BiRecurrent":
            fwd_t, rev_t = _birnn_recurrents(sub["attr"]["birnn"])
            fwd_name = fwd_t["attr"]["topology"]["name"]
            if sub["attr"].get("bnorm"):
                # per-direction BN: the runners' slots are
                # '<bi>_f'/'<bi>_b' (nn/recurrent.py BiRecurrent._runners)
                bi = sub["name"]
                _assign_recurrent_bn(params, state, fwd_t,
                                     rec_slot=f"{bi}_f")
                _assign_recurrent_bn(params, state, rev_t,
                                     rec_slot=f"{bi}_b",
                                     cell_slot=f"{fwd_name}_bwd")
                return
            _assign_cell_weights(params, fwd_t["attr"]["topology"])
            # the built model's backward cell is a rename of the forward
            # one ("<fwd>_bwd", nn/recurrent.py BiRecurrent._ensure_bwd);
            # the reference's reverse topology has its own name — assign
            # with the same shape/structure validation as the fwd cell
            _assign_cell_weights(params, rev_t["attr"]["topology"],
                                 target=f"{fwd_name}_bwd",
                                 target_tree=fwd_t["attr"]["topology"])
            return
        if st in _CELL_TYPES or st == "MultiRNNCell":
            _assign_cell_weights(params, sub)
            return
        if st == "TimeDistributed":
            # the weights belong to the wrapped layer (the "layer"
            # module attr); the TimeDistributed node's own flat list
            # mirrors them
            for inner in _leaf_modules(sub["attr"]["layer"]):
                assign_leaf(inner)
            return
        arrs = sub["params"] if sub["has_params"] else \
            [t for t in (sub["weight"], sub["bias"]) if t is not None]
        if not arrs:
            return
        name = sub["name"]
        if name not in params:
            raise ValueError(
                f".bigdl layer {name!r} carries parameters but the built "
                "model has no params under that name")
        own = dict(params[name])
        keys = [k for k in nn.Module._weights_order(own)]
        if len(arrs) > len(keys):
            raise ValueError(
                f"{name}: {len(arrs)} serialized parameters, module "
                f"has {len(keys)}")
        built = _by_name.get(name)
        fix = _WEIGHT_FIX.get(type(built).__name__) \
            if built is not None else None
        if fix is not None:
            arrs = fix(built, arrs)
        for k, arr in zip(keys, arrs):
            want = np.shape(own[k])
            own[k] = np.asarray(arr, np.float32).reshape(want)
        params[name] = own

    for sub in _leaf_modules(tree):
        assign_leaf(sub)
    # BN running statistics: tensor attrs on the BN module
    # (nn/BatchNormalization.scala:323 doLoadModule); descend through
    # TimeDistributed wrappers — their BN rides the 'layer' attr
    def _bn_trees(subtree):
        for leaf in _leaf_modules(subtree):
            if _short_type(leaf["type"]) == "TimeDistributed":
                yield from _bn_trees(leaf["attr"]["layer"])
            else:
                yield leaf

    for sub in _bn_trees(tree):
        if _short_type(sub["type"]) not in (
                "SpatialBatchNormalization", "BatchNormalization"):
            continue
        own_st = state.get(sub["name"])
        if not isinstance(own_st, dict):
            continue
        own_st = dict(own_st)
        for attr_key, st_key in (("runningMean", "running_mean"),
                                 ("runningVar", "running_var")):
            val = sub["attr"].get(attr_key)
            if val is not None and st_key in own_st:
                own_st[st_key] = np.asarray(val, np.float32).reshape(
                    np.shape(own_st[st_key]))
        state[sub["name"]] = own_st
    model.set_params(params, state)
    return model


# --------------------------------------------------------------------- #
# writer (≙ ModulePersister.saveToFile with ProtoStorageType)            #
# --------------------------------------------------------------------- #
def _enc_storage(arr: np.ndarray, sid: int) -> bytes:
    body = enc_int64(1, _DT_FLOAT)
    body += enc_bytes(2, np.ascontiguousarray(arr, "<f4").tobytes())
    body += enc_int64(9, sid)
    return body


def _enc_tensor_msg(arr: np.ndarray, tid: int, sid: int,
                    inline: bool) -> bytes:
    body = enc_int64(1, _DT_FLOAT)
    sizes = b"".join(enc_int64(2, d) for d in arr.shape)
    body += sizes
    body += enc_int64(4, 1)                  # storageOffset (1-based)
    body += enc_int64(5, arr.ndim)
    body += enc_int64(6, arr.size)
    st = _enc_storage(arr, sid) if inline else (
        enc_int64(1, _DT_FLOAT) + enc_int64(9, sid))
    body += enc_bytes(8, st)
    body += enc_int64(9, tid)
    return body


def _attr_entry(key: str, attr_body: bytes) -> bytes:
    return enc_bytes(8, enc_string(1, key) + enc_bytes(2, attr_body))


def _attr_int(v: int) -> bytes:
    return enc_int64(1, _DT_INT32) + enc_int64(3, v & ((1 << 64) - 1))


def _alloc_tensor(arr, counter, global_entries) -> bytes:
    """Allocate tensor+storage ids, stash inline data in global_storage,
    return the non-inline (storage-referencing) tensor message."""
    arr = np.asarray(arr, np.float32)
    counter[0] += 1
    tid = counter[0]
    counter[0] += 1
    sid = counter[0]
    global_entries[str(tid)] = _enc_tensor_msg(arr, tid, sid, inline=True)
    return _enc_tensor_msg(arr, tid, sid, inline=False)


def _attr_tensor(arr, counter, global_entries) -> bytes:
    """Tensor AttrValue; data rides global_storage like parameters do."""
    return enc_int64(1, _DT_TENSOR) + enc_bytes(
        10, _alloc_tensor(arr, counter, global_entries))


def _attr_double(v: float) -> bytes:
    return enc_int64(1, _DT_DOUBLE) + proto.enc_double(6, v)


def _attr_bool(v: bool) -> bytes:
    return enc_int64(1, _DT_BOOL) + enc_int64(8, 1 if v else 0)


def _attr_int_array(vals) -> bytes:
    arr = enc_int64(1, len(list(vals))) + enc_int64(2, _DT_INT32)
    for v in vals:
        arr += enc_int64(3, v & ((1 << 64) - 1))
    return enc_int64(1, _DT_ARRAY) + enc_bytes(15, arr)


def _attr_str_array(vals) -> bytes:
    vals = list(vals)
    arr = enc_int64(1, len(vals)) + enc_int64(2, _DT_STRING)
    for v in vals:
        arr += enc_string(7, v)
    return enc_int64(1, _DT_ARRAY) + enc_bytes(15, arr)


def _module_attrs(mod) -> Dict[str, bytes]:
    if isinstance(mod, nn.Linear):
        return {"inputSize": _attr_int(mod.input_size),
                "outputSize": _attr_int(mod.output_size),
                "withBias": _attr_bool(mod.with_bias)}
    if isinstance(mod, nn.SpatialConvolution):
        kh, kw = mod.kernel
        sh, sw = mod.stride
        ph, pw = mod.pad
        return {"nInputPlane": _attr_int(mod.n_input_plane),
                "nOutputPlane": _attr_int(mod.n_output_plane),
                "kernelW": _attr_int(kw), "kernelH": _attr_int(kh),
                "strideW": _attr_int(sw), "strideH": _attr_int(sh),
                "padW": _attr_int(pw), "padH": _attr_int(ph),
                "nGroup": _attr_int(mod.n_group),
                "withBias": _attr_bool(mod.with_bias)}
    if isinstance(mod, (nn.SpatialMaxPooling, nn.SpatialAveragePooling)):
        kh, kw = mod.kernel
        sh, sw = mod.stride
        ph, pw = mod.pad
        return {"kW": _attr_int(kw), "kH": _attr_int(kh),
                "dW": _attr_int(sw), "dH": _attr_int(sh),
                "padW": _attr_int(pw), "padH": _attr_int(ph)}
    if isinstance(mod, (nn.SpatialBatchNormalization,
                        nn.BatchNormalization)):
        return {"nOutput": _attr_int(mod.n_output),
                "eps": _attr_double(mod.eps),
                "momentum": _attr_double(mod.momentum),
                "affine": _attr_bool(mod.affine)}
    if isinstance(mod, nn.LookupTable):
        return {"nIndex": _attr_int(mod.n_index),
                "nOutput": _attr_int(mod.n_output),
                "paddingValue": _attr_double(mod.padding_value or 0.0),
                # reference "no renorm" sentinel is Double.MaxValue
                "maxNorm": _attr_double(
                    1.7976931348623157e308 if mod.max_norm is None
                    else float(mod.max_norm)),
                "normType": _attr_double(float(mod.norm_type or 2.0)),
                "shouldScaleGradByFreq": _attr_bool(False),
                "maskZero": _attr_bool(bool(getattr(mod, "mask_zero",
                                                    False)))}
    if isinstance(mod, nn.SpatialFullConvolution):
        if getattr(mod, "format", "NCHW") != "NCHW":
            raise ValueError(
                "save_bigdl: SpatialFullConvolution(format='NHWC') has "
                "no reference wire form")
        kh, kw = mod.kernel
        sh, sw = mod.stride
        ph, pw = mod.pad
        ah, aw = mod.adj
        return {"nInputPlane": _attr_int(mod.n_input_plane),
                "nOutputPlane": _attr_int(mod.n_output_plane),
                "kW": _attr_int(kw), "kH": _attr_int(kh),
                "dW": _attr_int(sw), "dH": _attr_int(sh),
                "padW": _attr_int(pw), "padH": _attr_int(ph),
                "adjW": _attr_int(aw), "adjH": _attr_int(ah),
                "nGroup": _attr_int(mod.n_group),
                "noBias": _attr_bool(not mod.with_bias)}
    if isinstance(mod, nn.SpatialDilatedConvolution):
        kh, kw = mod.kernel
        sh, sw = mod.stride
        ph, pw = mod.pad
        dh, dw = mod.dilation
        return {"nInputPlane": _attr_int(mod.n_input_plane),
                "nOutputPlane": _attr_int(mod.n_output_plane),
                "kW": _attr_int(kw), "kH": _attr_int(kh),
                "dW": _attr_int(sw), "dH": _attr_int(sh),
                "padW": _attr_int(pw), "padH": _attr_int(ph),
                "dilationW": _attr_int(dw), "dilationH": _attr_int(dh)}
    if isinstance(mod, nn.TemporalConvolution):
        return {"inputFrameSize": _attr_int(mod.input_frame_size),
                "outputFrameSize": _attr_int(mod.output_frame_size),
                "kernelW": _attr_int(mod.kernel_w),
                "strideW": _attr_int(mod.stride_w)}
    if isinstance(mod, nn.SpatialZeroPadding):
        if getattr(mod, "format", "NCHW") != "NCHW":
            raise ValueError(
                "save_bigdl: SpatialZeroPadding(format='NHWC') has no "
                "reference wire form")
        pl, pr, pt, pb = mod.pads
        return {"padLeft": _attr_int(pl), "padRight": _attr_int(pr),
                "padTop": _attr_int(pt), "padBottom": _attr_int(pb)}
    if isinstance(mod, nn.Padding):
        return {"dim": _attr_int(mod.dim), "pad": _attr_int(mod.pad),
                "nInputDim": _attr_int(mod.n_input_dim),
                "value": _attr_double(mod.value),
                "nIndex": _attr_int(1)}
    if isinstance(mod, nn.Dropout):
        return {"initP": _attr_double(mod.p)}
    if isinstance(mod, nn.Reshape):
        return {"size": _attr_int_array(mod.size)}
    if isinstance(mod, nn.JoinTable):
        return {"dimension": _attr_int(mod.dimension),
                "nInputDims": _attr_int(mod.n_input_dims)}
    if isinstance(mod, nn.Concat):
        return {"dimension": _attr_int(mod.dimension)}
    if isinstance(mod, nn.SpatialCrossMapLRN):
        return {"size": _attr_int(mod.size),
                "alpha": _attr_double(mod.alpha),
                "beta": _attr_double(mod.beta),
                "k": _attr_double(mod.k)}
    if isinstance(mod, nn.PReLU):
        return {"nOutputPlane": _attr_int(mod.n_output_plane)}
    if isinstance(mod, nn.ELU):
        return {"alpha": _attr_double(mod.alpha)}
    if isinstance(mod, nn.Power):
        return {"power": _attr_double(mod.power),
                "scale": _attr_double(mod.scale),
                "shift": _attr_double(mod.shift)}
    if isinstance(mod, nn.View):
        return {"sizes": _attr_int_array(mod.sizes)}
    if isinstance(mod, nn.Clamp) or isinstance(mod, nn.HardTanh):
        # Clamp subclasses HardTanh; reference Clamp ctor is (min: Int,
        # max: Int) while HardTanh takes doubles
        if type(mod).__name__ == "Clamp":
            return {"min": _attr_int(int(mod.min_value)),
                    "max": _attr_int(int(mod.max_value))}
        return {"minValue": _attr_double(mod.min_value),
                "maxValue": _attr_double(mod.max_value)}
    if isinstance(mod, nn.SoftPlus):
        return {"beta": _attr_double(mod.beta)}
    if isinstance(mod, nn.LeakyReLU):
        return {"negval": _attr_double(mod.negval)}
    if isinstance(mod, nn.Threshold):
        return {"th": _attr_double(mod.th), "v": _attr_double(mod.v)}
    if isinstance(mod, nn.MulConstant):
        return {"scalar": _attr_double(mod.scalar)}
    if isinstance(mod, nn.AddConstant):
        return {"constant_scalar": _attr_double(mod.constant)}
    if isinstance(mod, nn.Squeeze):
        if isinstance(mod.dim, (tuple, list)) or mod.batch_mode:
            raise ValueError(
                "save_bigdl: Squeeze with multiple dims or batch_mode "
                "has no reference wire form")
        return {} if mod.dim is None else {"dim": _attr_int(mod.dim)}
    if isinstance(mod, nn.Unsqueeze):
        return {"pos": _attr_int(mod.pos)}
    if isinstance(mod, nn.Select):
        return {"dimension": _attr_int(mod.dim),
                "index": _attr_int(mod.index)}
    if isinstance(mod, nn.Narrow):
        return {"dimension": _attr_int(mod.dimension),
                "offset": _attr_int(mod.offset),
                "length": _attr_int(mod.length)}
    if isinstance(mod, nn.Mean):
        return {"dimension": _attr_int(mod.dimension),
                "nInputDims": _attr_int(getattr(mod, "n_input_dims", -1)),
                "squeeze": _attr_bool(mod.squeeze)}
    if isinstance(mod, (nn.CMul, nn.CAdd)):
        return {"size": _attr_int_array(mod.size)}
    if isinstance(mod, nn.Normalize):
        return {"p": _attr_double(mod.p), "eps": _attr_double(mod.eps)}
    if isinstance(mod, nn.GaussianDropout):
        return {"rate": _attr_double(mod.rate)}
    if isinstance(mod, nn.GaussianNoise):
        return {"stddev": _attr_double(mod.stddev)}
    if isinstance(mod, nn.SelectTable):
        return {"index": _attr_int(mod.index)}
    if isinstance(mod, nn.NarrowTable):
        return {"offset": _attr_int(mod.offset),
                "length": _attr_int(mod.length)}
    if isinstance(mod, nn.Index):
        return {"dimension": _attr_int(mod.dimension)}
    return {}


_TYPE_NAMES = {}
for _short, _fac in _FACTORY.items():
    _TYPE_NAMES[_short] = _NS + _short


def _enc_graph(mod, params, state, counter, global_entries) -> bytes:
    """nn.Graph -> StaticGraph wire form: subModules with preModules
    wiring, inputNames/outputNames attrs, per-node edges maps
    (≙ nn/Graph.scala GraphSerializable doSerializeModule)."""
    body = enc_string(1, mod.name)
    body += enc_string(7, _NS + "StaticGraph")
    # every node the file references: the DFS-from-outputs topo PLUS any
    # declared input node that no output path reaches
    all_nodes = list(mod._topo)
    seen_ids = {id(n) for n in all_nodes}
    for n in mod.input_nodes:
        if id(n) not in seen_ids:
            all_nodes.insert(0, n)
            seen_ids.add(id(n))
    names_of = {}
    used_names = set()
    n_in = 0
    for node in all_nodes:
        if node.module is None:
            nm = f"{mod.name}.input{n_in}"
            n_in += 1
        else:
            nm = node.module.name
        if nm in used_names:
            # the wire format keys nodes by module name; one module
            # instance at two graph positions would collapse on load
            raise NotImplementedError(
                f"save_bigdl: module {nm!r} appears at multiple graph "
                "nodes (shared-module graphs are not supported)")
        used_names.add(nm)
        names_of[id(node)] = nm
    for node in all_nodes:
        nm = names_of[id(node)]
        pres = [names_of[id(p)] for p in node.prev_nodes]
        if node.module is None:
            sub = enc_string(1, nm) + enc_string(7, _NS + "Input")
        else:
            sub = _enc_module(node.module, params, state, counter,
                              global_entries)
        for p in pres:
            sub += enc_string(5, p)      # preModules
        body += enc_bytes(2, sub)
    # per-node edges maps: the reference loader unconditionally reads
    # "<name>_edges" (Graph.scala prepareLoadModule), so they must exist;
    # -1 encodes the default Edge() (no tuple index).  Our own loader
    # wires by preModules and ignores these.
    for node in all_nodes:
        nm = names_of[id(node)]
        inner = enc_string(1, nm)
        for p in (names_of[id(q)] for q in node.prev_nodes):
            av = enc_int64(1, _DT_INT32) \
                + enc_int64(3, (-1) & ((1 << 64) - 1))
            inner = inner + enc_bytes(2, enc_string(1, p)
                                      + enc_bytes(2, av))
        outer = enc_string(1, f"{nm}_edges") + enc_bytes(
            2, enc_string(1, nm)
            + enc_bytes(2, enc_int64(1, _DT_NAME_ATTR_LIST)
                        + enc_bytes(14, inner)))
        body += _attr_entry(f"{nm}_edges",
                            enc_int64(1, _DT_NAME_ATTR_LIST)
                            + enc_bytes(14, outer))
    body += _attr_entry("inputNames", _attr_str_array(
        names_of[id(n)] for n in mod.input_nodes))
    body += _attr_entry("outputNames", _attr_str_array(
        names_of[id(n)] for n in mod.output_nodes))
    return body


def _enc_module(mod, params, state, counter, global_entries) -> bytes:
    from ..nn.graph import Graph as _NNGraph
    if isinstance(mod, _NNGraph):
        return _enc_graph(mod, params, state, counter, global_entries)
    cls = type(mod).__name__
    if cls not in _TYPE_NAMES:
        raise ValueError(f"save_bigdl: unsupported layer {cls}")
    body = enc_string(1, mod.name)
    body += enc_string(7, _TYPE_NAMES[cls])
    if isinstance(mod, nn.TimeDistributed):
        # reference form: the wrapped module rides the 'layer' attr
        # (ctor reflection), NOT subModules; the TD node's flat params
        # mirror the layer's (TimeDistributed.parameters)
        inner = params.get(mod.layer.name, {})
        keys = nn.Module._weights_order(inner)
        if keys:
            body += enc_int64(15, 1)
            for k in keys:
                body += enc_bytes(16, _alloc_tensor(inner[k], counter,
                                                    global_entries))
        layer_bytes = _enc_module(mod.layer, params, state, counter,
                                  global_entries)
        body += _attr_entry("layer", enc_int64(1, _DT_MODULE)
                            + enc_bytes(13, layer_bytes))
        body += _attr_entry("maskZero", _attr_bool(
            bool(getattr(mod, "mask_zero", False))))
        return body
    if mod.children():
        for sub in mod.children():
            body += enc_bytes(2, _enc_module(sub, params, state, counter,
                                             global_entries))
    else:
        own = params.get(mod.name, {})
        keys = nn.Module._weights_order(own)
        if keys:
            body += enc_int64(15, 1)   # hasParameters
            arrs = [own[k] for k in keys]
            unfix = _WEIGHT_UNFIX.get(cls)
            if unfix is not None:
                arrs = unfix(mod, arrs)
            for arr in arrs:
                # data lives once in global_storage; the parameter slot
                # references the storage id (ModuleLoader.scala:119)
                body += enc_bytes(16, _alloc_tensor(arr, counter,
                                                    global_entries))
    for k, v in _module_attrs(mod).items():
        body += _attr_entry(k, v)
    if isinstance(mod, (nn.SpatialBatchNormalization,
                        nn.BatchNormalization)) and not mod.children():
        # nn/BatchNormalization.scala:346 doSerializeModule writes all
        # four tensor attrs; :323 doLoadModule reads them unconditionally
        own_st = (state or {}).get(mod.name) or {}
        rm = np.asarray(own_st.get(
            "running_mean", np.zeros(mod.n_output)), np.float32)
        rv = np.asarray(own_st.get(
            "running_var", np.ones(mod.n_output)), np.float32)
        for key, arr in (("runningMean", rm), ("runningVar", rv),
                         ("saveMean", np.zeros_like(rm)),
                         ("saveStd", np.zeros_like(rm))):
            body += _attr_entry(
                key, _attr_tensor(arr, counter, global_entries))
    return body


def save_bigdl(model, path: str):
    """Write `model` as a reference-format `.bigdl` file
    (≙ Module.saveModule / ModulePersister.saveToFile)."""
    params = model.ensure_initialized()
    state = getattr(model, "_state", None) or {}
    counter = [0]
    global_entries: Dict[str, bytes] = {}
    body = _enc_module(model, params, state, counter, global_entries)
    # top-level global_storage attr: NameAttrList{ name, attr{tid->tensor} }
    nal = enc_string(1, "global_storage")
    for tid, tensor_body in global_entries.items():
        attr_val = enc_int64(1, _DT_TENSOR) + enc_bytes(10, tensor_body)
        nal += enc_bytes(2, enc_string(1, tid) + enc_bytes(2, attr_val))
    gs_attr = enc_int64(1, 14) + enc_bytes(14, nal)   # NAME_ATTR_LIST
    body += _attr_entry("global_storage", gs_attr)
    # tmp + os.replace: same crash-safety contract as serializer.py's
    # _write_payload_zip — never corrupt an existing file mid-write
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(body)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return path
