"""Caffe model import/export (≙ utils/caffe/: CaffeLoader.scala,
CaffePersister.scala, Converter.scala, LayerConverter.scala,
V1LayerConverter.scala).

`load_caffe(prototxt, caffemodel)` parses the deploy prototxt (pure-python
text parser) to build a bigdl_tpu `nn` graph and fills weights from the
binary caffemodel (parsed with utils.proto's wire decoder — no protoc
dependency).  `save_caffe(model, ...)` persists a Sequential subset back to
prototxt + caffemodel that this loader round-trips.

Supported layer types: Input, Convolution (incl. dilation), Deconvolution,
InnerProduct, Pooling (MAX/AVE), ReLU, ELU, PReLU, Sigmoid, TanH,
Softmax(WithLoss), LRN, Dropout, Concat, Eltwise (incl. SUM coefficients),
Flatten, Reshape, BatchNorm(+Scale), Scale, Power, Exp, Log, AbsVal,
Threshold, Tile, Slice, Split, RNN/Recurrent
(≙ utils/caffe/Converter.scala:632 layer dispatch).
"""
from __future__ import annotations

import re
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import proto
from .proto import iter_fields
from .. import nn


# --------------------------------------------------------------------- #
# prototxt text parser                                                  #
# --------------------------------------------------------------------- #
_TOKEN = re.compile(r'("(?:[^"\\]|\\.)*")|([{}:])|([^\s{}:]+)')


def _tokenize(text: str):
    for line in text.splitlines():
        line = line.split("#", 1)[0]
        for m in _TOKEN.finditer(line):
            yield m.group(0)


class PrototxtMessage(dict):
    """Repeated fields accumulate into lists."""

    def add(self, key, value):
        if key in self:
            cur = self[key]
            if isinstance(cur, list):
                cur.append(value)
            else:
                self[key] = [cur, value]
        else:
            self[key] = value

    def get_list(self, key):
        v = self.get(key)
        if v is None:
            return []
        return v if isinstance(v, list) else [v]


def parse_prototxt(text: str) -> PrototxtMessage:
    tokens = list(_tokenize(text))
    pos = 0

    def parse_value(tok):
        if tok.startswith('"'):
            return tok[1:-1]
        if tok in ("true", "false"):
            return tok == "true"
        try:
            return int(tok)
        except ValueError:
            try:
                return float(tok)
            except ValueError:
                return tok  # enum

    def parse_block():
        nonlocal pos
        msg = PrototxtMessage()
        while pos < len(tokens):
            tok = tokens[pos]
            if tok == "}":
                pos += 1
                return msg
            key = tok
            pos += 1
            if pos < len(tokens) and tokens[pos] == ":":
                pos += 1
                msg.add(key, parse_value(tokens[pos]))
                pos += 1
            elif pos < len(tokens) and tokens[pos] == "{":
                pos += 1
                msg.add(key, parse_block())
            else:
                raise ValueError(f"prototxt parse error near {key!r}")
        return msg

    return parse_block()


# --------------------------------------------------------------------- #
# caffemodel binary parser (weights)                                    #
# --------------------------------------------------------------------- #
def _decode_blob(buf: bytes) -> np.ndarray:
    shape: Tuple[int, ...] = ()
    data: List[float] = []
    legacy = {}
    for f, w, v in iter_fields(buf):
        if f == 7 and w == 2:  # shape: BlobShape{dim=1 packed int64}
            dims = []
            for f2, w2, v2 in iter_fields(v):
                if f2 == 1 and w2 == 2:
                    i = 0
                    while i < len(v2):
                        n, i = proto._read_varint(v2, i)
                        dims.append(n)
                elif f2 == 1 and w2 == 0:
                    dims.append(v2)
            shape = tuple(dims)
        elif f == 5:  # data (packed float)
            if w == 2:
                data.append(np.frombuffer(v, np.float32))
            else:
                data.append(np.asarray([v], np.float32))
        elif f in (1, 2, 3, 4) and w == 0:  # legacy num/channels/h/w
            legacy[f] = v
    arr = (np.concatenate([np.atleast_1d(d) for d in data])
           if data else np.zeros(0, np.float32)).astype(np.float32)
    if not shape and legacy:
        shape = tuple(legacy.get(i, 1) for i in (1, 2, 3, 4))
    if shape and arr.size == int(np.prod(shape)):
        arr = arr.reshape(shape)
    return arr


def parse_caffemodel(data: bytes) -> Dict[str, List[np.ndarray]]:
    """layer name -> blobs (weights, bias, ...); merges V1 `layers` (field 2)
    and V2 `layer` (field 100)."""
    blobs: Dict[str, List[np.ndarray]] = {}
    for f, w, v in iter_fields(data):
        if f == 100 and w == 2:  # LayerParameter
            name = None
            layer_blobs = []
            for f2, w2, v2 in iter_fields(v):
                if f2 == 1 and w2 == 2:
                    name = v2.decode("utf-8")
                elif f2 == 7 and w2 == 2:
                    layer_blobs.append(_decode_blob(v2))
            if name and layer_blobs:
                blobs[name] = layer_blobs
        elif f == 2 and w == 2:  # V1LayerParameter
            name = None
            layer_blobs = []
            for f2, w2, v2 in iter_fields(v):
                if f2 == 4 and w2 == 2:
                    name = v2.decode("utf-8")
                elif f2 == 6 and w2 == 2:
                    layer_blobs.append(_decode_blob(v2))
            if name and layer_blobs:
                blobs[name] = layer_blobs
    return blobs


# --------------------------------------------------------------------- #
# layer conversion (≙ LayerConverter.scala)                             #
# --------------------------------------------------------------------- #
def _ks(param, base, h_key, w_key):
    """kernel/stride/pad resolution: *_h/*_w override the repeated field."""
    h = param.get(h_key)
    w = param.get(w_key)
    if h is not None or w is not None:
        return int(h or 0), int(w or 0)
    vals = param.get_list(base) if isinstance(param, PrototxtMessage) else []
    if not vals:
        vals = [param.get(base)] if param.get(base) is not None else []
    if not vals:
        return None
    if len(vals) == 1:
        return int(vals[0]), int(vals[0])
    return int(vals[0]), int(vals[1])


def _convert_layer(ltype: str, lp: PrototxtMessage, in_channels: int,
                   blobs: Optional[List[np.ndarray]] = None):
    """Returns (module, out_channels) or None for pass-through.

    ``blobs`` (the layer's caffemodel arrays, when available) resolve
    shapes the prototxt alone cannot, the way the reference reads them
    from weight blobs (utils/caffe/LayerConverter.scala:39
    fromCaffeConvolution nInputPlane, :190 fromCaffePreLU nOutPlane)."""
    t = ltype.lower()
    if t in ("convolution", "deconvolution"):
        cp = lp.get("convolution_param", PrototxtMessage())
        nout = int(cp.get("num_output"))
        kh, kw = _ks(cp, "kernel_size", "kernel_h", "kernel_w")
        sh, sw = _ks(cp, "stride", "stride_h", "stride_w") or (1, 1)
        ph, pw = _ks(cp, "pad", "pad_h", "pad_w") or (0, 0)
        group = int(cp.get("group", 1))
        bias = bool(cp.get("bias_term", True))
        dil = [int(d) for d in cp.get_list("dilation")]
        # caffe repeated spatial params are (h, w); one entry = square
        dh_, dw_ = (1, 1) if not dil else \
            (dil[0], dil[0]) if len(dil) == 1 else (dil[0], dil[1])
        if in_channels is None and blobs:
            # weight blob: (out, in/group, kh, kw) for conv,
            # (in, out/group, kh, kw) for deconv
            in_channels = (blobs[0].shape[0] if t == "deconvolution"
                           else blobs[0].shape[1] * group)
        if t == "deconvolution":
            if (dh_, dw_) != (1, 1):
                raise ValueError("dilated Deconvolution is not supported")
            mod = nn.SpatialFullConvolution(
                in_channels, nout, kw, kh, sw, sh, pw, ph,
                n_group=group, no_bias=not bias)
        elif (dh_, dw_) != (1, 1):
            if group != 1:
                raise ValueError(
                    "grouped dilated Convolution is not supported "
                    f"(layer has dilation={(dh_, dw_)}, group={group})")
            mod = nn.SpatialDilatedConvolution(
                in_channels, nout, kw, kh, sw, sh, pw, ph,
                dw_, dh_, with_bias=bias)
        else:
            mod = nn.SpatialConvolution(in_channels, nout, kw, kh, sw, sh,
                                        pw, ph, n_group=group,
                                        with_bias=bias)
        return mod, nout
    if t == "innerproduct" or t == "inner_product":
        ip = lp.get("inner_product_param", PrototxtMessage())
        nout = int(ip.get("num_output"))
        bias = bool(ip.get("bias_term", True))
        if in_channels is None and blobs:
            in_channels = blobs[0].shape[-1]
        return nn.Linear(in_channels, nout, with_bias=bias), nout
    if t == "pooling":
        pp = lp.get("pooling_param", PrototxtMessage())
        kh, kw = _ks(pp, "kernel_size", "kernel_h", "kernel_w") or (2, 2)
        sh, sw = _ks(pp, "stride", "stride_h", "stride_w") or (kh, kw)
        ph, pw = _ks(pp, "pad", "pad_h", "pad_w") or (0, 0)
        pool = str(pp.get("pool", "MAX")).upper()
        if pool in ("MAX", "0"):
            mod = nn.SpatialMaxPooling(kw, kh, sw, sh, pw, ph,
                                       ceil_mode=True)
        else:
            mod = nn.SpatialAveragePooling(kw, kh, sw, sh, pw, ph,
                                           count_include_pad=False,
                                           ceil_mode=True)
        return mod, in_channels
    if t == "relu":
        return nn.ReLU(), in_channels
    if t == "sigmoid":
        return nn.Sigmoid(), in_channels
    if t == "tanh":
        return nn.Tanh(), in_channels
    if t in ("softmax", "softmaxwithloss"):
        # caffe softmax_param.axis defaults to 1 (channels); pass it
        # explicitly — nn.SoftMax's 3D default (unbatched CHW, axis 0)
        # would otherwise normalize sequence batches over N
        sp = lp.get("softmax_param", PrototxtMessage())
        return nn.SoftMax(axis=int(sp.get("axis", 1))), in_channels
    if t == "lrn":
        lrn = lp.get("lrn_param", PrototxtMessage())
        return nn.SpatialCrossMapLRN(
            int(lrn.get("local_size", 5)), float(lrn.get("alpha", 1.0)),
            float(lrn.get("beta", 0.75)), float(lrn.get("k", 1.0))), \
            in_channels
    if t == "dropout":
        dp = lp.get("dropout_param", PrototxtMessage())
        return nn.Dropout(float(dp.get("dropout_ratio", 0.5))), in_channels
    if t == "batchnorm":
        bp = lp.get("batch_norm_param", PrototxtMessage())
        return nn.SpatialBatchNormalization(
            in_channels, eps=float(bp.get("eps", 1e-5)),
            affine=False), in_channels
    if t == "scale":
        sp = lp.get("scale_param", PrototxtMessage())
        if bool(sp.get("bias_term", False)):
            mod = nn.Sequential(nn.CMul((1, in_channels, 1, 1)),
                                nn.CAdd((1, in_channels, 1, 1)))
        else:
            mod = nn.CMul((1, in_channels, 1, 1))
        return mod, in_channels
    if t == "elu":
        ep = lp.get("elu_param", PrototxtMessage())
        return nn.ELU(float(ep.get("alpha", 1.0))), in_channels
    if t == "prelu":
        n = blobs[0].reshape(-1).shape[0] if blobs else (in_channels or 0)
        return nn.PReLU(n), in_channels
    if t == "power":
        pw = lp.get("power_param", PrototxtMessage())
        return nn.Power(float(pw.get("power", 1.0)),
                        float(pw.get("scale", 1.0)),
                        float(pw.get("shift", 0.0))), in_channels
    if t == "exp":
        return nn.Exp(), in_channels
    if t == "log":
        return nn.Log(), in_channels
    if t == "absval":
        return nn.Abs(), in_channels
    if t == "threshold":
        tp = lp.get("threshold_param", PrototxtMessage())
        return nn.BinaryThreshold(float(tp.get("threshold", 1e-6))), \
            in_channels
    if t == "tile":
        tp = lp.get("tile_param", PrototxtMessage())
        axis = int(tp.get("axis", 1))
        tiles = int(tp.get("tiles", 1))
        # caffe axis is 0-based incl. batch; Tile dims are Torch 1-based
        return nn.Tile(axis + 1, tiles), in_channels
    if t == "reshape":
        rp = lp.get("reshape_param", PrototxtMessage())
        shp = rp.get("shape", PrototxtMessage())
        if isinstance(shp, list):
            shp = shp[0]
        dims = [int(d) for d in shp.get_list("dim")]
        return nn.InferReshape(dims), None
    if t in ("rnn", "recurrent"):
        # the reference emits a bare (cell-less) Recurrent here
        # (Converter.scala:200); we wire caffe's recurrent_param num_output
        # into an actual RnnCell so the imported layer computes
        rp = lp.get("recurrent_param", PrototxtMessage())
        nout = int(rp.get("num_output", in_channels or 0))
        return nn.Recurrent(nn.RnnCell(in_channels, nout)), nout
    raise ValueError(f"unsupported caffe layer type {ltype!r}")


from ..nn.module import Module as _Module


class CaffeFlatten(_Module):
    """Caffe's implicit flatten before InnerProduct: (N, ...) -> (N, -1)."""

    def apply(self, params, x, ctx):
        return x.reshape(x.shape[0], -1)


def _convert(ltype, lp, in_ch, blobs=None):
    if ltype.lower() == "flatten":
        return CaffeFlatten(), None
    return _convert_layer(ltype, lp, in_ch, blobs)


def _out_spatial(mod, spatial):
    """Track (h, w) through a converted module for the implicit flatten
    before InnerProduct."""
    if spatial is None or not hasattr(mod, "kernel"):
        return spatial
    kh, kw = mod.kernel
    sh, sw = mod.stride
    ph, pw = mod.pad if hasattr(mod, "pad") else (0, 0)
    if isinstance(mod, nn.SpatialFullConvolution):
        ah, aw = mod.adj
        return ((spatial[0] - 1) * sh - 2 * ph + kh + ah,
                (spatial[1] - 1) * sw - 2 * pw + kw + aw)
    ceil = bool(getattr(mod, "ceil_mode", False))

    def _osz(i, k, s, p):
        num = i + 2 * p - k
        return (-(-num // s) if ceil else num // s) + 1
    if isinstance(mod, nn.SpatialDilatedConvolution):
        dh, dw = mod.dilation
        kh, kw = dh * (kh - 1) + 1, dw * (kw - 1) + 1
    return (_osz(spatial[0], kh, sh, ph), _osz(spatial[1], kw, sw, pw))


# --------------------------------------------------------------------- #
# loader                                                                #
# --------------------------------------------------------------------- #
class CaffeLoader:
    """≙ utils/caffe/CaffeLoader.scala (sequential deploy nets)."""

    def __init__(self, prototxt_path: str, model_path: Optional[str] = None,
                 match_all: bool = True):
        with open(prototxt_path) as f:
            self.net = parse_prototxt(f.read())
        self.blobs: Dict[str, List[np.ndarray]] = {}
        if model_path:
            with open(model_path, "rb") as f:
                self.blobs = parse_caffemodel(f.read())
        self.match_all = match_all

    def _input_shape(self):
        # input_shape { dim: ... } or layer type Input
        ish = self.net.get("input_shape")
        if ish is not None:
            if isinstance(ish, list):
                ish = ish[0]
            return [int(d) for d in ish.get_list("dim")]
        if "input_dim" in self.net:
            return [int(d) for d in self.net.get_list("input_dim")]
        for lp in self.net.get_list("layer"):
            if str(lp.get("type", "")).lower() == "input":
                shp = lp.get("input_param", PrototxtMessage()).get("shape")
                if isinstance(shp, list):
                    shp = shp[0]
                if shp is not None:
                    return [int(d) for d in shp.get_list("dim")]
        return None

    def _layer_list(self):
        return self.net.get_list("layer") + self.net.get_list("layers")

    def _is_chain(self):
        """True when bottom/top wiring is absent or a pure chain — the
        Sequential fast path; anything else (multi-bottom Concat/Eltwise,
        fan-out) builds a Graph like the reference CaffeLoader DAG."""
        prev_top = None
        for lp in self._layer_list():
            if str(lp.get("type", "")).lower() in ("input", "data"):
                prev_top = lp.get_list("top")[0] if lp.get_list("top") \
                    else prev_top
                continue
            bottoms = lp.get_list("bottom")
            tops = lp.get_list("top")
            if len(bottoms) > 1 or len(tops) > 1:
                return False
            if bottoms and prev_top is not None and bottoms[0] != prev_top:
                return False
            if tops:
                prev_top = tops[0]
        return True

    def create_module(self):
        if not self._is_chain():
            return self._create_graph()
        return self._create_sequential()

    def _create_graph(self):
        """DAG deploy nets (GoogLeNet-style): blobs are wired by bottom/top
        names into an nn.Graph (≙ CaffeLoader.scala's directed graph)."""
        from ..nn.graph import Graph, Input, Node

        shape = self._input_shape()
        in_name = str(self.net.get("input", "data"))
        in_ch0 = None
        if shape and len(shape) >= 2:
            # rank-3 inputs are (N, T, features) sequences: the feature dim
            # (what Linear/RNN consume) is last; rank-4 are NCHW images
            in_ch0 = shape[-1] if len(shape) == 3 else shape[1]
        for lp in self._layer_list():
            if str(lp.get("type", "")).lower() in ("input", "data") \
                    and lp.get_list("top"):
                in_name = lp.get_list("top")[0]
        # blob name -> (node, channels, spatial)
        input_node = Input()
        blobs_env = {in_name: (input_node, in_ch0,
                               tuple(shape[2:]) if shape and len(shape) == 4
                               else None)}
        weight_assign = []
        for lp in self._layer_list():
            ltype = str(lp.get("type", ""))
            t = ltype.lower()
            if t in ("input", "data"):
                continue
            name = lp.get("name", f"layer{len(weight_assign)}")
            bottoms = lp.get_list("bottom")
            tops = lp.get_list("top") or [name]
            ins = [blobs_env[b] for b in bottoms]
            if t == "concat":
                cp = lp.get("concat_param", PrototxtMessage())
                axis = int(cp.get("axis", cp.get("concat_dim", 1)))
                mod = nn.JoinTable(axis + 1)
                out_ch = sum(c for _, c, _ in ins) if axis == 1 else ins[0][1]
                spatial = ins[0][2]
            elif t == "eltwise":
                ep = lp.get("eltwise_param", PrototxtMessage())
                op = str(ep.get("operation", "SUM")).upper()
                coeffs = [float(c) for c in ep.get_list("coeff")]
                if op in ("SUM", "1") and coeffs and coeffs != [1.0] * len(coeffs):
                    if len(coeffs) != len(ins):
                        raise ValueError(
                            f"Eltwise {name!r}: {len(coeffs)} coeffs for "
                            f"{len(ins)} bottoms (caffe requires equal "
                            "counts)")
                    if coeffs == [1.0, -1.0]:
                        mod = nn.CSubTable()
                    else:
                        # scale each input by its coefficient, then sum
                        # (≙ Converter.scala fromCaffeEltwise MulConstant
                        # composition)
                        ins = [(Node(nn.MulConstant(c), [n]), ch, sp)
                               for c, (n, ch, sp) in zip(coeffs, ins)]
                        mod = nn.CAddTable()
                else:
                    mod = {"SUM": nn.CAddTable, "1": nn.CAddTable,
                           "PROD": nn.CMulTable, "0": nn.CMulTable,
                           "MAX": nn.CMaxTable, "2": nn.CMaxTable}[op]()
                out_ch, spatial = ins[0][1], ins[0][2]
            elif t == "slice":
                # caffe Slice: chunk the axis across the tops (equal split
                # or slice_point boundaries), dims kept — per-top Narrow
                # nodes (the reference's SplitTable mapping drops the axis)
                sp_ = lp.get("slice_param", PrototxtMessage())
                axis = int(sp_.get("axis", sp_.get("slice_dim", 1)))
                points = [int(p) for p in sp_.get_list("slice_point")]
                in_node, in_ch, spatial = ins[0]
                if not points:
                    if axis == 1 and in_ch:
                        total = in_ch
                    else:
                        raise ValueError(
                            f"Slice {name!r}: need slice_point or known "
                            "channel count on axis 1")
                    step = total // len(tops)
                    points = [step * i for i in range(1, len(tops))]
                    bounds = [0] + points + [total]
                else:
                    bounds = [0] + points + [None]
                for i, top in enumerate(tops):
                    start, end = bounds[i], bounds[i + 1]
                    length = (end - start) if end is not None else -1
                    nar = nn.Narrow(axis + 1, start + 1, length)
                    nar.set_name(f"{name}.{i}" if len(tops) > 1 else name)
                    ch, sp_out = in_ch, spatial
                    if axis == 1:
                        # the open-ended last chunk spans in_ch - start
                        ch = length if length > 0 else (
                            in_ch - start if in_ch else in_ch)
                    elif axis in (2, 3) and spatial is not None:
                        full = spatial[axis - 2]
                        seg = length if length > 0 else full - start
                        sp_out = (seg, spatial[1]) if axis == 2 \
                            else (spatial[0], seg)
                    blobs_env[top] = (Node(nar, [in_node]), ch, sp_out)
                continue
            elif t == "split":
                for top in tops:
                    blobs_env[top] = ins[0]
                continue
            else:
                in_ch, spatial = ins[0][1], ins[0][2]
                if t in ("innerproduct", "inner_product") \
                        and spatial is not None:
                    flat = CaffeFlatten()
                    node = Node(flat, [ins[0][0]])
                    ins = [(node, in_ch * int(np.prod(spatial)), None)]
                    in_ch, spatial = ins[0][1], None
                mod, out_ch = _convert(ltype, lp, in_ch,
                                       self.blobs.get(name))
                if out_ch is None:
                    out_ch = in_ch
                spatial = _out_spatial(mod, spatial)
            mod.set_name(name)
            node = Node(mod, [n for n, _, _ in ins])
            out_entry = (node, out_ch, spatial)
            for top in tops:
                blobs_env[top] = out_entry
            weight_assign.append((name, mod))

        # outputs: blobs produced but never consumed
        consumed = set()
        for lp in self._layer_list():
            for b in lp.get_list("bottom"):
                consumed.add(b)
        # in-place layers overwrite their blob entry, so take the final
        # mapping's unconsumed tops (preserving prototxt order)
        out_nodes, seen = [], set()
        for blob, (node, _, _) in blobs_env.items():
            if blob not in consumed and node.module is not None \
                    and id(node) not in seen:
                out_nodes.append(node)
                seen.add(id(node))
        if not out_nodes:
            # every blob was consumed (net ends with an in-place layer,
            # top == bottom): the last layer's node is the output
            last = weight_assign[-1][1] if weight_assign else None
            for node, _, _ in blobs_env.values():
                if node.module is last and last is not None:
                    out_nodes = [node]
                    break
            if not out_nodes:
                raise ValueError(
                    "could not determine the DAG output blob (all blobs "
                    "consumed and no final layer found)")
        model = Graph([input_node],
                      out_nodes if len(out_nodes) > 1 else [out_nodes[-1]])
        params, state = model.init_params(0)
        for name, mod in weight_assign:
            if name in self.blobs:
                self._assign_blobs(mod, self.blobs[name], params, state)
        model.set_params(params, state)
        return model

    def _create_sequential(self):
        """Build a Sequential following the prototxt layer order, loading
        weights by layer name (≙ CaffeLoader.createCaffeModel)."""
        shape = self._input_shape()
        # rank-3 = (N, T, features) sequences (feature dim last); rank-4 NCHW
        in_ch = None
        if shape and len(shape) >= 2:
            in_ch = shape[-1] if len(shape) == 3 else shape[1]
        spatial = shape[2:] if shape and len(shape) == 4 else None
        model = nn.Sequential()
        weight_assign = []
        for lp in self.net.get_list("layer") + self.net.get_list("layers"):
            ltype = str(lp.get("type", ""))
            if ltype.lower() in ("input", "data"):
                continue
            name = lp.get("name", f"layer{len(model)}")
            if ltype.lower() in ("innerproduct", "inner_product") \
                    and spatial is not None:
                # caffe flattens implicitly before IP layers
                model.add(CaffeFlatten())
                in_ch = in_ch * int(np.prod(spatial))
                spatial = None
            mod, out_ch = _convert(ltype, lp, in_ch, self.blobs.get(name))
            mod.set_name(name)
            model.add(mod)
            if out_ch is not None:
                in_ch = out_ch
            spatial = _out_spatial(mod, spatial)
            weight_assign.append((name, mod))
        params, state = model.init_params(0)
        for name, mod in weight_assign:
            if name not in self.blobs:
                continue
            self._assign_blobs(mod, self.blobs[name], params, state)
        model.set_params(params, state)
        return model

    @staticmethod
    def _assign_blobs(mod, blobs, params, state):
        """Fill one converted module from a caffe layer's blobs
        (≙ CaffeLoader.copyParameter).  BatchNorm stores accumulated
        (mean_sum, var_sum, scale_factor) — the running stats are
        blobs[0..1] / scale_factor and live in the module STATE, not
        params.  Scale stores (gamma[, beta]) -> CMul weight / CAdd bias."""
        if isinstance(mod, nn.BatchNormalization):
            sf = float(blobs[2].reshape(-1)[0]) if len(blobs) >= 3 else 1.0
            factor = 0.0 if sf == 0.0 else 1.0 / sf
            st = dict(state.get(mod.name, {}))
            if len(blobs) >= 1:
                st["running_mean"] = (blobs[0].reshape(-1) * factor) \
                    .astype(np.float32)
            if len(blobs) >= 2:
                st["running_var"] = (blobs[1].reshape(-1) * factor) \
                    .astype(np.float32)
            state[mod.name] = st
            if mod.affine and len(blobs) >= 5:
                params[mod.name] = {
                    "weight": blobs[3].reshape(-1).astype(np.float32),
                    "bias": blobs[4].reshape(-1).astype(np.float32)}
            return
        if isinstance(mod, nn.Recurrent):
            # caffe RNNLayer blobs: W_xh (hid, in), B_h (hid,),
            # W_hh (hid, hid); our RnnCell computes x @ weight_i with
            # weight_i (in, hid) — transpose on the way in
            cell = mod.cell
            p = dict(params.get(cell.name, {}))
            if len(blobs) >= 1 and "weight_i" in p:
                p["weight_i"] = np.ascontiguousarray(
                    blobs[0].reshape(np.shape(p["weight_i"])[::-1]).T) \
                    .astype(np.float32)
            if len(blobs) >= 2 and "bias" in p:
                p["bias"] = blobs[1].reshape(
                    np.shape(p["bias"])).astype(np.float32)
            if len(blobs) >= 3 and "weight_h" in p:
                p["weight_h"] = np.ascontiguousarray(
                    blobs[2].reshape(np.shape(p["weight_h"])[::-1]).T) \
                    .astype(np.float32)
            params[cell.name] = p
            return
        if isinstance(mod, nn.Sequential):  # Scale with bias_term
            cmul, cadd = mod.children()
            if len(blobs) >= 1:
                params[cmul.name] = {"weight": blobs[0].reshape(
                    np.shape(params[cmul.name]["weight"])).astype(np.float32)}
            if len(blobs) >= 2:
                params[cadd.name] = {"bias": blobs[1].reshape(
                    np.shape(params[cadd.name]["bias"])).astype(np.float32)}
            return
        p = dict(params.get(mod.name, {}))
        if "weight" in p and len(blobs) >= 1:
            p["weight"] = blobs[0].reshape(np.shape(p["weight"])) \
                .astype(np.float32)
        if "bias" in p and len(blobs) >= 2:
            p["bias"] = blobs[1].reshape(np.shape(p["bias"])) \
                .astype(np.float32)
        params[mod.name] = p

    @staticmethod
    def load(prototxt_path: str, model_path: Optional[str] = None):
        return CaffeLoader(prototxt_path, model_path).create_module()


def load_caffe(prototxt_path: str, model_path: Optional[str] = None):
    """≙ Module.loadCaffeModel."""
    return CaffeLoader.load(prototxt_path, model_path)


# --------------------------------------------------------------------- #
# persister                                                             #
# --------------------------------------------------------------------- #
def _blob_bytes(arr: np.ndarray) -> bytes:
    shape_body = b""
    for d in arr.shape:
        shape_body += proto.enc_int64(1, d)
    return (proto.enc_bytes(7, shape_body)
            + proto.enc_bytes(5, np.ascontiguousarray(
                arr, np.float32).tobytes()))


def save_caffe(model, prototxt_path: str, model_path: str,
               input_shape=None):
    """Persist a Sequential subset (≙ utils/caffe/CaffePersister.scala):
    writes a deploy prototxt and a V2 caffemodel with the weights."""
    params = model.ensure_initialized()
    lines = ['name: "bigdl_tpu"']
    if input_shape is not None:
        dims = "\n".join(f"  dim: {d}" for d in input_shape)
        lines.append(f"input: \"data\"\ninput_shape {{\n{dims}\n}}")
    body = b""
    for mod in model.children():
        name = mod.name
        p = params.get(name, {})
        lp = proto.enc_string(1, name)
        if isinstance(mod, nn.SpatialConvolution):
            kh, kw = mod.kernel
            sh, sw = mod.stride
            ph, pw = mod.pad
            lp += proto.enc_string(2, "Convolution")
            cp = proto.enc_int64(1, mod.n_output_plane)
            cp += proto.enc_int64(4, kh) if kh == kw else (
                proto.enc_int64(11, kh) + proto.enc_int64(12, kw))
            cp += proto.enc_int64(6, sh) if sh == sw else (
                proto.enc_int64(13, sh) + proto.enc_int64(14, sw))
            cp += proto.enc_int64(3, max(ph, 0))
            cp += proto.enc_int64(5, mod.n_group)
            lp += proto.enc_bytes(106, cp)
            lines.append(
                f'layer {{ name: "{name}" type: "Convolution" '
                f'convolution_param {{ num_output: {mod.n_output_plane} '
                f'kernel_h: {kh} kernel_w: {kw} stride_h: {sh} '
                f'stride_w: {sw} pad_h: {max(ph,0)} pad_w: {max(pw,0)} '
                f'group: {mod.n_group} '
                f'bias_term: {"true" if mod.with_bias else "false"} }} }}')
            lp += proto.enc_bytes(7, _blob_bytes(np.asarray(p["weight"])))
            if mod.with_bias:
                lp += proto.enc_bytes(7, _blob_bytes(np.asarray(p["bias"])))
        elif isinstance(mod, nn.Linear):
            lp += proto.enc_string(2, "InnerProduct")
            nout = np.asarray(p["weight"]).shape[0]
            lp += proto.enc_bytes(117, proto.enc_int64(1, nout))
            lines.append(
                f'layer {{ name: "{name}" type: "InnerProduct" '
                f'inner_product_param {{ num_output: {nout} }} }}')
            lp += proto.enc_bytes(7, _blob_bytes(np.asarray(p["weight"])))
            if "bias" in p:
                lp += proto.enc_bytes(7, _blob_bytes(np.asarray(p["bias"])))
        elif isinstance(mod, nn.ReLU):
            lp += proto.enc_string(2, "ReLU")
            lines.append(f'layer {{ name: "{name}" type: "ReLU" }}')
        elif isinstance(mod, nn.Sigmoid):
            lp += proto.enc_string(2, "Sigmoid")
            lines.append(f'layer {{ name: "{name}" type: "Sigmoid" }}')
        elif isinstance(mod, nn.Tanh):
            lp += proto.enc_string(2, "TanH")
            lines.append(f'layer {{ name: "{name}" type: "TanH" }}')
        elif isinstance(mod, nn.SoftMax):
            lp += proto.enc_string(2, "Softmax")
            lines.append(f'layer {{ name: "{name}" type: "Softmax" }}')
        elif isinstance(mod, CaffeFlatten):
            lp += proto.enc_string(2, "Flatten")
            lines.append(f'layer {{ name: "{name}" type: "Flatten" }}')
        elif isinstance(mod, nn.SpatialMaxPooling):
            kh, kw = mod.kernel
            sh, sw = mod.stride
            lp += proto.enc_string(2, "Pooling")
            lines.append(
                f'layer {{ name: "{name}" type: "Pooling" pooling_param '
                f'{{ pool: MAX kernel_h: {kh} kernel_w: {kw} '
                f'stride_h: {sh} stride_w: {sw} }} }}')
        else:
            raise ValueError(
                f"save_caffe: unsupported layer {type(mod).__name__}")
        body += proto.enc_bytes(100, lp)
    with open(prototxt_path, "w") as f:
        f.write("\n".join(lines) + "\n")
    with open(model_path, "wb") as f:
        f.write(proto.enc_string(1, "bigdl_tpu") + body)
