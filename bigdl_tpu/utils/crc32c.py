"""CRC32C (Castagnoli) with the TFRecord masking, pure python.

≙ the reference's use of org.tensorflow hadoop CRC32C for tfevents/TFRecord
framing.  `bigdl_tpu.native` provides a C++ fast path; this module is the
always-available fallback and the definition of correctness.
"""
from __future__ import annotations

_POLY = 0x82F63B78
_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ _POLY if _c & 1 else _c >> 1
    _TABLE.append(_c)

_MASK_DELTA = 0xA282EAD8


def crc32c(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def mask(crc: int) -> int:
    """TFRecord 'masked' rotation of a raw crc — exposed separately so
    streaming consumers (checkpoint shard hashing) can chain ``crc32c``
    over chunks and mask once at the end."""
    return ((crc >> 15) | (crc << 17)) + _MASK_DELTA & 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    """TFRecord 'masked' crc (≙ tensorflow/core/lib/hash/crc32c.h Mask)."""
    return mask(crc32c(data))


def unmask(masked: int) -> int:
    rot = (masked - _MASK_DELTA) & 0xFFFFFFFF
    return ((rot >> 17) | (rot << 15)) & 0xFFFFFFFF
