"""TFRecord + fixed-length record IO (≙ utils/tf/TFRecordWriter.scala,
TFRecordIterator.scala, FixedLengthRecordReader.scala).

Record framing: u64 little-endian length | masked crc32c(length) | payload |
masked crc32c(payload).  CRC verification on read is optional (the
reference's iterator skips it too) but on by default here.
`bigdl_tpu.native` supplies a C++ crc32c fast path when built.
"""
from __future__ import annotations

import os
import struct
from typing import Iterator, List, Optional

from .crc32c import masked_crc32c


class TFRecordWriter:
    """≙ utils/tf/TFRecordWriter.scala."""

    def __init__(self, path_or_file):
        self._own = isinstance(path_or_file, (str, os.PathLike))
        self._f = open(path_or_file, "wb") if self._own else path_or_file

    def write(self, record: bytes):
        header = struct.pack("<Q", len(record))
        self._f.write(header)
        self._f.write(struct.pack("<I", masked_crc32c(header)))
        self._f.write(record)
        self._f.write(struct.pack("<I", masked_crc32c(record)))

    def flush(self):
        self._f.flush()

    def close(self):
        if self._own:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class TFRecordIterator:
    """≙ utils/tf/TFRecordIterator.scala."""

    def __init__(self, path_or_file, check_crc: bool = True):
        self._own = isinstance(path_or_file, (str, os.PathLike))
        self._f = open(path_or_file, "rb") if self._own else path_or_file
        self.check_crc = check_crc

    def __iter__(self) -> Iterator[bytes]:
        return self

    def __next__(self) -> bytes:
        header = self._f.read(8)
        if len(header) < 8:
            if self._own:
                self._f.close()
            raise StopIteration
        (length,) = struct.unpack("<Q", header)
        (len_crc,) = struct.unpack("<I", self._f.read(4))
        payload = self._f.read(length)
        (pay_crc,) = struct.unpack("<I", self._f.read(4))
        if self.check_crc:
            if len_crc != masked_crc32c(header):
                raise IOError("TFRecord length crc mismatch")
            if pay_crc != masked_crc32c(payload):
                raise IOError("TFRecord payload crc mismatch")
        return payload


def read_tfrecords(path: str, check_crc: bool = True) -> List[bytes]:
    return list(TFRecordIterator(path, check_crc))


def write_tfrecords(path: str, records) -> None:
    with TFRecordWriter(path) as w:
        for r in records:
            w.write(r)


class FixedLengthRecordReader:
    """Fixed-size binary records with optional header/footer bytes per file
    (≙ utils/tf/FixedLengthRecordReader.scala; CIFAR-10 binary layout)."""

    def __init__(self, path: str, record_bytes: int, header_bytes: int = 0,
                 footer_bytes: int = 0):
        self.path = path
        self.record_bytes = record_bytes
        self.header_bytes = header_bytes
        self.footer_bytes = footer_bytes

    def __iter__(self) -> Iterator[bytes]:
        size = os.path.getsize(self.path)
        with open(self.path, "rb") as f:
            f.seek(self.header_bytes)
            end = size - self.footer_bytes
            while f.tell() + self.record_bytes <= end:
                yield f.read(self.record_bytes)
