"""TFRecord + fixed-length record IO (≙ utils/tf/TFRecordWriter.scala,
TFRecordIterator.scala, FixedLengthRecordReader.scala).

Record framing: u64 little-endian length | masked crc32c(length) | payload |
masked crc32c(payload).  CRC verification on read is optional (the
reference's iterator skips it too) but on by default here.
`bigdl_tpu.native` supplies a C++ crc32c fast path when built.
"""
from __future__ import annotations

import os
import struct
from typing import Iterator, List, Optional

from .crc32c import masked_crc32c


class TFRecordWriter:
    """≙ utils/tf/TFRecordWriter.scala."""

    def __init__(self, path_or_file):
        self._own = isinstance(path_or_file, (str, os.PathLike))
        self._f = open(path_or_file, "wb") if self._own else path_or_file

    def write(self, record: bytes):
        header = struct.pack("<Q", len(record))
        self._f.write(header)
        self._f.write(struct.pack("<I", masked_crc32c(header)))
        self._f.write(record)
        self._f.write(struct.pack("<I", masked_crc32c(record)))

    def flush(self):
        self._f.flush()

    def close(self):
        if self._own:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class TFRecordIterator:
    """≙ utils/tf/TFRecordIterator.scala."""

    def __init__(self, path_or_file, check_crc: bool = True):
        self._own = isinstance(path_or_file, (str, os.PathLike))
        self._f = open(path_or_file, "rb") if self._own else path_or_file
        self.check_crc = check_crc

    def __iter__(self) -> Iterator[bytes]:
        return self

    def __next__(self) -> bytes:
        header = self._f.read(8)
        if len(header) < 8:
            if self._own:
                self._f.close()
            raise StopIteration
        (length,) = struct.unpack("<Q", header)
        (len_crc,) = struct.unpack("<I", self._f.read(4))
        payload = self._f.read(length)
        (pay_crc,) = struct.unpack("<I", self._f.read(4))
        if self.check_crc:
            if len_crc != masked_crc32c(header):
                raise IOError("TFRecord length crc mismatch")
            if pay_crc != masked_crc32c(payload):
                raise IOError("TFRecord payload crc mismatch")
        return payload


def read_tfrecords(path: str, check_crc: bool = True) -> List[bytes]:
    return list(TFRecordIterator(path, check_crc))


def write_tfrecords(path: str, records) -> None:
    with TFRecordWriter(path) as w:
        for r in records:
            w.write(r)


class FixedLengthRecordReader:
    """Fixed-size binary records with optional header/footer bytes per file
    (≙ utils/tf/FixedLengthRecordReader.scala; CIFAR-10 binary layout)."""

    def __init__(self, path: str, record_bytes: int, header_bytes: int = 0,
                 footer_bytes: int = 0):
        self.path = path
        self.record_bytes = record_bytes
        self.header_bytes = header_bytes
        self.footer_bytes = footer_bytes

    def __iter__(self) -> Iterator[bytes]:
        size = os.path.getsize(self.path)
        with open(self.path, "rb") as f:
            f.seek(self.header_bytes)
            end = size - self.footer_bytes
            while f.tell() + self.record_bytes <= end:
                yield f.read(self.record_bytes)


# --------------------------------------------------------------------- #
# tf.Example records (≙ nn/tf/ParsingOps.scala ParseExample)            #
# --------------------------------------------------------------------- #
def make_example(features: dict) -> bytes:
    """Encode {name: bytes|str|list[int]|list[float]|ndarray} as a
    serialized tf.Example."""
    import numpy as np
    from . import proto

    def feature_bytes(value) -> bytes:
        if isinstance(value, (bytes, str)):
            v = value.encode() if isinstance(value, str) else value
            return proto.enc_bytes(1, proto.enc_bytes(1, v))  # BytesList
        arr = np.asarray(value)
        if np.issubdtype(arr.dtype, np.floating):
            payload = b"".join(proto.enc_float(1, float(x))
                               for x in arr.reshape(-1))
            return proto.enc_bytes(2, payload)               # FloatList
        payload = b"".join(proto.enc_int64(1, int(x))
                           for x in arr.reshape(-1))
        return proto.enc_bytes(3, payload)                   # Int64List

    entries = b""
    for name, value in features.items():
        entry = (proto.enc_string(1, name)
                 + proto.enc_bytes(2, feature_bytes(value)))
        entries += proto.enc_bytes(1, entry)                 # map entry
    return proto.enc_bytes(1, entries)                       # Features


def parse_example(record: bytes) -> dict:
    """Decode a serialized tf.Example into {name: list|bytes}."""
    import numpy as np
    from . import proto
    from .proto import iter_fields, _read_varint

    out = {}
    for f, w, v in iter_fields(record):
        if f != 1 or w != 2:
            continue
        for f2, w2, v2 in iter_fields(v):          # Features.feature map
            if f2 != 1 or w2 != 2:
                continue
            name = None
            value = None
            for f3, w3, v3 in iter_fields(v2):
                if f3 == 1 and w3 == 2:
                    name = v3.decode("utf-8")
                elif f3 == 2 and w3 == 2:          # Feature
                    for f4, w4, v4 in iter_fields(v3):
                        if f4 == 1 and w4 == 2:    # BytesList
                            vals = [b for f5, w5, b in iter_fields(v4)
                                    if f5 == 1 and w5 == 2]
                            value = vals[0] if len(vals) == 1 else vals
                        elif f4 == 2 and w4 == 2:  # FloatList
                            floats = []
                            for f5, w5, v5 in iter_fields(v4):
                                if f5 == 1 and w5 == 5:
                                    floats.append(v5)
                                elif f5 == 1 and w5 == 2:  # packed
                                    import struct as _s
                                    floats.extend(_s.unpack(
                                        f"<{len(v5) // 4}f", v5))
                            value = np.asarray(floats, np.float32)
                        elif f4 == 3 and w4 == 2:  # Int64List
                            ints = []
                            for f5, w5, v5 in iter_fields(v4):
                                if f5 == 1 and w5 == 0:
                                    ints.append(v5)
                                elif f5 == 1 and w5 == 2:  # packed
                                    i = 0
                                    while i < len(v5):
                                        n, i = _read_varint(v5, i)
                                        ints.append(n)
                            value = np.asarray(ints, np.int64)
            if name is not None:
                out[name] = value
    return out
