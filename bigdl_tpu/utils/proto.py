"""Minimal protobuf wire-format encoder/decoder for TensorBoard Event
records (≙ visualization/tensorboard/FileWriter.scala + the TF event.proto
/ summary.proto subset BigDL serializes).

Hand-rolled varint encoding: the full protobuf toolchain is unnecessary for
the four message shapes TensorBoard scalars/histograms need, and this keeps
the event writer dependency-free.
"""
from __future__ import annotations

import struct
from typing import Iterator, List, Tuple


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def enc_double(field: int, v: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", v)


def enc_float(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", v)


def enc_int64(field: int, v: int) -> bytes:
    return _key(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def enc_bytes(field: int, v: bytes) -> bytes:
    return _key(field, 2) + _varint(len(v)) + v


def enc_string(field: int, v: str) -> bytes:
    return enc_bytes(field, v.encode("utf-8"))


def enc_packed_doubles(field: int, vals) -> bytes:
    payload = b"".join(struct.pack("<d", float(v)) for v in vals)
    return enc_bytes(field, payload)


# ---- message builders (field numbers from TF event.proto/summary.proto) --- #
def summary_value_scalar(tag: str, value: float) -> bytes:
    return enc_string(1, tag) + enc_float(2, value)


def histogram_proto(vmin, vmax, num, vsum, sum_sq, limits, counts) -> bytes:
    return (enc_double(1, vmin) + enc_double(2, vmax) + enc_double(3, num)
            + enc_double(4, vsum) + enc_double(5, sum_sq)
            + enc_packed_doubles(6, limits) + enc_packed_doubles(7, counts))


def summary_value_histo(tag: str, histo: bytes) -> bytes:
    return enc_string(1, tag) + enc_bytes(5, histo)


def event(wall_time: float, step: int, *, file_version: str = None,
          summary_values: List[bytes] = None) -> bytes:
    out = enc_double(1, wall_time) + enc_int64(2, step)
    if file_version is not None:
        out += enc_string(3, file_version)
    if summary_values:
        summary = b"".join(enc_bytes(1, v) for v in summary_values)
        out += enc_bytes(5, summary)
    return out


# ---- decoding (for readScalar) ------------------------------------------- #
def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = n = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def iter_fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 1:
            v = struct.unpack("<d", buf[i:i + 8])[0]
            i += 8
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wire == 5:
            v = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, v


def decode_scalar_event(buf: bytes):
    """Returns (wall_time, step, [(tag, value)]) or None if not a scalar."""
    wall = 0.0
    step = 0
    scalars = []
    for field, wire, v in iter_fields(buf):
        if field == 1 and wire == 1:
            wall = v
        elif field == 2 and wire == 0:
            step = v
        elif field == 5 and wire == 2:  # summary
            for f2, w2, v2 in iter_fields(v):
                if f2 == 1 and w2 == 2:  # Summary.Value
                    tag = None
                    val = None
                    for f3, w3, v3 in iter_fields(v2):
                        if f3 == 1 and w3 == 2:
                            tag = v3.decode("utf-8")
                        elif f3 == 2 and w3 == 5:
                            val = v3
                    if tag is not None and val is not None:
                        scalars.append((tag, val))
    return wall, step, scalars
