"""Object persistence (≙ utils/File.scala save/load).

The reference serializes arbitrary objects to local/HDFS paths via java
serialization.  Ours writes the tagged-JSON + .npy zip state format
(utils/serializer.save_state_file — no pickle, stable across class
refactors) whenever the object is expressible in it, and falls back to
pickle only for arbitrary Python objects the format cannot hold.  Device
arrays are converted to host numpy first (a checkpoint must never capture
live device buffers); writes are atomic (no torn files on crash).
"""
from __future__ import annotations

import os
import pickle
import zipfile

import jax
import numpy as np


def save(obj, path: str, is_overwrite: bool = True):
    from .serializer import SerializationError, save_state_file
    if os.path.exists(path) and not is_overwrite:
        raise FileExistsError(path)
    host = jax.tree_util.tree_map(
        lambda v: np.asarray(v) if isinstance(v, jax.Array) else v, obj,
        is_leaf=lambda v: isinstance(v, jax.Array))
    tmp = f"{path}.tmp-{os.getpid()}"
    if os.path.exists(tmp):     # stale tmp from a crashed earlier save
        os.remove(tmp)
    try:
        try:
            save_state_file(host, tmp)
        except SerializationError:
            # object the format cannot hold -> pickle fallback.  O_EXCL:
            # this pid owns the tmp exclusively; fsync before the rename
            # so a crash mid-replace can never surface a short file as
            # the committed checkpoint
            if os.path.exists(tmp):
                os.remove(tmp)
            fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o666)
            with os.fdopen(fd, "wb") as f:
                pickle.dump(host, f, protocol=pickle.HIGHEST_PROTOCOL)
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # no torn .tmp litter on ANY failure path — including a raise
        # from os.replace itself (cross-device rename, permission),
        # which previously left the O_EXCL tmp behind and made every
        # subsequent save of the same path trip over it
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


def load(path: str):
    from .serializer import load_state_file
    # route by leading magic bytes, not zipfile.is_zipfile content
    # sniffing: a PICKLED payload that embeds zip bytes would satisfy
    # is_zipfile (it scans for the end-of-central-directory record), but
    # a real state file always starts with the zip local-header magic and
    # a pickle always starts with \x80
    with open(path, "rb") as f:
        head = f.read(2)
    if head == b"PK":
        from .serializer import _to_host
        return _to_host(load_state_file(path))  # detached host arrays
    with open(path, "rb") as f:  # legacy / arbitrary-object fallback
        return pickle.load(f)
