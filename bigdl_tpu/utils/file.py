"""Object persistence (≙ utils/File.scala save/load).

The reference serializes to local/HDFS paths via java serialization; ours
pickles with device arrays converted to host numpy first (a checkpoint must
never capture live device buffers)."""
from __future__ import annotations

import os
import pickle

import jax
import numpy as np


def save(obj, path: str, is_overwrite: bool = True):
    if os.path.exists(path) and not is_overwrite:
        raise FileExistsError(path)
    host = jax.tree_util.tree_map(
        lambda v: np.asarray(v) if isinstance(v, jax.Array) else v, obj,
        is_leaf=lambda v: isinstance(v, jax.Array))
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(host, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)  # atomic: no torn checkpoints on crash


def load(path: str):
    with open(path, "rb") as f:
        return pickle.load(f)
