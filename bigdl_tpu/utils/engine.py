"""Runtime engine (≙ utils/Engine.scala, ThreadPool.scala).

The reference Engine owns MKL thread pools, core affinity, and the
Spark-executor topology (nodeNumber x coreNumber).  On TPU the compute
threading belongs to XLA; what remains host-side is (a) the device/mesh
topology, (b) a worker pool for data pipelines, and (c) process-group
initialization for multi-host pods (jax.distributed ≙ the Spark cluster
bootstrap).
"""
from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

import jax

_state = threading.local()
_engine_lock = threading.Lock()
_initialized = False
_io_pool: Optional[ThreadPoolExecutor] = None
_core_number = os.cpu_count() or 1
_node_number = 1


def init(node_number: Optional[int] = None,
         core_number: Optional[int] = None,
         coordinator_address: Optional[str] = None,
         process_id: Optional[int] = None) -> None:
    """≙ Engine.init: single call to set up the runtime.  For multi-host
    pods pass coordinator_address/process_id to bootstrap jax.distributed
    (the Spark master/executor handshake analogue)."""
    global _initialized, _core_number, _node_number, _io_pool
    with _engine_lock:
        if coordinator_address is not None:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=node_number or 1,
                process_id=process_id or 0)
        _node_number = node_number or jax.process_count()
        _core_number = core_number or os.cpu_count() or 1
        _io_pool = ThreadPoolExecutor(
            max_workers=max(2, _core_number // 2),
            thread_name_prefix="bigdl-io")
        _initialized = True


def is_initialized() -> bool:
    return _initialized


def core_number() -> int:
    """≙ Engine.coreNumber (host cores for data workers)."""
    return _core_number


def node_number() -> int:
    """≙ Engine.nodeNumber (processes in the pod)."""
    return _node_number


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def default_pool() -> ThreadPoolExecutor:
    """≙ Engine.default thread pool — host-side IO/augmentation workers."""
    global _io_pool
    if _io_pool is None:
        init()
    return _io_pool


def invoke(tasks) -> List:
    """Run callables on the worker pool and wait (≙ ThreadPool.invokeAndWait)."""
    pool = default_pool()
    return [f.result() for f in [pool.submit(t) for t in tasks]]
