"""Table activity type.

The reference framework's ``Activity`` is either a Tensor or a ``Table``
(com.intel.analytics.bigdl.utils.Table), a 1-indexed heterogeneous container
threaded through multi-input/multi-output layers (utils/Table.scala).

On TPU we represent activities as JAX pytrees.  ``Table`` is a thin list
wrapper registered as a pytree node so it can flow through ``jit``/``grad``
unchanged, while keeping the reference's 1-based indexing convention for
API parity (``table[1]`` is the first element).
"""
from __future__ import annotations

import jax


class Table:
    """1-indexed heterogeneous activity container (pytree)."""

    def __init__(self, *elements):
        if len(elements) == 1 and isinstance(elements[0], (list, tuple)):
            elements = tuple(elements[0])
        self._elems = list(elements)

    # -- 1-based indexing, matching the reference Table --------------------
    def __getitem__(self, i):
        if isinstance(i, int):
            if i < 1 or i > len(self._elems):
                raise IndexError(f"Table index {i} out of range 1..{len(self._elems)}")
            return self._elems[i - 1]
        raise TypeError("Table indices are 1-based ints")

    def __setitem__(self, i, v):
        if not isinstance(i, int) or i < 1:
            raise TypeError("Table indices are 1-based ints")
        while len(self._elems) < i:
            self._elems.append(None)
        self._elems[i - 1] = v

    def insert(self, v):
        self._elems.append(v)
        return self

    def __len__(self):
        return len(self._elems)

    def length(self):
        return len(self._elems)

    def __iter__(self):
        return iter(self._elems)

    def to_list(self):
        return list(self._elems)

    def __repr__(self):
        return f"Table({', '.join(repr(e) for e in self._elems)})"

    def __eq__(self, other):
        if isinstance(other, Table):
            return self._elems == other._elems
        if isinstance(other, (list, tuple)):
            return self._elems == list(other)
        return NotImplemented


def _table_flatten(t):
    return tuple(t._elems), None


def _table_unflatten(aux, children):
    return Table(*children)


jax.tree_util.register_pytree_node(Table, _table_flatten, _table_unflatten)


def T(*elements):
    """Constructor alias matching the reference's ``T()`` helper."""
    return Table(*elements)


def as_list(activity):
    """Normalize an activity (Table | list | tuple | array) to a python list."""
    if isinstance(activity, Table):
        return activity.to_list()
    if isinstance(activity, (list, tuple)):
        return list(activity)
    return [activity]
