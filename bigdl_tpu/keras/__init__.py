"""bigdl_tpu.keras — Keras-style API (≙ nn/keras, Keras 1.2.2 surface).

    from bigdl_tpu.keras import Sequential, Dense, Convolution2D, ...
    model = Sequential()
    model.add(Convolution2D(32, 3, 3, activation="relu",
                            input_shape=(1, 28, 28)))
    ...
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    model.fit(x, y, batch_size=128, nb_epoch=5)
"""
from .layers import (
    KerasLayer, Dense, Activation, Dropout, Flatten, Reshape, Permute,
    RepeatVector, Masking, Highway, MaxoutDense, Embedding,
    GaussianDropout, GaussianNoise, SpatialDropout1D, SpatialDropout2D,
    SpatialDropout3D, BatchNormalization,
    LeakyReLU, ELU, ThresholdedReLU, SReLU, SoftMax,
    Convolution1D, Convolution2D, Convolution3D,
    AtrousConvolution1D, AtrousConvolution2D, Deconvolution2D,
    SeparableConvolution2D, LocallyConnected1D, LocallyConnected2D,
    MaxPooling1D, MaxPooling2D, MaxPooling3D,
    AveragePooling1D, AveragePooling2D, AveragePooling3D,
    GlobalAveragePooling1D, GlobalAveragePooling2D, GlobalAveragePooling3D,
    GlobalMaxPooling1D, GlobalMaxPooling2D, GlobalMaxPooling3D,
    ZeroPadding1D, ZeroPadding2D, ZeroPadding3D,
    Cropping1D, Cropping2D, Cropping3D,
    UpSampling1D, UpSampling2D, UpSampling3D,
    SimpleRNN, LSTM, GRU, ConvLSTM2D, Bidirectional, TimeDistributed,
    Merge,
)
from .topology import Sequential, Model, Input, InputLayer, KerasModel
from .converter import (DefinitionLoader, WeightLoader, load_keras,
                        KerasConversionError)
