"""Keras topology: Sequential and functional Model with
compile / fit / evaluate / predict (≙ nn/keras/Topology.scala +
pyspark/bigdl/nn/keras/topology.py).

Training delegates to the native optimizers: LocalOptimizer on one chip,
DistriOptimizer over a mesh when ``mesh=`` is given to :meth:`fit` — the
Keras front end adds no second training path, just string-to-object
resolution (loss/optimizer/metric names).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.module import Module
from .. import nn as N
from ..nn import graph as graph_lib
from .layers import KerasLayer
from .. import optim as O


def _resolve_loss(loss):
    if isinstance(loss, str):
        table = {
            "categorical_crossentropy": N.CategoricalCrossEntropy,
            # keras models emit probabilities (softmax activation), so NLL
            # must log() them (≙ keras/optimization.py ClassNLLCriterion(
            # logProbAsInput=False))
            "sparse_categorical_crossentropy": lambda: N.ClassNLLCriterion(
                log_prob_as_input=False),
            "mse": N.MSECriterion, "mean_squared_error": N.MSECriterion,
            "mae": N.AbsCriterion, "mean_absolute_error": N.AbsCriterion,
            "binary_crossentropy": N.BCECriterion,
            "hinge": N.MarginCriterion,
            "squared_hinge": lambda: N.MarginCriterion(squared=True),
            "kld": N.DistKLDivCriterion,
            "kullback_leibler_divergence": N.KullbackLeiblerDivergenceCriterion,
            "poisson": N.PoissonCriterion,
            "cosine_proximity": N.CosineProximityCriterion,
            "mean_absolute_percentage_error": N.MeanAbsolutePercentageCriterion,
            "mape": N.MeanAbsolutePercentageCriterion,
            "mean_squared_logarithmic_error": N.MeanSquaredLogarithmicCriterion,
            "msle": N.MeanSquaredLogarithmicCriterion,
        }
        return table[loss]()
    return loss


def _resolve_optim(optimizer):
    if isinstance(optimizer, str):
        table = {"sgd": lambda: O.SGD(learning_rate=0.01),
                 "adam": O.Adam, "adagrad": O.Adagrad,
                 "adadelta": O.Adadelta, "adamax": O.Adamax,
                 "rmsprop": O.RMSprop}
        return table[optimizer.lower()]()
    return optimizer


def _resolve_metric(m):
    if isinstance(m, str):
        table = {"accuracy": O.Top1Accuracy, "acc": O.Top1Accuracy,
                 "top1": O.Top1Accuracy, "top5": O.Top5Accuracy,
                 "loss": O.Loss, "mae": O.MAE}
        return table[m.lower()]()
    return m


class KerasModel(Module):
    """Shared compile/fit/evaluate/predict for Sequential and Model."""

    def __init__(self, name=None):
        super().__init__(name=name)
        self.loss = None
        self.optim_method = None
        self.metrics: List = []

    def compile(self, optimizer, loss, metrics=None):
        self.optim_method = _resolve_optim(optimizer)
        self.loss = _resolve_loss(loss)
        self.metrics = [_resolve_metric(m) for m in (metrics or [])]
        return self

    def fit(self, x, y=None, batch_size=32, nb_epoch=10,
            validation_data=None, mesh=None, distributed=False):
        if self.loss is None:
            raise RuntimeError("call compile() before fit()")
        data = x if y is None else (np.asarray(x), np.asarray(y))
        if distributed or mesh is not None:
            from ..optim.distri_optimizer import DistriOptimizer
            from ..parallel import mesh as mesh_lib
            opt = DistriOptimizer(self, data, self.loss,
                                  batch_size=batch_size,
                                  mesh=mesh or mesh_lib.get_mesh())
        else:
            opt = O.LocalOptimizer(self, data, self.loss,
                                   batch_size=batch_size)
        opt.set_optim_method(self.optim_method)
        opt.set_end_when(O.Trigger.max_epoch(nb_epoch))
        if validation_data is not None and self.metrics:
            vx, vy = validation_data
            opt.set_validation(O.Trigger.every_epoch(),
                               (np.asarray(vx), np.asarray(vy)),
                               self.metrics, batch_size=batch_size)
        opt.optimize()
        return self

    def evaluate(self, x, y, batch_size=32):
        methods = self.metrics or [O.Top1Accuracy()]
        if O.Loss not in [type(m) for m in methods] and self.loss is not None:
            methods = methods + [O.Loss(self.loss)]
        return O.Evaluator(self).test((np.asarray(x), np.asarray(y)), methods)

    def predict(self, x, batch_size=32):
        return O.Predictor(self, batch_size=batch_size).predict(np.asarray(x))

    def predict_classes(self, x, batch_size=32, zero_based_label=True):
        cls = O.Predictor(self, batch_size=batch_size).predict_class(
            np.asarray(x))
        return cls - 1 if zero_based_label else cls


class Sequential(KerasModel):
    """Linear stack of Keras layers (≙ keras/Topology.scala Sequential)."""

    def __init__(self, name=None):
        super().__init__(name=name)
        self.layer_list: List[Module] = []
        self._out_shape = None

    def add(self, layer):
        if not self.layer_list and isinstance(layer, KerasLayer) \
                and layer.input_shape is None and layer.inner is None:
            raise ValueError("first layer needs input_shape=")
        if isinstance(layer, KerasLayer):
            in_shape = self._out_shape
            if in_shape is None:
                if layer.input_shape is not None:
                    in_shape = (None,) + tuple(layer.input_shape)
                elif layer._built_shape is not None:
                    in_shape = layer._built_shape  # standalone-built earlier
                else:
                    raise ValueError(
                        f"{layer.name}: input shape unknown; pass "
                        "input_shape= to this layer")
            self._out_shape = layer.compute_output_shape(in_shape)
        else:
            # raw nn module: propagate shape via eval_shape if possible
            if self._out_shape is not None:
                concrete = (2,) + tuple(self._out_shape[1:])
                try:
                    out = layer.get_output_shape(concrete)
                    self._out_shape = (None,) + tuple(out[1:])
                except Exception:
                    self._out_shape = None
        self.layer_list.append(layer)
        return self

    @property
    def output_shape(self):
        return self._out_shape

    def children(self):
        return list(self.layer_list)

    _serde_extra_attrs = ("_out_shape",)

    def _serde_restore_children(self, children):
        self.layer_list = [c for c in children if c is not None]

    def init(self, rng):
        p = {}
        for i, l in enumerate(self.layer_list):
            p.update(l.init(jax.random.fold_in(rng, i)))
        return p

    def initial_state(self):
        s = {}
        for l in self.layer_list:
            s.update(l.initial_state())
        return s

    def apply(self, params, x, ctx):
        for l in self.layer_list:
            x = l.apply(params, x, ctx)
        return x


class Model(KerasModel):
    """Functional graph model: ``Model(input=[nodes], output=node)``
    (≙ keras/Topology.scala Model). Build nodes with :func:`Input` and by
    calling layers on nodes."""

    def __init__(self, input, output, name=None):
        super().__init__(name=name)
        self.graph = N.Graph(input, output)

    def children(self):
        return [self.graph]

    # serde: the ctor signature (graph Nodes) can't be replayed from
    # config; rebuild around the persisted child Graph instead
    def _serde_config(self):
        return {"name": self.name}

    @classmethod
    def _serde_build(cls, config, children):
        m = cls.__new__(cls)
        KerasModel.__init__(m, name=config.get("name"))
        m.graph = children[0]
        return m

    def init(self, rng):
        return self.graph.init(rng)

    def initial_state(self):
        return self.graph.initial_state()

    def apply(self, params, x, ctx):
        return self.graph.apply(params, x, ctx)


def Input(shape=None, name=None):
    """Graph input node; shape excludes batch (keras convention)."""
    node = graph_lib.Input(name=name)
    node.keras_shape = (None,) + tuple(shape) if shape else None
    return node


def _keras_call(self, x, rng=None):
    """Calling a Keras layer on a graph Node builds it (from the node's
    keras_shape when known) and wires a graph edge."""
    if isinstance(x, graph_lib.Node) or (
            isinstance(x, (list, tuple))
            and x and isinstance(x[0], graph_lib.Node)):
        nodes = [x] if isinstance(x, graph_lib.Node) else list(x)
        shape = getattr(nodes[0], "keras_shape", None)
        if shape is not None and self.inner is None:
            self.build(shape)
        elif self.inner is None and self.input_shape is not None:
            self.build((None,) + tuple(self.input_shape))
        node = graph_lib.Node(self, nodes)
        if shape is not None:
            shapes = [getattr(n, "keras_shape", None) for n in nodes]
            try:
                if (len(nodes) > 1 and all(shapes)
                        and hasattr(self, "compute_output_shape_multi")):
                    node.keras_shape = \
                        self.compute_output_shape_multi(shapes)
                else:
                    node.keras_shape = self.compute_output_shape(shape)
            except Exception:
                node.keras_shape = None
        return node
    return Module.__call__(self, x, rng=rng)


KerasLayer.__call__ = _keras_call


def InputLayer(input_shape=None, name=None):
    """pyspark-compat spelling of :func:`Input`
    (bigdl/nn/keras/layer.py InputLayer: entry point into a model;
    input_shape excludes batch)."""
    return Input(shape=input_shape, name=name)
