"""Keras 1.2.2 model-file converter (≙ pyspark/bigdl/keras/converter.py:
DefinitionLoader / WeightLoader / WeightsConverter).

The reference converts a *live* keras 1.2.2 model object (it requires the old
keras installed and drives ``klayer.get_weights()``).  Here the JSON model
definition is parsed directly — no keras dependency — and weights are read
straight out of the HDF5 file in the keras-1.x layout (root attr
``layer_names``, per-layer groups with attr ``weight_names``), so files
written by ``model.to_json()`` + ``model.save_weights()`` load without the
original framework.

Only ``dim_ordering="th"`` (channels-first) definitions are supported, like
the reference converter (which rejects ``tf`` ordering for most layers).
"""
from __future__ import annotations

import json

import numpy as np

from . import layers as L
from . import topology as T
from .. import nn as N


class KerasConversionError(ValueError):
    pass


def _unsupported(what):
    raise KerasConversionError(f"unsupported keras construct: {what}")


def _th(cfg, who):
    if cfg.get("dim_ordering", "th") != "th":
        _unsupported(f"{who} with dim_ordering="
                     f"'{cfg.get('dim_ordering')}' (use 'th')")


def _input_shape(cfg):
    bis = cfg.get("batch_input_shape")
    return tuple(bis[1:]) if bis else None


def _act(cfg):
    a = cfg.get("activation", "linear")
    return None if a == "linear" else a


# --------------------------------------------------------------------- #
# per-class definition builders: keras-1.2.2 config dict -> our layer   #
# --------------------------------------------------------------------- #
def _dense(cfg):
    return L.Dense(cfg["output_dim"], activation=_act(cfg),
                   with_bias=cfg.get("bias", True),
                   input_shape=_input_shape(cfg), name=cfg.get("name"))


def _activation(cfg):
    return L.Activation(cfg["activation"], input_shape=_input_shape(cfg),
                        name=cfg.get("name"))


def _convolution2d(cfg):
    _th(cfg, "Convolution2D")
    sub = tuple(cfg.get("subsample", (1, 1)))
    return L.Convolution2D(cfg["nb_filter"], cfg["nb_row"], cfg["nb_col"],
                           activation=_act(cfg),
                           border_mode=cfg.get("border_mode", "valid"),
                           subsample=sub, bias=cfg.get("bias", True),
                           input_shape=_input_shape(cfg),
                           name=cfg.get("name"))


def _convolution1d(cfg):
    return L.Convolution1D(cfg["nb_filter"], cfg["filter_length"],
                           activation=_act(cfg),
                           border_mode=cfg.get("border_mode", "valid"),
                           subsample_length=cfg.get("subsample_length", 1),
                           bias=cfg.get("bias", True),
                           input_shape=_input_shape(cfg),
                           name=cfg.get("name"))


def _convolution3d(cfg):
    _th(cfg, "Convolution3D")
    return L.Convolution3D(cfg["nb_filter"], cfg["kernel_dim1"],
                           cfg["kernel_dim2"], cfg["kernel_dim3"],
                           activation=_act(cfg),
                           border_mode=cfg.get("border_mode", "valid"),
                           subsample=tuple(cfg.get("subsample", (1, 1, 1))),
                           bias=cfg.get("bias", True),
                           input_shape=_input_shape(cfg),
                           name=cfg.get("name"))


def _atrousconvolution1d(cfg):
    return L.AtrousConvolution1D(
        cfg["nb_filter"], cfg["filter_length"], activation=_act(cfg),
        subsample_length=cfg.get("subsample_length", 1),
        atrous_rate=cfg.get("atrous_rate", 1),
        input_shape=_input_shape(cfg), name=cfg.get("name"))


def _atrousconvolution2d(cfg):
    _th(cfg, "AtrousConvolution2D")
    rate = cfg.get("atrous_rate", (1, 1))
    rate = tuple(rate) if isinstance(rate, (list, tuple)) else (rate, rate)
    return L.AtrousConvolution2D(
        cfg["nb_filter"], cfg["nb_row"], cfg["nb_col"],
        activation=_act(cfg), subsample=tuple(cfg.get("subsample", (1, 1))),
        atrous_rate=rate, input_shape=_input_shape(cfg),
        name=cfg.get("name"))


def _deconvolution2d(cfg):
    _th(cfg, "Deconvolution2D")
    return L.Deconvolution2D(
        cfg["nb_filter"], cfg["nb_row"], cfg["nb_col"],
        activation=_act(cfg), subsample=tuple(cfg.get("subsample", (1, 1))),
        border_mode=cfg.get("border_mode", "valid"),
        bias=cfg.get("bias", True), input_shape=_input_shape(cfg),
        name=cfg.get("name"))


def _separableconvolution2d(cfg):
    _th(cfg, "SeparableConvolution2D")
    return L.SeparableConvolution2D(
        cfg["nb_filter"], cfg["nb_row"], cfg["nb_col"],
        activation=_act(cfg), border_mode=cfg.get("border_mode", "valid"),
        subsample=tuple(cfg.get("subsample", (1, 1))),
        depth_multiplier=cfg.get("depth_multiplier", 1),
        bias=cfg.get("bias", True), input_shape=_input_shape(cfg),
        name=cfg.get("name"))


def _locallyconnected1d(cfg):
    return L.LocallyConnected1D(
        cfg["nb_filter"], cfg["filter_length"], activation=_act(cfg),
        subsample_length=cfg.get("subsample_length", 1),
        input_shape=_input_shape(cfg), name=cfg.get("name"))


def _locallyconnected2d(cfg):
    _th(cfg, "LocallyConnected2D")
    return L.LocallyConnected2D(
        cfg["nb_filter"], cfg["nb_row"], cfg["nb_col"],
        activation=_act(cfg), border_mode=cfg.get("border_mode", "valid"),
        subsample=tuple(cfg.get("subsample", (1, 1))),
        input_shape=_input_shape(cfg), name=cfg.get("name"))


def _convlstm2d(cfg):
    _th(cfg, "ConvLSTM2D")
    if cfg.get("nb_row") != cfg.get("nb_col"):
        _unsupported("ConvLSTM2D with non-square kernel")
    return L.ConvLSTM2D(cfg["nb_filter"], cfg["nb_row"],
                        return_sequences=cfg.get("return_sequences", False),
                        go_backwards=cfg.get("go_backwards", False),
                        border_mode=cfg.get("border_mode", "same"),
                        input_shape=_input_shape(cfg),
                        name=cfg.get("name"))


def _pool3d(cls):
    def build(cfg):
        _th(cfg, cls.__name__)
        return cls(tuple(cfg.get("pool_size", (2, 2, 2))),
                   strides=tuple(cfg["strides"]) if cfg.get("strides")
                   else None, input_shape=_input_shape(cfg),
                   name=cfg.get("name"))
    return build


def _maxpooling2d(cfg):
    _th(cfg, "MaxPooling2D")
    return L.MaxPooling2D(tuple(cfg.get("pool_size", (2, 2))),
                          strides=tuple(cfg["strides"]) if cfg.get("strides")
                          else None,
                          border_mode=cfg.get("border_mode", "valid"),
                          input_shape=_input_shape(cfg),
                          name=cfg.get("name"))


def _averagepooling2d(cfg):
    _th(cfg, "AveragePooling2D")
    return L.AveragePooling2D(tuple(cfg.get("pool_size", (2, 2))),
                              strides=tuple(cfg["strides"])
                              if cfg.get("strides") else None,
                              border_mode=cfg.get("border_mode", "valid"),
                              input_shape=_input_shape(cfg),
                              name=cfg.get("name"))


def _maxpooling1d(cfg):
    return L.MaxPooling1D(cfg.get("pool_length", 2),
                          stride=cfg.get("stride"),
                          input_shape=_input_shape(cfg),
                          name=cfg.get("name"))


def _averagepooling1d(cfg):
    return L.AveragePooling1D(cfg.get("pool_length", 2),
                              stride=cfg.get("stride"),
                              input_shape=_input_shape(cfg),
                              name=cfg.get("name"))


def _embedding(cfg):
    return L.Embedding(cfg["input_dim"], cfg["output_dim"],
                       input_shape=_input_shape(cfg)
                       or ((cfg["input_length"],)
                           if cfg.get("input_length") else None),
                       name=cfg.get("name"))


def _batchnormalization(cfg):
    if cfg.get("mode", 0) != 0:
        _unsupported(f"BatchNormalization mode={cfg['mode']}")
    if cfg.get("axis", 1) != 1:
        _unsupported(f"BatchNormalization axis={cfg['axis']} (use 1)")
    return L.BatchNormalization(epsilon=cfg.get("epsilon", 1e-3),
                                momentum=cfg.get("momentum", 0.99),
                                input_shape=_input_shape(cfg),
                                name=cfg.get("name"))


def _recurrent(cls):
    def build(cfg):
        if cfg.get("stateful"):
            _unsupported("stateful recurrent layers")
        return cls(cfg["output_dim"], activation=cfg.get("activation", "tanh"),
                   inner_activation=cfg.get("inner_activation",
                                            "hard_sigmoid"),
                   return_sequences=cfg.get("return_sequences", False),
                   go_backwards=cfg.get("go_backwards", False),
                   input_shape=_input_shape(cfg)
                   or ((cfg["input_length"], cfg["input_dim"])
                       if cfg.get("input_length") and cfg.get("input_dim")
                       else None),
                   name=cfg.get("name"))
    return build


def _timedistributed(cfg):
    inner_spec = cfg["layer"]
    inner = _builder(inner_spec["class_name"])(inner_spec["config"])
    return L.TimeDistributed(inner, input_shape=_input_shape(cfg),
                             name=cfg.get("name"))


def _bidirectional(cfg):
    inner_spec = cfg["layer"]
    inner = _builder(inner_spec["class_name"])(inner_spec["config"])
    return L.Bidirectional(inner, merge_mode=cfg.get("merge_mode", "concat"),
                           input_shape=_input_shape(cfg),
                           name=cfg.get("name"))


def _merge(cfg):
    mode = cfg.get("mode", "sum")
    if not isinstance(mode, str):
        _unsupported("Merge with a lambda mode")
    branches = None
    if cfg.get("layers"):
        # Merge-at-the-head-of-a-Sequential: each entry is a full nested
        # model definition (the branch towers)
        branches = [DefinitionLoader.from_json_str(json.dumps(spec))
                    if spec.get("class_name") in ("Sequential", "Model",
                                                  "Functional")
                    else _builder(spec["class_name"])(spec["config"])
                    for spec in cfg["layers"]]
    in_shape = None
    if branches is not None:
        # branch towers carry their own input shapes; the Merge layer's
        # build shape is one branch's output (used only for the concat dim)
        out = getattr(branches[0], "output_shape", None)
        if out is not None:
            in_shape = tuple(out[1:])
    return L.Merge(layers=branches, mode=mode,
                   concat_axis=cfg.get("concat_axis", -1),
                   input_shape=in_shape, name=cfg.get("name"))


def _simple(cls, *fields, defaults=None):
    """Builder for layers whose config keys match our ctor kwargs 1:1."""
    defaults = defaults or {}

    def build(cfg):
        kw = {}
        for f in fields:
            if f in cfg:
                v = cfg[f]
                kw[f] = tuple(v) if isinstance(v, list) else v
            elif f in defaults:
                kw[f] = defaults[f]
        return cls(input_shape=_input_shape(cfg), name=cfg.get("name"), **kw)
    return build


_BUILDERS = {
    "Dense": _dense,
    "Activation": _activation,
    "Dropout": _simple(L.Dropout, "p"),
    "SpatialDropout1D": _simple(L.SpatialDropout1D, "p"),
    "SpatialDropout2D": _simple(L.SpatialDropout2D, "p"),
    "SpatialDropout3D": _simple(L.SpatialDropout3D, "p"),
    "GaussianDropout": _simple(L.GaussianDropout, "p"),
    "GaussianNoise": _simple(L.GaussianNoise, "sigma"),
    "Flatten": _simple(L.Flatten),
    "Reshape": _simple(L.Reshape, "target_shape"),
    "Permute": _simple(L.Permute, "dims"),
    "RepeatVector": _simple(L.RepeatVector, "n"),
    "Masking": _simple(L.Masking, "mask_value"),
    "Highway": lambda cfg: L.Highway(activation=_act(cfg),
                                     with_bias=cfg.get("bias", True),
                                     input_shape=_input_shape(cfg),
                                     name=cfg.get("name")),
    "MaxoutDense": lambda cfg: L.MaxoutDense(cfg["output_dim"],
                                             nb_feature=cfg.get("nb_feature",
                                                                4),
                                             input_shape=_input_shape(cfg),
                                             name=cfg.get("name")),
    "Embedding": _embedding,
    "BatchNormalization": _batchnormalization,
    "LeakyReLU": _simple(L.LeakyReLU, "alpha"),
    "ELU": _simple(L.ELU, "alpha"),
    "ThresholdedReLU": _simple(L.ThresholdedReLU, "theta"),
    "SReLU": _simple(L.SReLU),
    "Convolution1D": _convolution1d,
    "Convolution2D": _convolution2d,
    "Convolution3D": _convolution3d,
    "AtrousConvolution1D": _atrousconvolution1d,
    "AtrousConvolution2D": _atrousconvolution2d,
    "Deconvolution2D": _deconvolution2d,
    "SeparableConvolution2D": _separableconvolution2d,
    "LocallyConnected1D": _locallyconnected1d,
    "LocallyConnected2D": _locallyconnected2d,
    "ConvLSTM2D": _convlstm2d,
    "MaxPooling1D": _maxpooling1d,
    "MaxPooling2D": _maxpooling2d,
    "MaxPooling3D": _pool3d(L.MaxPooling3D),
    "AveragePooling1D": _averagepooling1d,
    "AveragePooling2D": _averagepooling2d,
    "AveragePooling3D": _pool3d(L.AveragePooling3D),
    "GlobalAveragePooling1D": _simple(L.GlobalAveragePooling1D),
    "GlobalMaxPooling1D": _simple(L.GlobalMaxPooling1D),
    "GlobalAveragePooling2D": _simple(L.GlobalAveragePooling2D),
    "GlobalMaxPooling2D": _simple(L.GlobalMaxPooling2D),
    "GlobalAveragePooling3D": _simple(L.GlobalAveragePooling3D),
    "GlobalMaxPooling3D": _simple(L.GlobalMaxPooling3D),
    "ZeroPadding1D": _simple(L.ZeroPadding1D, "padding"),
    "ZeroPadding2D": _simple(L.ZeroPadding2D, "padding", "dim_ordering"),
    "ZeroPadding3D": _simple(L.ZeroPadding3D, "padding"),
    "Cropping1D": _simple(L.Cropping1D, "cropping"),
    "Cropping2D": _simple(L.Cropping2D, "cropping", "dim_ordering"),
    "Cropping3D": _simple(L.Cropping3D, "cropping"),
    "UpSampling1D": _simple(L.UpSampling1D, "length"),
    "UpSampling2D": _simple(L.UpSampling2D, "size"),
    "UpSampling3D": _simple(L.UpSampling3D, "size"),
    "SimpleRNN": _recurrent(L.SimpleRNN),
    "LSTM": _recurrent(L.LSTM),
    "GRU": _recurrent(L.GRU),
    "TimeDistributed": _timedistributed,
    "TimeDistributedDense": None,  # filled below
    "Bidirectional": _bidirectional,
    "Merge": _merge,
}
_BUILDERS["TimeDistributedDense"] = lambda cfg: L.TimeDistributed(
    _dense(cfg), input_shape=_input_shape(cfg), name=cfg.get("name"))


def _builder(class_name):
    b = _BUILDERS.get(class_name)
    if b is None:
        _unsupported(f"layer class {class_name}")
    return b


# --------------------------------------------------------------------- #
# Keras 2.x schema (tf.keras / keras>=2 JSON): translated onto the same #
# wrapper layers.  Conv/pool/BN 2D honor data_format: channels_last     #
# builds the TPU-native NHWC nn layers directly, channels_first the     #
# NCHW ones; 1D layers are (B, T, C) in both schemas.                   #
# --------------------------------------------------------------------- #
def _k2_order(cfg):
    """keras-2 data_format -> wrapper dim_ordering.  channels_last maps
    onto the TPU-native NHWC nn layers; channels_first onto NCHW."""
    df = cfg.get("data_format") or "channels_last"
    if df == "channels_last":
        return "tf"
    if df == "channels_first":
        return "th"
    _unsupported(f"data_format={df!r}")


def _k2_pad(cfg, who):
    p = cfg.get("padding", "valid")
    if p not in ("valid", "same"):
        _unsupported(f"{who} padding={p!r}")
    return p


def _k2_dense(cfg):
    return L.Dense(cfg["units"], activation=_act(cfg),
                   with_bias=cfg.get("use_bias", True),
                   input_shape=_input_shape(cfg), name=cfg.get("name"))


def _k2_dropout(cfg):
    return L.Dropout(cfg["rate"], input_shape=_input_shape(cfg),
                     name=cfg.get("name"))


def _k2_embedding(cfg):
    if cfg.get("mask_zero"):
        _unsupported("Embedding mask_zero=True")
    return L.Embedding(cfg["input_dim"], cfg["output_dim"],
                       input_shape=_input_shape(cfg), name=cfg.get("name"))


def _k2_batchnorm(cfg):
    if not (cfg.get("center", True) and cfg.get("scale", True)):
        _unsupported("BatchNormalization without center/scale")
    ax = cfg.get("axis", -1)
    ax = ax[0] if isinstance(ax, (list, tuple)) else ax
    # axis -1/3 = channels-last (4D) or plain feature BN (2D/3D);
    # axis 1 = channels-first spatial BN
    return L.BatchNormalization(epsilon=cfg.get("epsilon", 1e-3),
                                momentum=cfg.get("momentum", 0.99),
                                dim_ordering="th" if ax == 1 else "tf",
                                input_shape=_input_shape(cfg),
                                name=cfg.get("name"))


def _k2_recurrent(cls, cfg, who):
    if cfg.get("go_backwards"):
        _unsupported(f"{who} go_backwards=True")
    if who == "GRU" and (cfg.get("activation", "tanh") != "tanh"
                         or cfg.get("recurrent_activation",
                                    "sigmoid") != "sigmoid"):
        _unsupported("GRU with non-default activations")
    extra = {}
    if who == "GRU":
        # absent key = pre-2.2 keras (classic form); tf.keras 2.x always
        # writes it — BOTH forms load (nn.GRU(reset_after=...))
        extra["reset_after"] = bool(cfg.get("reset_after", False))
    return cls(cfg["units"], activation=cfg.get("activation", "tanh"),
               inner_activation=cfg.get("recurrent_activation", "sigmoid"),
               **extra,
               return_sequences=cfg.get("return_sequences", False),
               input_shape=_input_shape(cfg), name=cfg.get("name"))


def _k2_bidirectional(cfg):
    inner_spec = cfg["layer"]
    inner = _k2_builder(inner_spec["class_name"])(inner_spec["config"])
    return L.Bidirectional(inner, merge_mode=cfg.get("merge_mode",
                                                     "concat"),
                           input_shape=_input_shape(cfg),
                           name=cfg.get("name"))


def _one(v, default=1):
    if v is None:
        return default
    return v[0] if isinstance(v, (list, tuple)) else v


def _pair(v, default=(1, 1)):
    if v is None:
        return default
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


def _k2_conv1d(cfg):
    k = _one(cfg["kernel_size"])
    s = _one(cfg.get("strides"))
    d = _one(cfg.get("dilation_rate"))
    pad = _k2_pad(cfg, "Conv1D")
    if pad == "same" and d == 1 and s != 1:
        _unsupported("Conv1D padding='same' with strides>1")
    if d > 1:
        if s != 1:
            _unsupported("Conv1D dilation with strides")
        if pad != "valid":
            _unsupported("dilated Conv1D with padding='same'")
        if not cfg.get("use_bias", True):
            _unsupported("dilated Conv1D without bias")
        return L.AtrousConvolution1D(
            cfg["filters"], k, activation=_act(cfg),
            atrous_rate=d,
            input_shape=_input_shape(cfg), name=cfg.get("name"))
    return L.Convolution1D(cfg["filters"], k, activation=_act(cfg),
                           border_mode=pad, subsample_length=s,
                           bias=cfg.get("use_bias", True),
                           input_shape=_input_shape(cfg),
                           name=cfg.get("name"))


def _k2_conv2d(cfg):
    kh, kw = _pair(cfg["kernel_size"])
    sh, sw = _pair(cfg.get("strides"))
    if _pair(cfg.get("dilation_rate")) != (1, 1):
        _unsupported("Conv2D dilation_rate != 1 (use channels_first "
                     "AtrousConvolution2D semantics via the keras-1 "
                     "schema)")
    return L.Convolution2D(cfg["filters"], kh, kw, activation=_act(cfg),
                           border_mode=_k2_pad(cfg, "Conv2D"),
                           subsample=(sh, sw),
                           dim_ordering=_k2_order(cfg),
                           bias=cfg.get("use_bias", True),
                           input_shape=_input_shape(cfg),
                           name=cfg.get("name"))


def _k2_sepconv2d(cfg):
    kh, kw = _pair(cfg["kernel_size"])
    sh, sw = _pair(cfg.get("strides"))
    if _pair(cfg.get("dilation_rate")) != (1, 1):
        _unsupported("SeparableConv2D dilation_rate != 1")
    return L.SeparableConvolution2D(
        cfg["filters"], kh, kw, activation=_act(cfg),
        border_mode=_k2_pad(cfg, "SeparableConv2D"),
        subsample=(sh, sw),
        depth_multiplier=cfg.get("depth_multiplier", 1),
        dim_ordering=_k2_order(cfg), bias=cfg.get("use_bias", True),
        input_shape=_input_shape(cfg), name=cfg.get("name"))


def _k2_conv2dtranspose(cfg):
    kh, kw = _pair(cfg["kernel_size"])
    sh, sw = _pair(cfg.get("strides"))
    if cfg.get("output_padding") is not None:
        _unsupported("Conv2DTranspose with explicit output_padding")
    if _pair(cfg.get("dilation_rate")) != (1, 1):
        _unsupported("Conv2DTranspose dilation_rate != 1")
    return L.Deconvolution2D(cfg["filters"], kh, kw, activation=_act(cfg),
                             subsample=(sh, sw),
                             border_mode=_k2_pad(cfg, "Conv2DTranspose"),
                             dim_ordering=_k2_order(cfg),
                             bias=cfg.get("use_bias", True),
                             input_shape=_input_shape(cfg),
                             name=cfg.get("name"))


def _padpair2d(v):
    """keras-2 2D pad/crop spec -> ((top, bottom), (left, right)).
    Accepts int, (h, w), or ((t, b), (l, r))."""
    if isinstance(v, int):
        return ((v, v), (v, v))
    a, b = v
    if isinstance(a, int):
        return ((a, a), (b, b))
    return (tuple(a), tuple(b))


def _k2_upsampling2d(cfg):
    if cfg.get("interpolation", "nearest") != "nearest":
        _unsupported(f"UpSampling2D interpolation="
                     f"{cfg.get('interpolation')!r} (only 'nearest')")
    return L.UpSampling2D(size=_pair(cfg.get("size"), (2, 2)),
                          dim_ordering=_k2_order(cfg),
                          input_shape=_input_shape(cfg),
                          name=cfg.get("name"))


def _k2_pool2d(cls):
    def build(cfg):
        ph, pw = _pair(cfg.get("pool_size"), (2, 2))
        st = _pair(cfg.get("strides"), (ph, pw))
        return cls(pool_size=(ph, pw), strides=tuple(st),
                   border_mode=_k2_pad(cfg, cls.__name__),
                   dim_ordering=_k2_order(cfg),
                   input_shape=_input_shape(cfg), name=cfg.get("name"))
    return build


def _k2_pool1d(cls):
    def build(cfg):
        k = _one(cfg.get("pool_size"), 2)
        s = _one(cfg.get("strides"), k)
        return cls(pool_length=k, stride=s,
                   border_mode=_k2_pad(cfg, cls.__name__),
                   input_shape=_input_shape(cfg), name=cfg.get("name"))
    return build


def _k2_global2d(cls):
    def build(cfg):
        return cls(dim_ordering=_k2_order(cfg),
                   input_shape=_input_shape(cfg), name=cfg.get("name"))
    return build


def _k2_merge(mode):
    def build(cfg):
        kw = {}
        if mode == "concat":
            axis = cfg.get("axis", -1)
            kw["concat_axis"] = axis
        return L.Merge(mode=mode, input_shape=_input_shape(cfg),
                       name=cfg.get("name"), **kw)
    return build


_K2_BUILDERS = {
    "Dense": _k2_dense,
    "Activation": _activation,
    "Dropout": _k2_dropout,
    "Flatten": lambda cfg: L.Flatten(input_shape=_input_shape(cfg),
                                     name=cfg.get("name")),
    "Reshape": lambda cfg: L.Reshape(tuple(cfg["target_shape"]),
                                     input_shape=_input_shape(cfg),
                                     name=cfg.get("name")),
    "Embedding": _k2_embedding,
    "BatchNormalization": _k2_batchnorm,
    "SimpleRNN": lambda cfg: _k2_recurrent(L.SimpleRNN, cfg, "SimpleRNN"),
    "LSTM": lambda cfg: _k2_recurrent(L.LSTM, cfg, "LSTM"),
    "GRU": lambda cfg: _k2_recurrent(L.GRU, cfg, "GRU"),
    "Bidirectional": _k2_bidirectional,
    "Conv1D": _k2_conv1d,
    "Conv2D": _k2_conv2d,
    "MaxPooling2D": _k2_pool2d(L.MaxPooling2D),
    "AveragePooling2D": _k2_pool2d(L.AveragePooling2D),
    "MaxPooling1D": _k2_pool1d(L.MaxPooling1D),
    "AveragePooling1D": _k2_pool1d(L.AveragePooling1D),
    "GlobalMaxPooling1D": lambda cfg: L.GlobalMaxPooling1D(
        input_shape=_input_shape(cfg), name=cfg.get("name")),
    "GlobalAveragePooling1D": lambda cfg: L.GlobalAveragePooling1D(
        input_shape=_input_shape(cfg), name=cfg.get("name")),
    "GlobalMaxPooling2D": _k2_global2d(L.GlobalMaxPooling2D),
    "GlobalAveragePooling2D": _k2_global2d(L.GlobalAveragePooling2D),
    "SeparableConv2D": _k2_sepconv2d,
    "Conv2DTranspose": _k2_conv2dtranspose,
    "ZeroPadding2D": lambda cfg: L.ZeroPadding2D(
        padding=_padpair2d(cfg.get("padding", 1)),
        dim_ordering=_k2_order(cfg),
        input_shape=_input_shape(cfg), name=cfg.get("name")),
    "Cropping2D": lambda cfg: L.Cropping2D(
        cropping=_padpair2d(cfg.get("cropping", 0)),
        dim_ordering=_k2_order(cfg),
        input_shape=_input_shape(cfg), name=cfg.get("name")),
    "UpSampling2D": _k2_upsampling2d,
    "LeakyReLU": lambda cfg: L.LeakyReLU(alpha=cfg.get("alpha", 0.3),
                                         input_shape=_input_shape(cfg),
                                         name=cfg.get("name")),
    "ELU": lambda cfg: L.ELU(alpha=cfg.get("alpha", 1.0),
                             input_shape=_input_shape(cfg),
                             name=cfg.get("name")),
    "Permute": lambda cfg: L.Permute(tuple(cfg["dims"]),
                                     input_shape=_input_shape(cfg),
                                     name=cfg.get("name")),
    "RepeatVector": lambda cfg: L.RepeatVector(
        cfg["n"], input_shape=_input_shape(cfg), name=cfg.get("name")),
    "ThresholdedReLU": lambda cfg: L.ThresholdedReLU(
        theta=cfg.get("theta", 1.0), input_shape=_input_shape(cfg),
        name=cfg.get("name")),
    "GaussianNoise": lambda cfg: L.GaussianNoise(
        cfg["stddev"], input_shape=_input_shape(cfg),
        name=cfg.get("name")),
    "GaussianDropout": lambda cfg: L.GaussianDropout(
        cfg["rate"], input_shape=_input_shape(cfg), name=cfg.get("name")),
    "SpatialDropout1D": lambda cfg: L.SpatialDropout1D(
        cfg.get("rate", 0.5), input_shape=_input_shape(cfg),
        name=cfg.get("name")),
    "Add": _k2_merge("sum"),
    "Multiply": _k2_merge("mul"),
    "Average": _k2_merge("ave"),
    "Maximum": _k2_merge("max"),
    "Concatenate": _k2_merge("concat"),
}


def _k2_builder(class_name):
    b = _K2_BUILDERS.get(class_name)
    if b is None:
        _unsupported(f"keras-2 layer class {class_name}")
    return b


def _is_keras2(spec):
    """Keras >=2 JSON: keras_version key, a Sequential whose config is
    a dict with a 'layers' list (keras 1 configs are bare lists), or —
    for stripped JSONs — any layer config using a keras-2-only key
    (filters/units/rate replaced keras-1's nb_filter/output_dim/p)."""
    kv = spec.get("keras_version", "")
    if kv:
        return not str(kv).startswith("1")
    if (spec.get("class_name") == "Sequential"
            and isinstance(spec.get("config"), dict)):
        return True
    cfg = spec.get("config")
    layers = cfg.get("layers", []) if isinstance(cfg, dict) else \
        (cfg if isinstance(cfg, list) else [])
    k2_only = {"filters", "units", "rate", "data_format"}
    k1_only = {"nb_filter", "output_dim", "p", "dim_ordering"}
    for layer in layers:
        lc = layer.get("config", {}) if isinstance(layer, dict) else {}
        if k1_only & set(lc):
            return False
        if k2_only & set(lc):
            return True
    return False


class DefinitionLoader:
    """Build a bigdl_tpu.keras model from a keras JSON definition —
    the keras-1.2.2 schema the reference converts (≙ converter.py
    DefinitionLoader, minus the live-keras dependency), or the
    keras-2.x / tf.keras schema (auto-detected)."""

    @classmethod
    def from_json_path(cls, path):
        with open(path) as f:
            return cls.from_spec(json.load(f))

    @classmethod
    def from_json_str(cls, json_str):
        return cls.from_spec(json.loads(json_str))

    @classmethod
    def from_spec(cls, spec):
        kind = spec.get("class_name")
        builder = _k2_builder if _is_keras2(spec) else _builder
        if kind == "Sequential":
            cfg = spec["config"]
            layer_specs = cfg["layers"] if isinstance(cfg, dict) else cfg
            return cls._sequential(layer_specs, builder)
        if kind in ("Model", "Functional"):
            return cls._graph(spec["config"], builder)
        _unsupported(f"top-level class {kind}")

    @classmethod
    def _sequential(cls, layer_specs, builder=_builder):
        model = T.Sequential()
        pending_shape = None
        for spec in layer_specs:
            if spec["class_name"] == "InputLayer":
                shp = spec["config"].get("batch_input_shape") \
                    or spec["config"].get("batch_shape")
                pending_shape = tuple(shp[1:]) if shp else None
                continue
            cfg = spec["config"]
            own = _input_shape(cfg)
            # prefer the InputLayer's shape whenever the layer's own is
            # absent or partial (tf.keras writes [None, None] on inner
            # layers); a partial own shape (None dims) survives when no
            # InputLayer preceded — recurrent layers only need the last
            # dim, matching the keras-1 behavior
            if pending_shape is not None and (
                    own is None or any(d is None for d in own)):
                cfg = dict(cfg, batch_input_shape=(None,) + pending_shape)
            pending_shape = None
            model.add(builder(spec["class_name"])(cfg))
        return model

    @classmethod
    def _graph(cls, cfg, builder=_builder):
        nodes = {}          # layer name -> graph node
        specs = {l["name"]: l for l in cfg["layers"]}

        def build_node(name):
            if name in nodes:
                return nodes[name]
            spec = specs[name]
            if spec["class_name"] == "InputLayer":
                shp = spec["config"].get("batch_input_shape") \
                    or spec["config"].get("batch_shape")
                nodes[name] = T.Input(shape=tuple(shp[1:]) if shp else None,
                                      name=name)
                return nodes[name]
            if len(spec.get("inbound_nodes", [])) > 1:
                # one layer applied at several call sites shares weights
                # across sites — not representable here (the reference
                # converter rejects this too: __check_is_share_weights)
                _unsupported(f"layer {name!r} applied at multiple call "
                             "sites (shared weights)")
            in_names = [inb[0] for node in spec["inbound_nodes"]
                        for inb in node]
            ins = [build_node(n) for n in in_names]
            layer = builder(spec["class_name"])(spec["config"])
            nodes[name] = layer(ins[0] if len(ins) == 1 else ins)
            return nodes[name]

        for lname in specs:
            build_node(lname)
        ins = [nodes[il[0]] for il in cfg["input_layers"]]
        outs = [nodes[ol[0]] for ol in cfg["output_layers"]]
        return T.Model(ins if len(ins) > 1 else ins[0],
                       outs if len(outs) > 1 else outs[0])


# --------------------------------------------------------------------- #
# weight loading                                                        #
# --------------------------------------------------------------------- #
def _dec(s):
    return s.decode() if isinstance(s, bytes) else s


def read_keras_hdf5(path):
    """Return [(layer_name, [arrays...])] in file order from a keras-1.x
    HDF5 weight file (also accepts full-model files w/ 'model_weights')."""
    import h5py
    out = []
    with h5py.File(path, "r") as f:
        g = f["model_weights"] if "model_weights" in f else f
        layer_names = [_dec(n) for n in g.attrs["layer_names"]]
        for ln in layer_names:
            lg = g[ln]
            wnames = [_dec(n) for n in lg.attrs.get("weight_names", [])]
            if wnames:
                out.append((ln, [np.asarray(lg[w]) for w in wnames]))
    return out


def _find(module, cls):
    return [m for m in module.modules() if isinstance(m, cls)]


def _set(params, mod, **arrs):
    import jax.numpy as jnp
    entry = dict(params.get(mod.name, {}))
    for k, v in arrs.items():
        if k in entry and tuple(entry[k].shape) != tuple(v.shape):
            raise KerasConversionError(
                f"{mod.name}.{k}: file weight shape {v.shape} != model "
                f"shape {tuple(entry[k].shape)}")
        entry[k] = jnp.asarray(v)
    params[mod.name] = entry


def _gates_lstm(ws):
    """keras1 LSTM weight order: [W_i,U_i,b_i, W_c,U_c,b_c, W_f,U_f,b_f,
    W_o,U_o,b_o]; ours is fused (in,4H) with gate order i,f,g,o."""
    Wi, Ui, bi, Wc, Uc, bc, Wf, Uf, bf, Wo, Uo, bo = ws
    return (np.concatenate([Wi, Wf, Wc, Wo], 1),
            np.concatenate([Ui, Uf, Uc, Uo], 1),
            np.concatenate([bi, bf, bc, bo], 0))


def _set_gru(params, cell, Wz, Uz, bz, Wr, Ur, br, Wh, Uh, bh,
             bh_z=None, bh_r=None, bh_h=None):
    """Route per-gate GRU arrays into our fused-(r,z)+candidate params;
    the bh_* recurrent biases feed the reset_after (v3) form."""
    import jax.numpy as jnp
    entry = dict(params.get(cell.name, {}))
    gates = dict(entry.get("gates", {}))
    newg = dict(entry.get("new", {}))
    gates.update(weight_i=jnp.asarray(np.concatenate([Wr, Wz], 1)),
                 weight_h=jnp.asarray(np.concatenate([Ur, Uz], 1)),
                 bias=jnp.asarray(np.concatenate([br, bz], 0)))
    newg.update(weight_i=jnp.asarray(Wh), weight_h=jnp.asarray(Uh),
                bias=jnp.asarray(bh))
    if bh_r is not None:
        gates["bias_h"] = jnp.asarray(np.concatenate([bh_r, bh_z], 0))
        newg["bias_h"] = jnp.asarray(bh_h)
    entry["gates"], entry["new"] = gates, newg
    params[cell.name] = entry


def _load_cell(cell, ws, params, schema="k1"):
    if schema == "k2":
        return _load_cell_k2(cell, ws, params)
    if isinstance(cell, N.LSTM):
        wi, wh, b = _gates_lstm(ws)
        _set(params, cell, weight_i=wi, weight_h=wh, bias=b)
    elif isinstance(cell, N.GRU):
        # keras1 GRU order: [W_z,U_z,b_z, W_r,U_r,b_r, W_h,U_h,b_h]
        Wz, Uz, bz, Wr, Ur, br, Wh, Uh, bh = ws
        _set_gru(params, cell, Wz, Uz, bz, Wr, Ur, br, Wh, Uh, bh)
    elif isinstance(cell, N.RnnCell):
        W, U, b = ws
        _set(params, cell, weight_i=W, weight_h=U, bias=b)
    else:
        raise KerasConversionError(f"no weight adapter for cell {cell}")


def _load_cell_k2(cell, ws, params):
    """keras-2 recurrent weights are fused: [kernel, recurrent, bias]."""
    if isinstance(cell, N.LSTM):
        # gate order i, f, c, o == our fused i, f, g(cell), o
        k, r, b = ws
        _set(params, cell, weight_i=k, weight_h=r, bias=b)
    elif isinstance(cell, N.GRU):
        # kernel thirds are z, r, h in both forms; reset_after=True adds
        # a (2, 3H) bias: row 0 input bias, row 1 recurrent bias
        k, r, b = ws
        H = k.shape[1] // 3
        if getattr(cell, "reset_after", False):
            b = np.asarray(b)
            if b.ndim != 2 or b.shape[0] != 2:
                raise KerasConversionError(
                    f"GRU reset_after expects (2, 3H) bias, got {b.shape}")
            bi, bh = b[0], b[1]
            _set_gru(params, cell,
                     k[:, :H], r[:, :H], bi[:H],
                     k[:, H:2 * H], r[:, H:2 * H], bi[H:2 * H],
                     k[:, 2 * H:], r[:, 2 * H:], bi[2 * H:],
                     bh_z=bh[:H], bh_r=bh[H:2 * H], bh_h=bh[2 * H:])
        else:
            b = np.asarray(b)
            if b.ndim != 1:
                raise KerasConversionError(
                    f"GRU bias shape {b.shape}: a (2, 3H) bias is the "
                    "reset_after form — build the layer with "
                    "reset_after=True to load these weights")
            _set_gru(params, cell,
                     k[:, :H], r[:, :H], b[:H],
                     k[:, H:2 * H], r[:, H:2 * H], b[H:2 * H],
                     k[:, 2 * H:], r[:, 2 * H:], b[2 * H:])
    elif isinstance(cell, N.RnnCell):
        k, r, b = ws
        _set(params, cell, weight_i=k, weight_h=r, bias=b)
    else:
        raise KerasConversionError(f"no k2 weight adapter for cell {cell}")


def _load_layer_weights(klayer, ws, params, state, schema="k1"):
    """Route one keras layer's weight list into our module's params/state."""
    if isinstance(klayer, L.TimeDistributed):
        klayer.ensure_built()
        inner = klayer.layer
        return _load_layer_weights(inner, ws, params, state, schema)
    if isinstance(klayer, L.Bidirectional):
        klayer.ensure_built()
        cells = _find(klayer, N.Cell)
        half = len(ws) // 2
        _load_cell(cells[0], ws[:half], params, schema)
        _load_cell(cells[1], ws[half:], params, schema)
        return
    if isinstance(klayer, (L.SimpleRNN, L.LSTM, L.GRU)):
        klayer.ensure_built()
        cell = _find(klayer, N.Cell)[0]
        return _load_cell(cell, ws, params, schema)
    klayer.ensure_built()
    if schema == "k2":
        # layouts that differ from keras 1 in the file
        if isinstance(klayer, L.Convolution2D):
            conv = _find(klayer, N.SpatialConvolution)[0]
            # file kernel is HWIO regardless of data_format -> ours OIHW
            W = np.transpose(ws[0], (3, 2, 0, 1))
            _set(params, conv, weight=W,
                 **({"bias": ws[1]} if len(ws) > 1 else {}))
            return
        if isinstance(klayer, L.AtrousConvolution1D):
            conv = _find(klayer, N.SpatialDilatedConvolution)[0]
            # file kernel (k, in, out) -> ours OIHW with kernel (k, 1)
            W = np.transpose(ws[0], (2, 1, 0))[..., None]
            _set(params, conv, weight=W,
                 **({"bias": ws[1]} if len(ws) > 1 else {}))
            return
        if isinstance(klayer, L.Convolution1D):
            conv = _find(klayer, N.TemporalConvolution)[0]
            # file kernel (k, in, out) -> ours (out, in, k)
            W = np.transpose(ws[0], (2, 1, 0))
            _set(params, conv, weight=W,
                 **({"bias": ws[1]} if len(ws) > 1 else {}))
            return
        if isinstance(klayer, L.Deconvolution2D):
            conv = _find(klayer, N.SpatialFullConvolution)[0]
            # file kernel (kh, kw, out, in) -> ours (in, out, kh, kw)
            W = np.transpose(ws[0], (3, 2, 0, 1))
            _set(params, conv, weight=W,
                 **({"bias": ws[1]} if len(ws) > 1 else {}))
            return
        if isinstance(klayer, L.SeparableConvolution2D):
            conv = _find(klayer, N.SpatialSeparableConvolution)[0]
            # depthwise (kh, kw, in, mult) -> grouped OIHW
            # (in*mult, 1, kh, kw) with input-major channel order
            dw = np.transpose(ws[0], (2, 3, 0, 1))
            dw = dw.reshape(dw.shape[0] * dw.shape[1], 1,
                            dw.shape[2], dw.shape[3])
            # pointwise (1, 1, in*mult, out) -> (out, in*mult, 1, 1)
            pw = np.transpose(ws[1], (3, 2, 0, 1))
            _set(params, conv, depth_weight=dw, point_weight=pw,
                 **({"bias": ws[2]} if len(ws) > 2 else {}))
            return
        # Dense/Embedding/BatchNormalization file layouts match keras 1:
        # fall through to the shared adapters below
    if isinstance(klayer, (L.Dense, L.Highway)):
        lins = _find(klayer, N.Linear)
        if isinstance(klayer, L.Dense):
            W = ws[0]
            _set(params, lins[0], weight=W.T,
                 **({"bias": ws[1]} if len(ws) > 1 else {}))
        else:  # Highway: keras order [W, W_gate(carry), b, b_gate]
            _unsupported("Highway hdf5 weights")  # rarely serialized; explicit
        return
    if isinstance(klayer, L.Embedding):
        lk = _find(klayer, N.LookupTable)[0]
        _set(params, lk, weight=ws[0])
        return
    if isinstance(klayer, (L.Convolution2D,)):
        conv = _find(klayer, N.SpatialConvolution)[0]
        _set(params, conv, weight=ws[0],
             **({"bias": ws[1]} if len(ws) > 1 else {}))
        return
    if isinstance(klayer, L.Convolution1D):
        conv = _find(klayer, N.TemporalConvolution)[0]
        # keras1 conv1d weight: (filter_length, 1, input_dim, nb_filter)
        W = np.transpose(ws[0][:, 0], (2, 1, 0))
        _set(params, conv, weight=W,
             **({"bias": ws[1]} if len(ws) > 1 else {}))
        return
    if isinstance(klayer, L.Convolution3D):
        conv = _find(klayer, N.VolumetricConvolution)[0]
        # keras1 th conv3d weight: (nb_filter, stack, k1, k2, k3) = ours
        _set(params, conv, weight=ws[0],
             **({"bias": ws[1]} if len(ws) > 1 else {}))
        return
    if isinstance(klayer, L.AtrousConvolution2D):
        conv = _find(klayer, N.SpatialDilatedConvolution)[0]
        _set(params, conv, weight=ws[0],
             **({"bias": ws[1]} if len(ws) > 1 else {}))
        return
    if isinstance(klayer, L.AtrousConvolution1D):
        conv = _find(klayer, N.SpatialDilatedConvolution)[0]
        # keras1 weight (filter_length, 1, input_dim, nb_filter)
        # -> ours OIHW with kernel (filter_length, 1)
        W = np.transpose(ws[0], (3, 2, 0, 1))
        _set(params, conv, weight=W,
             **({"bias": ws[1]} if len(ws) > 1 else {}))
        return
    if isinstance(klayer, L.Deconvolution2D):
        conv = _find(klayer, N.SpatialFullConvolution)[0]
        # keras1 th deconv weight (nb_filter, stack, r, c) -> ours (in, out, r, c)
        W = np.transpose(ws[0], (1, 0, 2, 3))
        _set(params, conv, weight=W,
             **({"bias": ws[1]} if len(ws) > 1 else {}))
        return
    if isinstance(klayer, L.BatchNormalization):
        bn = _find(klayer, N.BatchNormalization)[0]
        gamma, beta, mean, var = ws
        _set(params, bn, weight=gamma, bias=beta)
        import jax.numpy as jnp
        # owning copies (GL001): asarray could zero-copy adopt the h5
        # buffers, and BN state is donated by the train step
        state[bn.name] = {"running_mean": jnp.array(mean, copy=True),
                          "running_var": jnp.array(var, copy=True)}
        return
    raise KerasConversionError(
        f"no weight adapter for layer {type(klayer).__name__}")


class WeightLoader:
    """≙ converter.py WeightLoader.load_weights_from_hdf5/json: route a
    keras-1.x HDF5 weight file into a DefinitionLoader-built model."""

    @staticmethod
    def load_weights_from_hdf5(bmodel, hdf5_path, by_name=True,
                               schema="k1"):
        entries = read_keras_hdf5(hdf5_path)
        bmodel.ensure_initialized()
        params = dict(bmodel._params)
        state = dict(bmodel._state or {})
        klayers = {m.name: m for m in bmodel.modules()
                   if isinstance(m, L.KerasLayer)}
        ordered = [m for m in bmodel.modules()
                   if isinstance(m, L.KerasLayer) and _owns_weights(m)]
        for i, (lname, ws) in enumerate(entries):
            if by_name:
                if lname not in klayers:
                    # silently falling back to positional assignment here
                    # would overwrite some other layer's weights
                    raise KerasConversionError(
                        f"hdf5 layer {lname!r} not found in the model; "
                        "rename it to match or load with by_name=False")
                target = klayers[lname]
            elif i < len(ordered):
                target = ordered[i]
            else:
                raise KerasConversionError(
                    f"hdf5 layer {lname!r} has no counterpart in the model")
            _load_layer_weights(target, ws, params, state, schema)
        bmodel.set_params(params, state)
        return bmodel


def _owns_weights(klayer):
    return isinstance(klayer, (L.Dense, L.Highway, L.MaxoutDense,
                               L.Embedding, L.BatchNormalization,
                               L.Convolution1D, L.Convolution2D,
                               L.Convolution3D, L.AtrousConvolution1D,
                               L.AtrousConvolution2D, L.Deconvolution2D,
                               L.SeparableConvolution2D,
                               L.LocallyConnected1D, L.LocallyConnected2D,
                               L.SimpleRNN, L.LSTM, L.GRU,
                               L.Bidirectional, L.TimeDistributed))


def load_keras(json_path=None, hdf5_path=None, by_name=True):
    """≙ pyspark bigdl.nn.layer.Model.load_keras(json_path, hdf5_path).

    Accepts the keras-1.2.2 schema the reference supports AND the
    keras-2.x / tf.keras schema (auto-detected from the JSON).
    ``json_path=None`` with an ``hdf5_path`` loads a single-file keras
    model (``model.save('m.h5')``): the definition is read from the
    file's ``model_config`` attribute."""
    if json_path is None:
        if not hdf5_path:
            raise ValueError("need json_path and/or hdf5_path")
        spec = _model_config_from_hdf5(hdf5_path)
    else:
        with open(json_path) as f:
            spec = json.load(f)
    schema = "k2" if _is_keras2(spec) else "k1"
    model = DefinitionLoader.from_spec(spec)
    if hdf5_path:
        WeightLoader.load_weights_from_hdf5(model, hdf5_path,
                                            by_name=by_name, schema=schema)
    return model


def _model_config_from_hdf5(path):
    """Model definition from a full-model keras HDF5 (the
    ``model_config`` root attribute written by ``model.save``)."""
    import h5py
    with h5py.File(path, "r") as f:
        cfg = f.attrs.get("model_config")
        kv = f.attrs.get("keras_version")
    if cfg is None:
        raise KerasConversionError(
            f"{path} has no model_config attribute (weights-only file?) "
            "— pass the architecture JSON via json_path")
    spec = json.loads(_dec(cfg))
    # keras stores the version as a SIBLING root attr, not inside the
    # config JSON — without it a Functional spec would misdetect as k1
    if kv is not None and "keras_version" not in spec:
        spec["keras_version"] = _dec(kv)
    return spec
