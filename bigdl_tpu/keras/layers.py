"""Keras-style layer API (≙ nn/keras/*.scala, Keras 1.2.2 semantics).

Every Keras layer is a thin *shape-inferring* wrapper: construction records
hyper-parameters; ``build(input_shape)`` instantiates the underlying
``bigdl_tpu.nn`` module once the input shape is known (Sequential/Model
propagate shapes; standalone ``forward`` builds from the actual input).
Compute therefore always lowers through the same jnp/lax ops as the core
library — there is no second kernel path.

Conventions (matching the reference nn/keras/KerasLayer.scala):
  * ``input_shape`` excludes the batch dimension.
  * conv/pooling layers are channels-first ("th" dim ordering).
  * ``border_mode``: "valid" or "same".
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.module import Module, Ctx
from .. import nn as N


def _act_module(name, size_hint=None):
    """Activation by Keras name -> nn module."""
    if name is None or name == "linear":
        return N.Identity()
    table = {
        "relu": N.ReLU, "tanh": N.Tanh, "sigmoid": N.Sigmoid,
        # Keras softmax semantics: last-dim, so batched (N, T, C)
        # sequence outputs normalize per step (nn.SoftMax's default is
        # the reference's spatial channel-dim convention instead)
        "softmax": lambda: N.SoftMax(axis=-1), "softplus": N.SoftPlus,
        "softsign": N.SoftSign, "hard_sigmoid": N.HardSigmoid,
        "gelu": N.GELU, "silu": N.SiLU, "elu": N.ELU,
        "log_softmax": N.LogSoftMax,
    }
    if name not in table:
        raise ValueError(f"unknown activation {name!r}")
    return table[name]()


class KerasLayer(Module):
    """Base: records config, builds the inner nn module lazily."""

    def __init__(self, input_shape=None, name=None):
        super().__init__(name=name)
        self.input_shape = tuple(input_shape) if input_shape else None
        self.inner: Optional[Module] = None
        self._built_shape = None

    # subclasses implement: inner module from the *full* (batch incl.) shape
    def _build(self, input_shape) -> Module:
        raise NotImplementedError(type(self).__name__)

    @staticmethod
    def _shape_key(shape):
        """Batch-agnostic build key: the inner module never depends on the
        batch dim, so (None, 4) and (3, 4) must map to the SAME build —
        rebuilding would orphan already-initialized params."""
        return (None,) + tuple(shape)[1:]

    def build(self, input_shape):
        shape = tuple(input_shape)
        key = self._shape_key(shape)
        if self.inner is None or self._built_shape != key:
            self.inner = self._build(shape)
            self._built_shape = key
        return self.inner

    def ensure_built(self):
        if self.inner is None:
            if self.input_shape is None:
                raise ValueError(
                    f"{self.name}: first layer needs input_shape=")
            self.build((None,) + self.input_shape)
        return self.inner

    def children(self):
        return [self.inner] if self.inner is not None else []

    # serde: the built inner module (with its already-initialized param
    # names) must be persisted and re-attached — rebuilding it from config
    # would mint fresh auto-names and orphan the saved params
    _serde_extra_attrs = ("_built_shape",)

    def _serde_restore_children(self, children):
        if children and children[0] is not None:
            self.inner = children[0]

    def init(self, rng):
        return self.ensure_built().init(rng)

    def initial_state(self):
        return self.ensure_built().initial_state()

    def apply(self, params, x, ctx):
        return self.ensure_built().apply(params, x, ctx)

    def forward(self, x, rng=None):
        if self.inner is None and self.input_shape is None:
            shape = x[0].shape if isinstance(x, (list, tuple)) else x.shape
            self.build(shape)
        return super().forward(x, rng=rng)

    def compute_output_shape(self, input_shape):
        """input_shape includes batch (None allowed); returns same style.
        Variable NON-batch dims (None, e.g. free sequence length) are
        probed with two dummy sizes — output dims that track the dummy
        come back as None."""
        self.build(tuple(input_shape))
        batch = input_shape[0]
        rest = tuple(input_shape[1:])
        b = 2 if batch is None else batch
        if any(d is None for d in rest):
            # two LARGE probes whose gap (12) keeps ceil-div results
            # apart for any realistic stride <= 12 (8/12 collided at
            # stride >= 12), while both stay divisible by 2/3/4/6/12 so
            # Reshape((k, -1))-style inference still works (primes would
            # break it)
            c1 = (b,) + tuple(120 if d is None else d for d in rest)
            c2 = (b,) + tuple(132 if d is None else d for d in rest)
            o1 = self.inner.get_output_shape(c1)
            o2 = self.inner.get_output_shape(c2)
            if isinstance(o1, tuple) and o1 and isinstance(o1[0], int):
                return (batch,) + tuple(
                    None if x != y else x
                    for x, y in zip(o1[1:], o2[1:]))
            # table outputs with free dims: report the first probe's
            # shapes (conservative; rare)
            return jax.tree_util.tree_map(
                lambda s: (batch,) + tuple(s[1:]), o1)
        out = self.inner.get_output_shape((b,) + rest)
        if isinstance(out, tuple) and out and isinstance(out[0], int):
            return (batch,) + tuple(out[1:])
        return jax.tree_util.tree_map(
            lambda s: (batch,) + tuple(s[1:]), out)


class _Wrap(KerasLayer):
    """KerasLayer over an already-constructed nn module (shape-independent)."""

    def __init__(self, factory, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self._factory = factory

    def _build(self, input_shape):
        return self._factory(input_shape)


# ===================================================================== #
# core                                                                  #
# ===================================================================== #
class Dense(KerasLayer):
    """≙ nn/keras/Dense.scala."""

    def __init__(self, output_dim, activation=None, with_bias=True,
                 w_regularizer=None, b_regularizer=None,
                 input_shape=None, input_dim=None, name=None):
        if input_dim is not None and input_shape is None:
            input_shape = (input_dim,)
        super().__init__(input_shape=input_shape, name=name)
        self.output_dim = output_dim
        self.activation = activation
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def _build(self, input_shape):
        lin = N.Linear(input_shape[-1], self.output_dim,
                       with_bias=self.with_bias,
                       w_regularizer=self.w_regularizer,
                       b_regularizer=self.b_regularizer)
        if self.activation is None:
            return lin
        return N.Sequential().add(lin).add(_act_module(self.activation))


class Activation(KerasLayer):
    def __init__(self, activation, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.activation = activation

    def _build(self, input_shape):
        return _act_module(self.activation)


class Dropout(KerasLayer):
    def __init__(self, p, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.p = p

    def _build(self, input_shape):
        return N.Dropout(init_p=self.p)


class Flatten(KerasLayer):
    def _build(self, input_shape):
        n = int(np.prod(input_shape[1:]))
        return N.Reshape((n,))


class Reshape(KerasLayer):
    def __init__(self, target_shape, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.target_shape = tuple(target_shape)

    def _build(self, input_shape):
        return N.Reshape(self.target_shape)


class Permute(KerasLayer):
    """dims are 1-based over non-batch axes (keras semantics)."""

    def __init__(self, dims, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.dims = tuple(dims)

    def _build(self, input_shape):
        swaps = []
        cur = list(range(len(self.dims)))
        tgt = [d - 1 for d in self.dims]
        for i in range(len(tgt)):
            j = cur.index(tgt[i])
            if i != j:
                swaps.append((i + 1, j + 1))  # 1-based, batch excluded
                cur[i], cur[j] = cur[j], cur[i]
        return N.Transpose(swaps)


class RepeatVector(KerasLayer):
    def __init__(self, n, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.n = n

    def _build(self, input_shape):
        return N.Replicate(self.n, dim=1)


class Masking(KerasLayer):
    def __init__(self, mask_value=0.0, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.mask_value = mask_value

    def _build(self, input_shape):
        return N.Masking(mask_value=self.mask_value)


class Highway(KerasLayer):
    def __init__(self, activation="tanh", with_bias=True,
                 w_regularizer=None, b_regularizer=None,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.activation = activation
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def _build(self, input_shape):
        return N.Highway(input_shape[-1], with_bias=self.with_bias,
                         activation=_act_module(self.activation),
                         w_regularizer=self.w_regularizer,
                         b_regularizer=self.b_regularizer)


class MaxoutDense(KerasLayer):
    def __init__(self, output_dim, nb_feature=4, with_bias=True,
                 w_regularizer=None, b_regularizer=None,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.output_dim = output_dim
        self.nb_feature = nb_feature
        self.with_bias = with_bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def _build(self, input_shape):
        return N.Maxout(input_shape[-1], self.output_dim, self.nb_feature,
                        with_bias=self.with_bias,
                        w_regularizer=self.w_regularizer,
                        b_regularizer=self.b_regularizer)


class Embedding(KerasLayer):
    """≙ nn/keras/Embedding.scala — 0-based indices, unlike nn.LookupTable."""

    def __init__(self, input_dim, output_dim, w_regularizer=None,
                 input_shape=None, input_length=None, name=None):
        if input_length is not None and input_shape is None:
            input_shape = (input_length,)
        super().__init__(input_shape=input_shape, name=name)
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.w_regularizer = w_regularizer

    def _build(self, input_shape):
        lut = N.LookupTable(self.input_dim, self.output_dim,
                            w_regularizer=self.w_regularizer)
        return N.Sequential().add(N.AddConstant(1.0)).add(lut)


class GaussianDropout(KerasLayer):
    def __init__(self, p, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.p = p

    def _build(self, input_shape):
        return N.GaussianDropout(rate=self.p)


class GaussianNoise(KerasLayer):
    def __init__(self, sigma, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.sigma = sigma

    def _build(self, input_shape):
        return N.GaussianNoise(stddev=self.sigma)


class SpatialDropout1D(KerasLayer):
    def __init__(self, p=0.5, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.p = p

    def _build(self, input_shape):
        return N.SpatialDropout1D(init_p=self.p)


class SpatialDropout2D(KerasLayer):
    def __init__(self, p=0.5, dim_ordering="th", input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.p = p

    def _build(self, input_shape):
        return N.SpatialDropout2D(init_p=self.p)


class SpatialDropout3D(KerasLayer):
    def __init__(self, p=0.5, dim_ordering="th", input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.p = p

    def _build(self, input_shape):
        return N.SpatialDropout3D(init_p=self.p)


class BatchNormalization(KerasLayer):
    def __init__(self, epsilon=1e-3, momentum=0.99, beta_init="zero",
                 gamma_init="one", dim_ordering="th",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.epsilon = epsilon
        self.momentum = momentum
        self.dim_ordering = dim_ordering

    def _build(self, input_shape):
        tf_order = self.dim_ordering == "tf"
        if len(input_shape) == 3 and tf_order:
            # (B, T, C) channels-last: per-feature BN over batch+time
            return N.TemporalBatchNormalization(
                input_shape[2], eps=self.epsilon,
                momentum=1.0 - self.momentum)
        n = input_shape[3] if tf_order and len(input_shape) == 4 \
            else input_shape[1]
        if len(input_shape) == 4:
            return N.SpatialBatchNormalization(
                n, eps=self.epsilon, momentum=1.0 - self.momentum,
                format="NHWC" if tf_order else "NCHW")
        return N.BatchNormalization(
            n, eps=self.epsilon, momentum=1.0 - self.momentum)


# ===================================================================== #
# advanced activations                                                  #
# ===================================================================== #
class LeakyReLU(KerasLayer):
    def __init__(self, alpha=0.3, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.alpha = alpha

    def _build(self, input_shape):
        return N.LeakyReLU(negval=self.alpha) \
            if _has_kw(N.LeakyReLU, "negval") else N.LeakyReLU(self.alpha)


class ELU(KerasLayer):
    def __init__(self, alpha=1.0, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.alpha = alpha

    def _build(self, input_shape):
        return N.ELU(self.alpha)


class ThresholdedReLU(KerasLayer):
    def __init__(self, theta=1.0, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.theta = theta

    def _build(self, input_shape):
        return N.Threshold(self.theta, 0.0)


class SReLU(KerasLayer):
    def __init__(self, input_shape=None, shared_axes=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.shared_axes = shared_axes

    def _build(self, input_shape):
        return N.SReLU(input_shape[1:], shared_axes=self.shared_axes)


class SoftMax(KerasLayer):
    def _build(self, input_shape):
        # Keras semantics: normalize the last dim (nn.SoftMax's default
        # is the reference's spatial channel-dim convention)
        return N.SoftMax(axis=-1)


def _has_kw(cls, kw):
    import inspect
    try:
        return kw in inspect.signature(cls.__init__).parameters
    except (TypeError, ValueError):
        return False


# ===================================================================== #
# convolution                                                           #
# ===================================================================== #
def _same_pad(border_mode):
    if border_mode not in ("valid", "same"):
        raise ValueError(f"border_mode must be valid|same, got {border_mode}")
    return -1 if border_mode == "same" else 0


class Convolution1D(KerasLayer):
    """(B, steps, dim) channels-last 1D conv (≙ keras/Convolution1D.scala)."""

    def __init__(self, nb_filter, filter_length, activation=None,
                 border_mode="valid", subsample_length=1,
                 w_regularizer=None, b_regularizer=None, bias=True,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activation
        self.border_mode = border_mode
        self.subsample_length = subsample_length
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.bias = bias

    def _build(self, input_shape):
        if self.border_mode == "same":
            raise ValueError("Convolution1D supports border_mode='valid' "
                             "(reference parity)")
        conv = N.TemporalConvolution(
            input_shape[-1], self.nb_filter, self.filter_length,
            stride_w=self.subsample_length,
            w_regularizer=self.w_regularizer,
            b_regularizer=self.b_regularizer)
        if self.activation is None:
            return conv
        return N.Sequential().add(conv).add(_act_module(self.activation))


class Convolution2D(KerasLayer):
    """(B, C, H, W) channels-first (≙ keras/Convolution2D.scala), or
    channels-last (B, H, W, C) with dim_ordering='tf' — the TPU-native
    NHWC layout, used by the keras-2/tf.keras converter."""

    def __init__(self, nb_filter, nb_row, nb_col, activation=None,
                 border_mode="valid", subsample=(1, 1), dim_ordering="th",
                 w_regularizer=None, b_regularizer=None, bias=True,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = subsample
        self.dim_ordering = dim_ordering
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.bias = bias

    def _build(self, input_shape):
        pad = _same_pad(self.border_mode)
        tf_order = self.dim_ordering == "tf"
        in_ch = input_shape[3] if tf_order else input_shape[1]
        conv = N.SpatialConvolution(
            in_ch, self.nb_filter, self.nb_col, self.nb_row,
            stride_w=self.subsample[1], stride_h=self.subsample[0],
            pad_w=pad, pad_h=pad, with_bias=self.bias,
            w_regularizer=self.w_regularizer,
            b_regularizer=self.b_regularizer,
            format="NHWC" if tf_order else "NCHW")
        if self.activation is None:
            return conv
        return N.Sequential().add(conv).add(_act_module(self.activation))


class Convolution3D(KerasLayer):
    def __init__(self, nb_filter, kernel_dim1, kernel_dim2, kernel_dim3,
                 activation=None, border_mode="valid", subsample=(1, 1, 1),
                 dim_ordering="th", w_regularizer=None, b_regularizer=None,
                 bias=True, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.args = (nb_filter, kernel_dim1, kernel_dim2, kernel_dim3)
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = subsample
        self.bias = bias
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def _build(self, input_shape):
        nb, k1, k2, k3 = self.args
        pad = _same_pad(self.border_mode)
        conv = N.VolumetricConvolution(
            input_shape[1], nb, k1, k3, k2,
            d_t=self.subsample[0], d_w=self.subsample[2],
            d_h=self.subsample[1], pad_t=pad, pad_w=pad, pad_h=pad,
            with_bias=self.bias, w_regularizer=self.w_regularizer,
            b_regularizer=self.b_regularizer)
        if self.activation is None:
            return conv
        return N.Sequential().add(conv).add(_act_module(self.activation))


class AtrousConvolution1D(KerasLayer):
    """Dilated 1D conv via a (1, W) dilated 2D conv on (B, C, 1, steps)."""

    def __init__(self, nb_filter, filter_length, activation=None,
                 subsample_length=1, atrous_rate=1,
                 w_regularizer=None, b_regularizer=None,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activation
        self.subsample_length = subsample_length
        self.atrous_rate = atrous_rate
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def _build(self, input_shape):
        dim = input_shape[-1]
        # steps ride the H axis of a (B, dim, steps, 1) image
        conv = N.SpatialDilatedConvolution(
            dim, self.nb_filter, 1, self.filter_length,
            dw=1, dh=self.subsample_length,
            dilation_w=1, dilation_h=self.atrous_rate,
            w_regularizer=self.w_regularizer,
            b_regularizer=self.b_regularizer)
        seq = (N.Sequential()
               .add(N.Transpose([(1, 2)]))       # (B, dim, steps)
               .add(N.Unsqueeze(3))              # (B, dim, steps, 1)
               .add(conv)
               .add(N.Squeeze(4))                # (B, nb, steps')
               .add(N.Transpose([(1, 2)])))      # (B, steps', nb)
        if self.activation is not None:
            seq.add(_act_module(self.activation))
        return seq


class AtrousConvolution2D(KerasLayer):
    def __init__(self, nb_filter, nb_row, nb_col, activation=None,
                 subsample=(1, 1), atrous_rate=(1, 1), dim_ordering="th",
                 w_regularizer=None, b_regularizer=None,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.activation = activation
        self.subsample = subsample
        self.atrous_rate = atrous_rate
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def _build(self, input_shape):
        conv = N.SpatialDilatedConvolution(
            input_shape[1], self.nb_filter, self.nb_col, self.nb_row,
            dw=self.subsample[1], dh=self.subsample[0],
            dilation_w=self.atrous_rate[1], dilation_h=self.atrous_rate[0],
            w_regularizer=self.w_regularizer,
            b_regularizer=self.b_regularizer)
        if self.activation is None:
            return conv
        return N.Sequential().add(conv).add(_act_module(self.activation))


class Deconvolution2D(KerasLayer):
    def __init__(self, nb_filter, nb_row, nb_col, activation=None,
                 subsample=(1, 1), dim_ordering="th",
                 w_regularizer=None, b_regularizer=None, bias=True,
                 border_mode="valid", input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.activation = activation
        self.subsample = subsample
        self.dim_ordering = dim_ordering
        self.border_mode = border_mode
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.bias = bias

    def _build(self, input_shape):
        tf_order = self.dim_ordering == "tf"
        in_ch = input_shape[3] if tf_order else input_shape[1]
        sh, sw = self.subsample
        if self.border_mode == "same":
            # keras/TF SAME transpose conv: out = in*stride.  Our module
            # emits (in-1)*s - 2*pad + k + adj, so per dim
            # pad = max(k-s, 0)//2 and adj = s - k + 2*pad (absorbs the
            # odd remainder; equals s-k when kernel < stride).
            ph = max(self.nb_row - sh, 0) // 2
            pw = max(self.nb_col - sw, 0) // 2
            ah = sh - self.nb_row + 2 * ph
            aw = sw - self.nb_col + 2 * pw
        else:
            ph = pw = ah = aw = 0
        conv = N.SpatialFullConvolution(
            in_ch, self.nb_filter, self.nb_col, self.nb_row,
            dw=sw, dh=sh, pad_w=pw, pad_h=ph, adj_w=aw, adj_h=ah,
            no_bias=not self.bias,
            format="NHWC" if tf_order else "NCHW",
            w_regularizer=self.w_regularizer,
            b_regularizer=self.b_regularizer)
        if self.activation is None:
            return conv
        return N.Sequential().add(conv).add(_act_module(self.activation))


class SeparableConvolution2D(KerasLayer):
    def __init__(self, nb_filter, nb_row, nb_col, activation=None,
                 border_mode="valid", subsample=(1, 1), depth_multiplier=1,
                 dim_ordering="th", depthwise_regularizer=None,
                 pointwise_regularizer=None, b_regularizer=None, bias=True,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = subsample
        self.depth_multiplier = depth_multiplier
        self.bias = bias
        self.depthwise_regularizer = depthwise_regularizer
        self.pointwise_regularizer = pointwise_regularizer
        self.b_regularizer = b_regularizer
        self.dim_ordering = dim_ordering

    def _build(self, input_shape):
        pad = _same_pad(self.border_mode)
        tf_order = self.dim_ordering == "tf"
        in_ch = input_shape[3] if tf_order else input_shape[1]
        conv = N.SpatialSeparableConvolution(
            in_ch, self.nb_filter, self.depth_multiplier,
            self.nb_col, self.nb_row, sw=self.subsample[1],
            sh=self.subsample[0], pw=pad, ph=pad, with_bias=self.bias,
            data_format="NHWC" if tf_order else "NCHW",
            w_regularizer=self.depthwise_regularizer,
            p_regularizer=self.pointwise_regularizer,
            b_regularizer=self.b_regularizer)
        if self.activation is None:
            return conv
        return N.Sequential().add(conv).add(_act_module(self.activation))


class LocallyConnected1D(KerasLayer):
    def __init__(self, nb_filter, filter_length, activation=None,
                 subsample_length=1, w_regularizer=None, b_regularizer=None,
                 bias=True, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activation
        self.subsample_length = subsample_length

    def _build(self, input_shape):
        conv = N.LocallyConnected1D(
            input_shape[1], input_shape[2], self.nb_filter,
            self.filter_length, stride_w=self.subsample_length)
        if self.activation is None:
            return conv
        return N.Sequential().add(conv).add(_act_module(self.activation))


class LocallyConnected2D(KerasLayer):
    def __init__(self, nb_filter, nb_row, nb_col, activation=None,
                 border_mode="valid", subsample=(1, 1), dim_ordering="th",
                 bias=True, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = nb_filter
        self.nb_row = nb_row
        self.nb_col = nb_col
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = subsample

    def _build(self, input_shape):
        pad = _same_pad(self.border_mode)
        conv = N.LocallyConnected2D(
            input_shape[1], input_shape[3], input_shape[2], self.nb_filter,
            self.nb_col, self.nb_row, stride_w=self.subsample[1],
            stride_h=self.subsample[0], pad_w=pad, pad_h=pad)
        if self.activation is None:
            return conv
        return N.Sequential().add(conv).add(_act_module(self.activation))


# ===================================================================== #
# pooling                                                               #
# ===================================================================== #
class MaxPooling1D(KerasLayer):
    def __init__(self, pool_length=2, stride=None, border_mode="valid",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.pool_length = pool_length
        self.stride = stride or pool_length

    def _build(self, input_shape):
        return N.TemporalMaxPooling(self.pool_length, self.stride)


class MaxPooling2D(KerasLayer):
    def __init__(self, pool_size=(2, 2), strides=None, border_mode="valid",
                 dim_ordering="th", input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.pool_size = pool_size
        self.strides = strides or pool_size
        self.border_mode = border_mode
        self.dim_ordering = dim_ordering

    def _build(self, input_shape):
        pad = _same_pad(self.border_mode)
        return N.SpatialMaxPooling(
            self.pool_size[1], self.pool_size[0],
            dw=self.strides[1], dh=self.strides[0], pad_w=pad, pad_h=pad,
            format="NHWC" if self.dim_ordering == "tf" else "NCHW")


class MaxPooling3D(KerasLayer):
    def __init__(self, pool_size=(2, 2, 2), strides=None,
                 border_mode="valid", dim_ordering="th",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.pool_size = pool_size
        self.strides = strides or pool_size

    def _build(self, input_shape):
        p, s = self.pool_size, self.strides
        return N.VolumetricMaxPooling(p[0], p[2], p[1], s[0], s[2], s[1])


class AveragePooling1D(KerasLayer):
    def __init__(self, pool_length=2, stride=None, border_mode="valid",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.pool_length = pool_length
        self.stride = stride or pool_length

    def _build(self, input_shape):
        # (B, steps, dim) -> (B, dim, steps, 1) -> pool H -> back
        pool = N.SpatialAveragePooling(1, self.pool_length,
                                       dw=1, dh=self.stride)
        return (N.Sequential()
                .add(N.Transpose([(1, 2)])).add(N.Unsqueeze(3))
                .add(pool)
                .add(N.Squeeze(4)).add(N.Transpose([(1, 2)])))


class AveragePooling2D(KerasLayer):
    def __init__(self, pool_size=(2, 2), strides=None, border_mode="valid",
                 dim_ordering="th", input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.pool_size = pool_size
        self.strides = strides or pool_size
        self.border_mode = border_mode
        self.dim_ordering = dim_ordering

    def _build(self, input_shape):
        pad = _same_pad(self.border_mode)
        return N.SpatialAveragePooling(
            self.pool_size[1], self.pool_size[0],
            dw=self.strides[1], dh=self.strides[0], pad_w=pad, pad_h=pad,
            format="NHWC" if self.dim_ordering == "tf" else "NCHW")


class AveragePooling3D(KerasLayer):
    def __init__(self, pool_size=(2, 2, 2), strides=None,
                 border_mode="valid", dim_ordering="th",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.pool_size = pool_size
        self.strides = strides or pool_size

    def _build(self, input_shape):
        p, s = self.pool_size, self.strides
        return N.VolumetricAveragePooling(p[0], p[2], p[1], s[0], s[2], s[1])


class _GlobalPool(KerasLayer):
    _mean = True
    dim_ordering = "th"

    def __init__(self, dim_ordering="th", input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.dim_ordering = dim_ordering

    def _build(self, input_shape):
        nd = len(input_shape)
        if self.dim_ordering == "tf":
            axes = list(range(1, nd - 1))  # spatial dims (channels-last)
        else:
            axes = list(range(2, nd))      # spatial dims (channels-first)
        op = N.Mean if self._mean else N.Max
        seq = N.Sequential()
        for ax in reversed(axes):          # reduce innermost first
            seq.add(op(dimension=ax + 1) if _has_kw(op, "dimension")
                    else op(ax + 1))
        return seq


class GlobalAveragePooling2D(_GlobalPool):
    _mean = True


class GlobalMaxPooling2D(_GlobalPool):
    _mean = False


class GlobalAveragePooling3D(_GlobalPool):
    _mean = True


class GlobalMaxPooling3D(_GlobalPool):
    _mean = False


class GlobalAveragePooling1D(KerasLayer):
    def _build(self, input_shape):
        return N.Mean(2)  # (B, steps, dim) -> mean over steps


class GlobalMaxPooling1D(KerasLayer):
    def _build(self, input_shape):
        return N.Max(2)


# ===================================================================== #
# padding / cropping / upsampling                                       #
# ===================================================================== #
class ZeroPadding1D(KerasLayer):
    def __init__(self, padding=1, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.padding = padding

    def _build(self, input_shape):
        p = self.padding
        left, right = (p, p) if isinstance(p, int) else p
        seq = N.Sequential()
        seq.add(N.Padding(2, -left, 3))
        seq.add(N.Padding(2, right, 3))
        return seq


class ZeroPadding2D(KerasLayer):
    def __init__(self, padding=(1, 1), dim_ordering="th",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.padding = padding
        self.dim_ordering = dim_ordering

    def _build(self, input_shape):
        fmt = "NHWC" if self.dim_ordering == "tf" else "NCHW"
        p = self.padding
        if len(p) == 2 and all(isinstance(v, (list, tuple)) for v in p):
            (pt, pb), (pl, pr) = p      # keras-2 ((top,bottom),(l,r))
        else:
            (pt, pb), (pl, pr) = (p[0], p[0]), (p[1], p[1])
        return N.SpatialZeroPadding(pl, pr, pt, pb, format=fmt)


class ZeroPadding3D(KerasLayer):
    def __init__(self, padding=(1, 1, 1), dim_ordering="th",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.padding = padding

    def _build(self, input_shape):
        p1, p2, p3 = self.padding
        seq = N.Sequential()
        # dims are 1-based over non-batch axes (C=1, D1=2, D2=3, D3=4)
        for dim, p in ((2, p1), (3, p2), (4, p3)):
            seq.add(N.Padding(dim, -p, 4))
            seq.add(N.Padding(dim, p, 4))
        return seq


class Cropping1D(KerasLayer):
    def __init__(self, cropping=(1, 1), input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.cropping = cropping

    def _build(self, input_shape):
        a, b = self.cropping
        steps = input_shape[1]
        return N.Narrow(2, a + 1, steps - a - b)


class Cropping2D(KerasLayer):
    def __init__(self, cropping=((0, 0), (0, 0)), dim_ordering="th",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.cropping = cropping
        self.dim_ordering = dim_ordering

    def _build(self, input_shape):
        return N.Cropping2D(list(self.cropping[0]), list(self.cropping[1]),
                            format="NHWC" if self.dim_ordering == "tf"
                            else "NCHW")


class Cropping3D(KerasLayer):
    def __init__(self, cropping=((1, 1), (1, 1), (1, 1)),
                 dim_ordering="th", input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.cropping = cropping

    def _build(self, input_shape):
        c = self.cropping
        return N.Cropping3D(list(c[0]), list(c[1]), list(c[2]))


class UpSampling1D(KerasLayer):
    def __init__(self, length=2, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.length = length

    def _build(self, input_shape):
        return N.UpSampling1D(self.length)


class UpSampling2D(KerasLayer):
    def __init__(self, size=(2, 2), dim_ordering="th",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.size = size
        self.dim_ordering = dim_ordering

    def _build(self, input_shape):
        return N.UpSampling2D(self.size,
                              format="NHWC" if self.dim_ordering == "tf"
                              else "NCHW")


class UpSampling3D(KerasLayer):
    def __init__(self, size=(2, 2, 2), dim_ordering="th",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.size = size

    def _build(self, input_shape):
        return N.UpSampling3D(self.size)


# ===================================================================== #
# recurrent                                                             #
# ===================================================================== #
class _KerasRecurrent(KerasLayer):
    def __init__(self, output_dim, activation="tanh",
                 inner_activation="hard_sigmoid", return_sequences=False,
                 go_backwards=False, w_regularizer=None, u_regularizer=None,
                 b_regularizer=None, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.output_dim = output_dim
        self.activation = activation
        self.inner_activation = inner_activation
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards
        self.w_regularizer = w_regularizer
        self.u_regularizer = u_regularizer
        self.b_regularizer = b_regularizer

    def _cell(self, input_dim):
        raise NotImplementedError

    def _build(self, input_shape):
        seq = N.Sequential()
        if self.go_backwards:
            seq.add(N.Reverse(2))
        seq.add(N.Recurrent().add(self._cell(input_shape[-1])))
        if not self.return_sequences:
            seq.add(N.Select(2, -1))
        return seq


class SimpleRNN(_KerasRecurrent):
    def _cell(self, input_dim):
        return N.RnnCell(input_dim, self.output_dim,
                         activation=_act_module(self.activation),
                         w_regularizer=self.w_regularizer,
                         u_regularizer=self.u_regularizer,
                         b_regularizer=self.b_regularizer) \
            if _has_kw(N.RnnCell, "u_regularizer") else \
            N.RnnCell(input_dim, self.output_dim,
                      activation=_act_module(self.activation))


class LSTM(_KerasRecurrent):
    def _cell(self, input_dim):
        # defaults (tanh / sigmoid) match the nn.LSTM cell's built-ins;
        # only non-default activations need wrapping as modules
        act = None if self.activation in (None, "tanh") \
            else _act_module(self.activation)
        inner = None if self.inner_activation in (None, "sigmoid") \
            else _act_module(self.inner_activation)
        return N.LSTM(input_dim, self.output_dim, activation=act,
                      inner_activation=inner)


class GRU(_KerasRecurrent):
    def __init__(self, *a, reset_after=False, **kw):
        super().__init__(*a, **kw)
        self.reset_after = reset_after

    def _cell(self, input_dim):
        return N.GRU(input_dim, self.output_dim,
                     reset_after=self.reset_after)


class ConvLSTM2D(KerasLayer):
    def __init__(self, nb_filter, nb_kernel, return_sequences=False,
                 go_backwards=False, border_mode="same",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = nb_filter
        self.nb_kernel = nb_kernel
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards

    def _build(self, input_shape):
        cell = N.ConvLSTMPeephole(
            input_shape[2], self.nb_filter, self.nb_kernel, self.nb_kernel)
        seq = N.Sequential()
        if self.go_backwards:
            seq.add(N.Reverse(2))
        seq.add(N.Recurrent().add(cell))
        if not self.return_sequences:
            seq.add(N.Select(2, -1))
        return seq


class Bidirectional(KerasLayer):
    """Wraps a keras recurrent layer; merge_mode concat|sum|mul|ave|max."""

    def __init__(self, layer: _KerasRecurrent, merge_mode="concat",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.layer = layer
        self.merge_mode = merge_mode

    def _build(self, input_shape):
        merges = {"concat": lambda: N.JoinTable(2, 2),
                  "sum": N.CAddTable, "mul": N.CMulTable,
                  "max": N.CMaxTable, "ave": N.CAveTable}
        rec = N.BiRecurrent(merge=merges[self.merge_mode]())
        rec.add(self.layer._cell(input_shape[-1]))
        seq = N.Sequential().add(rec)
        if not self.layer.return_sequences:
            seq.add(N.Select(2, -1))
        return seq


class TimeDistributed(KerasLayer):
    def __init__(self, layer: KerasLayer, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.layer = layer

    def _build(self, input_shape):
        inner = self.layer.build((input_shape[0],) + tuple(input_shape[2:]))
        return N.TimeDistributed(inner)


# ===================================================================== #
# merge                                                                 #
# ===================================================================== #
class Merge(KerasLayer):
    """Merge a table of inputs (≙ keras/Merge.scala). Used on Table input
    or with `layers=` inside Sequential."""

    def __init__(self, layers=None, mode="sum", concat_axis=-1,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.layers = layers
        self.mode = mode
        self.concat_axis = concat_axis

    def _build(self, input_shape):
        mode = self.mode
        if mode == "concat":
            # input_shape here is the shape of ONE branch; 1-based join dim
            nd = len(input_shape)
            merge = N.JoinTable(nd if self.concat_axis == -1 else
                                self.concat_axis + 1)
        else:
            table = {"sum": N.CAddTable, "mul": N.CMulTable,
                     "max": N.CMaxTable, "ave": N.CAveTable,
                     "dot": N.DotProduct, "cosine": N.CosineDistance}
            merge = table[mode]()
        if self.layers:
            par = N.ParallelTable()
            for l in self.layers:
                par.add(l.ensure_built() if isinstance(l, KerasLayer) else l)
            return N.Sequential().add(par).add(merge)
        return merge

    def compute_output_shape_multi(self, shapes):
        """Output shape from ALL branch shapes (graph nodes with several
        inbound edges — the single-shape compute_output_shape only sees
        one branch, which under-counts concat)."""
        base = tuple(shapes[0])
        if self.mode == "concat":
            nd = len(base)
            ax = (nd - 1) if self.concat_axis == -1 else self.concat_axis
            out = list(base)
            out[ax] = sum(s[ax] for s in shapes)
            return tuple(out)
        if self.mode in ("dot", "cosine"):
            return (base[0], 1)
        return base                       # sum/mul/max/ave: elementwise

    # -- branch-tower (layers=) support: the layer's input is a TABLE of
    #    branch inputs, so the single-tensor KerasLayer shape machinery
    #    must be bypassed -------------------------------------------------
    def _branch_out_shapes(self):
        outs = []
        for l in self.layers:
            shp = getattr(l, "output_shape", None)
            if shp is not None:
                outs.append(tuple(shp))
            elif isinstance(l, KerasLayer) and l.input_shape is not None:
                outs.append(tuple(l.compute_output_shape(
                    (None,) + tuple(l.input_shape))))
            else:
                raise ValueError(
                    f"{self.name}: branch {getattr(l, 'name', l)} has no "
                    "inferable output shape")
        return outs

    def ensure_built(self):
        if self.inner is None and self.layers:
            self.build(self._branch_out_shapes()[0])
        return super().ensure_built()

    def compute_output_shape(self, input_shape=None):
        if not self.layers:
            return super().compute_output_shape(input_shape)
        self.ensure_built()
        outs = self._branch_out_shapes()
        if self.mode == "concat":
            ax = self.concat_axis if self.concat_axis != -1 \
                else len(outs[0]) - 1
            base = list(outs[0])
            base[ax] = sum(o[ax] for o in outs)
            return tuple(base)
        if self.mode in ("dot", "cosine"):
            # reducing modes: row-wise scalar per sample
            return (outs[0][0],)
        return outs[0]


def merge(inputs, mode="sum", concat_axis=-1, name=None):
    return Merge(mode=mode, concat_axis=concat_axis, name=name)(inputs)
