"""bigdl_tpu.kernels — hand-written Pallas TPU kernels for hot paths the
XLA fusion heuristics leave on the table.

Import contract: this package must import cleanly on any backend —
Pallas TPU support is probed lazily and every kernel ships an
``interpret=True`` fallback so CPU tier-1 tests and the MULTICHIP
dryruns execute the *kernel code path itself*, not a shadow
implementation.  (The attention kernel predates this package and lives
in :mod:`bigdl_tpu.ops.flash_attention`.)
"""
from .fused_optim import (fused_adam_available, fused_adam_update,
                          fused_sgd_update)

__all__ = ["fused_adam_available", "fused_adam_update", "fused_sgd_update"]
