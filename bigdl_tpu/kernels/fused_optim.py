"""Fused optimizer-update Pallas kernels (Adam / AdamW / SGD-momentum).

``optim_method.py`` expresses each update as ~10 ``tree_map`` HLO ops per
leaf (two moment EMAs, bias corrections, rsqrt, the axpy); XLA usually
fuses them, but every op still makes a scheduling decision and the fused
group re-reads params/moments from HBM when the fusion splits.  These
kernels do the whole update in ONE pass per leaf: a grid over
(rows, 128)-blocks held in VMEM, each block reading param/moment/grad
exactly once and writing the new param/moments exactly once — the
optimizer update becomes a pure HBM-bandwidth stream.

Contract:

  * **Same math, same op order** as the reference ``update()`` methods.
    Bit-for-bit parity with the jitted tree-map path holds whenever XLA
    codegen makes consistent FMA-contraction choices across the two
    program structures: on the XLA CPU *thunk* runtime the choice is
    per-fusion-cluster, so Adam's ``b*m + (1-b)*g`` EMA can contract in
    one program and not the other — a measured 1-ulp/step drift on
    params (moments stay bitwise).  ``tests/test_fused_optim.py``
    therefore asserts BITWISE parity in a subprocess with
    ``--xla_cpu_use_thunk_runtime=false`` (consistent contraction,
    verified exact over multi-step runs) and tight-allclose parity
    in-process on the default runtime.  SGD (no division chain) is
    bitwise on both runtimes.
  * **interpret=True fallback off-TPU**: CPU tier-1 and the MULTICHIP
    dryruns execute the kernel body through the Pallas interpreter, so
    the code path tested on CPU is the one that runs on hardware.
  * Leaves the kernel cannot tile (non-f32 dtypes, empty leaves) fall
    back to the reference math per leaf — identical numerics, no
    silent skips: the choice is static per leaf shape/dtype.
  * Import never requires Pallas: probing failure degrades the whole
    module to the reference path (``fused_adam_available() == False``).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # Pallas TPU lowering is optional; interpret mode needs only core jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover - environment without pallas
    pl = None
    pltpu = None
    _HAS_PALLAS = False

# Test hook mirroring ops/flash_attention._INTERPRET: force interpret mode
# even where a TPU backend is present.
_FORCE_INTERPRET = False

_LANES = 128        # VPU lane width: last dim of every block
_SUBLANES = 8       # f32 sublane quantum
_BLOCK_ROWS = 256   # rows per grid step: 7 f32 operands ~ 0.9 MB VMEM


def fused_adam_available() -> bool:
    """Can the fused kernels run here (natively or interpreted)?"""
    return _HAS_PALLAS


def _interpret() -> bool:
    return _FORCE_INTERPRET or jax.default_backend() != "tpu"


def _leaf_ok(leaf) -> bool:
    """Static per-leaf eligibility: the kernel tiles f32 onto (8, 128)."""
    return (_HAS_PALLAS and getattr(leaf, "size", 0) > 0
            and getattr(leaf, "dtype", None) == jnp.float32)


def _scalar(x):
    return jnp.asarray(x, jnp.float32).reshape(1)


def _unzip(tuple_tree, n):
    """Split a tree whose leaves are n-tuples into n same-structure
    trees (the per-leaf kernels return (new_p, new_m, ...) tuples)."""
    flat, treedef = jax.tree_util.tree_flatten(
        tuple_tree, is_leaf=lambda x: isinstance(x, tuple))
    return tuple(jax.tree_util.tree_unflatten(treedef, [t[i] for t in flat])
                 for i in range(n))


def _run_blocked(kernel, scalars, arrays, n_out):
    """Run an elementwise kernel over same-shape f32 arrays.

    Arrays are raveled, zero-padded to a whole number of
    ``(block_rows, 128)`` tiles and streamed block-by-block through VMEM;
    scalars ride SMEM.  Zero padding is safe for every optimizer update
    here (0 grads + 0 moments -> 0 update) and the pad region is sliced
    off before returning.
    """
    shape, dtype = arrays[0].shape, arrays[0].dtype
    size = arrays[0].size
    rows = -(-size // _LANES)
    rows = -(-rows // _SUBLANES) * _SUBLANES
    block_rows = min(rows, _BLOCK_ROWS)
    rows = -(-rows // block_rows) * block_rows
    pad = rows * _LANES - size

    def prep(a):
        return jnp.pad(a.ravel(), (0, pad)).reshape(rows, _LANES)

    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0))
    outs = pl.pallas_call(
        kernel,
        grid=(rows // block_rows,),
        in_specs=[smem] * len(scalars) + [vmem] * len(arrays),
        out_specs=[vmem] * n_out,
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), dtype)] * n_out,
        interpret=_interpret(),
    )(*[_scalar(s) for s in scalars], *[prep(a) for a in arrays])
    return [o.ravel()[:size].reshape(shape) for o in outs]


# --------------------------------------------------------------------- #
# Adam / AdamW                                                          #
# --------------------------------------------------------------------- #
def _adam_kernel(clr_ref, bc1_ref, bc2_ref, p_ref, m_ref, v_ref, g_ref,
                 np_ref, nm_ref, nv_ref, *, beta1, beta2, eps,
                 weight_decay):
    # op order mirrors optim_method.Adam.update exactly (bit parity)
    g = g_ref[...]
    p = p_ref[...]
    m = beta1 * m_ref[...] + (1 - beta1) * g
    v = beta2 * v_ref[...] + (1 - beta2) * g * g
    clr = clr_ref[0]
    upd = clr * (m / bc1_ref[0]) / (jnp.sqrt(v / bc2_ref[0]) + eps)
    new_p = p - upd
    if weight_decay:                 # AdamW's decoupled decay, post-update
        new_p = new_p - clr * weight_decay * p
    np_ref[...] = new_p
    nm_ref[...] = m
    nv_ref[...] = v


def fused_adam_update(params, grads, m, v, *, clr, bc1, bc2, beta1, beta2,
                      eps, weight_decay=0.0):
    """One-pass Adam(W) update over a pytree.

    ``clr``/``bc1``/``bc2`` are the (possibly traced) step-dependent
    scalars the caller already computed; ``weight_decay`` > 0 applies
    AdamW's decoupled decay inside the same pass.  Returns
    ``(new_params, new_m, new_v)``.
    """
    kernel = functools.partial(_adam_kernel, beta1=beta1, beta2=beta2,
                               eps=eps, weight_decay=weight_decay)

    def upd(p, g, m_, v_):
        if _leaf_ok(p) and p.dtype == g.dtype == m_.dtype == v_.dtype:
            new_p, new_m, new_v = _run_blocked(
                kernel, (clr, bc1, bc2), (p, m_, v_, g), 3)
            return new_p, new_m, new_v
        # reference math, identical op order (non-f32 / empty leaves)
        new_m = beta1 * m_ + (1 - beta1) * g
        new_v = beta2 * v_ + (1 - beta2) * g * g
        new_p = p - (clr * (new_m / bc1)
                     / (jnp.sqrt(new_v / bc2) + eps)).astype(p.dtype)
        if weight_decay:
            new_p = new_p - clr * weight_decay * p
        return new_p, new_m, new_v

    return _unzip(jax.tree_util.tree_map(upd, params, grads, m, v), 3)


# --------------------------------------------------------------------- #
# SGD (momentum / nesterov / plain)                                     #
# --------------------------------------------------------------------- #
def _sgd_mom_kernel(clr_ref, p_ref, v_ref, g_ref, np_ref, nv_ref, *,
                    momentum, dampening, nesterov, weight_decay):
    g = g_ref[...]
    p = p_ref[...]
    if weight_decay > 0:
        g = g + weight_decay * p
    vel = momentum * v_ref[...] + (1.0 - dampening) * g
    step = g + momentum * vel if nesterov else vel
    np_ref[...] = p - clr_ref[0] * step
    nv_ref[...] = vel


def _sgd_plain_kernel(clr_ref, p_ref, g_ref, np_ref, *, weight_decay):
    g = g_ref[...]
    p = p_ref[...]
    if weight_decay > 0:
        g = g + weight_decay * p
    np_ref[...] = p - clr_ref[0] * g


def fused_sgd_update(params, grads, velocity=None, *, clr, momentum=0.0,
                     dampening=0.0, nesterov=False, weight_decay=0.0):
    """One-pass SGD update over a pytree; ``velocity=None`` selects the
    momentum-free kernel.  Returns ``(new_params, new_velocity)`` with
    ``new_velocity=None`` in the plain case."""
    if momentum > 0 and velocity is not None:
        kernel = functools.partial(
            _sgd_mom_kernel, momentum=momentum, dampening=dampening,
            nesterov=nesterov, weight_decay=weight_decay)

        def upd(p, g, v_):
            if _leaf_ok(p) and p.dtype == g.dtype == v_.dtype:
                new_p, new_v = _run_blocked(kernel, (clr,), (p, v_, g), 2)
                return new_p, new_v
            if weight_decay > 0:
                g = g + weight_decay * p
            vel = momentum * v_ + (1.0 - dampening) * g
            step = g + momentum * vel if nesterov else vel
            return p - clr * step.astype(p.dtype), vel

        return _unzip(jax.tree_util.tree_map(upd, params, grads, velocity),
                      2)

    kernel = functools.partial(_sgd_plain_kernel, weight_decay=weight_decay)

    def upd_plain(p, g):
        if _leaf_ok(p) and p.dtype == g.dtype:
            return _run_blocked(kernel, (clr,), (p, g), 1)[0]
        if weight_decay > 0:
            g = g + weight_decay * p
        return p - clr * g.astype(p.dtype)

    return jax.tree_util.tree_map(upd_plain, params, grads), None
