"""GL006 — naive retry loops & silently swallowed I/O errors.

The bug family this PR's retry layer retires:

  GL006-a  a ``while``/``for`` loop that calls ``time.sleep(<literal>)``
           directly in its body — the constant-sleep retry/poll shape.
           No backoff means a persistent failure burns CPU at a fixed
           rate forever; no jitter means every worker retries in
           lockstep (thundering herd on the shared filesystem the
           failure came from); no deadline means the loop outlives the
           caller's patience.  Use
           :class:`bigdl_tpu.utils.retry.RetryPolicy` (exponential
           backoff + full jitter + wall-clock deadline) for retries,
           or ``Event.wait(timeout)`` for polls that should wake early.

  GL006-b  ``except OSError: pass`` (or ``IOError``, or a tuple
           containing either) — an I/O failure reduced to silence.
           The checkpoint-GC shape: one un-deletable dir and the sweep
           "works" while the disk quietly fills.  Log it and count it
           (``rec.inc``), or classify it through the retry layer;
           best-effort paths that really may ignore the error say so
           in the baseline justification.

Library-only: a test's poll loop is its synchronization, a timing
script's sleep is its measurement, and test cleanup may ignore I/O
errors by design.
"""
from __future__ import annotations

import ast
from typing import List

from .base import (Project, Rule, SourceFile, Violation, ancestors,
                   call_name)

_IO_EXC_NAMES = ("OSError", "IOError", "EnvironmentError")


def _sleep_literal(call: ast.Call) -> bool:
    if call_name(call) not in ("time.sleep", "sleep"):
        return False
    if not call.args:
        return False
    arg = call.args[0]
    return isinstance(arg, ast.Constant) \
        and isinstance(arg.value, (int, float))


def _directly_in_loop(node: ast.AST) -> bool:
    """True when the nearest loop/function ancestor is a loop: a sleep
    inside a nested def is that function's business, not the loop's."""
    for a in ancestors(node):
        if isinstance(a, (ast.While, ast.For, ast.AsyncFor)):
            return True
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return False
    return False


def _names_io_error(expr: ast.AST) -> bool:
    if expr is None:
        return False
    if isinstance(expr, ast.Tuple):
        return any(_names_io_error(e) for e in expr.elts)
    name = ""
    if isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Attribute):
        name = expr.attr
    return name in _IO_EXC_NAMES


class GL006Retry(Rule):
    id = "GL006"
    title = "naive retry loops & swallowed I/O errors"
    library_only = True

    def check(self, src: SourceFile, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call) and _sleep_literal(node) \
                    and _directly_in_loop(node):
                out.append(self.violation(
                    src, node,
                    "constant time.sleep() in a retry/poll loop: no "
                    "backoff, no jitter, no deadline — use "
                    "utils.retry.RetryPolicy for retries or "
                    "Event.wait(timeout) for polls"))
            if isinstance(node, ast.ExceptHandler) \
                    and _names_io_error(node.type) \
                    and all(isinstance(stmt, ast.Pass)
                            for stmt in node.body):
                out.append(self.violation(
                    src, node,
                    "except OSError: pass swallows an I/O failure "
                    "silently; log + count it (rec.inc) or classify "
                    "it via utils.retry — justify genuine best-effort "
                    "paths in the baseline"))
        return out
