"""GL003 — lock & signal-handler discipline.

The PR-4 review-tax class.  Three shapes:

  GL003-a  an instance attribute written both *inside* a
           ``with self._lock`` block and *outside* one (in different
           methods of the same class).  The unguarded write races the
           guarded readers; the GIL makes each write atomic but not the
           read-modify-write and check-then-act sequences around it.
           The ``*_locked`` method-name suffix declares "caller holds
           the lock" (kernel-style) and counts as guarded.

  GL003-b  an attribute written from two or more methods with *no* lock
           at any write site, in a class that owns a lock and guards
           other attributes with it — mixed discipline.  Either the
           attribute is thread-shared (guard it) or it is not (say so
           in the baseline justification).

  GL003-c  ``signal.signal(sig, handler)`` installing a locally-defined
           handler while discarding the previous one — no chaining, no
           restore.  PR 4 needed three review passes to get SIGTERM
           chaining right between the PreemptionHandler and the
           FlightRecorder; an unchained install silently eats whichever
           of them ran first.  Saving the return value or calling
           ``signal.getsignal`` first passes; restoring ``SIG_DFL`` /
           ``SIG_IGN`` / a saved previous handler passes.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .base import (Project, Rule, SourceFile, Violation, dotted_name,
                   enclosing_function, lock_attrs, self_attr_writes,
                   under_with_lock)

# attributes a class conventionally mutates single-threadedly at setup
_SETUP_METHODS = ("__init__", "__post_init__", "__del__", "__enter__",
                  "__exit__")


class GL003Locks(Rule):
    id = "GL003"
    title = "lock & signal-handler discipline"

    def check(self, src: SourceFile, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for cls in ast.walk(src.tree):
            if isinstance(cls, ast.ClassDef):
                out.extend(self._check_class(src, cls))
        out.extend(self._check_signals(src))
        return out

    # -- a/b: shared-attribute discipline ------------------------------- #
    def _check_class(self, src: SourceFile, cls: ast.ClassDef
                     ) -> List[Violation]:
        yield_list: List[Violation] = []
        locks = lock_attrs(cls)
        if not locks:
            return yield_list
        # per attribute: guarded / unguarded write sites (method, node)
        guarded: Dict[str, List[Tuple[str, ast.AST]]] = {}
        unguarded: Dict[str, List[Tuple[str, ast.AST]]] = {}
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if meth.name in _SETUP_METHODS:
                continue
            for attr, node in self_attr_writes(meth):
                if attr in locks:
                    continue
                fn = enclosing_function(node)
                scope = fn.name if fn is not None else meth.name
                if under_with_lock(node, locks) \
                        or (fn is not None
                            and fn.name.endswith("_locked")):
                    guarded.setdefault(attr, []).append((scope, node))
                else:
                    unguarded.setdefault(attr, []).append((scope, node))
        for attr, sites in unguarded.items():
            if attr in guarded:
                for scope, node in sites:
                    out_v = self.violation(
                        src, node,
                        f"{cls.name}.{attr} is written under the lock in "
                        f"{guarded[attr][0][0]}() but without it here in "
                        f"{scope}(); guard every write (or rename the "
                        "method *_locked if the caller holds it)")
                    yield_list.append(out_v)
            elif len({s for s, _ in sites}) >= 2:
                # never guarded, but written from several methods in a
                # lock-owning class: mixed discipline
                scope0, node0 = sites[0]
                yield_list.append(self.violation(
                    src, node0,
                    f"{cls.name}.{attr} is written from "
                    f"{len({s for s, _ in sites})} methods "
                    f"({', '.join(sorted({s for s, _ in sites}))}) with "
                    "no lock held, in a class that lock-guards other "
                    "state; guard it or justify why it is not shared"))
        return yield_list

    # -- c: unchained signal installs ----------------------------------- #
    def _check_signals(self, src: SourceFile) -> List[Violation]:
        out: List[Violation] = []
        # handler names defined locally (def / lambda assignment)
        local_defs: Set[str] = {
            n.name for n in ast.walk(src.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) != "signal.signal":
                continue
            if len(node.args) < 2:
                continue
            handler = node.args[1]
            hname = dotted_name(handler)
            if hname.endswith("SIG_DFL") or hname.endswith("SIG_IGN"):
                continue            # disposition restore, not an install
            installs = isinstance(handler, ast.Lambda) \
                or (isinstance(handler, ast.Name)
                    and handler.id in local_defs) \
                or (isinstance(handler, ast.Attribute)
                    and isinstance(handler.value, ast.Name)
                    and handler.value.id == "self")
            if not installs:
                continue            # passing a saved prev back = restore
            # chained if the return value is kept or getsignal is called
            # in the same function
            from .base import parent as _parent
            if not isinstance(_parent(node), ast.Expr):
                continue            # result assigned/used: prev saved
            fn = enclosing_function(node)
            scope = fn if fn is not None else src.tree
            chained = any(
                isinstance(n, ast.Call)
                and dotted_name(n.func).endswith("getsignal")
                for n in ast.walk(scope))
            if not chained:
                out.append(self.violation(
                    src, node,
                    "signal handler installed without saving the "
                    "previous one — nothing to chain or restore; keep "
                    "signal.signal's return value (or getsignal first) "
                    "and call the prior handler (PR-4 SIGTERM-chaining "
                    "shape)"))
        return out
