"""Shared rule plumbing: the Violation record, the Rule interface, and
the AST helpers every rule family leans on (parent links, jit-traced
function discovery, lock-attribute discovery, with-lock containment).

Rules are pure stdlib ``ast`` passes — no jax/numpy import — so the CI
``lint`` job runs in seconds on a bare python.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set


@dataclass
class Violation:
    """One finding.  ``snippet`` (the stripped source line) is the
    baseline-matching key next to rule+file: line numbers drift with
    unrelated edits, the offending line's text does not."""
    rule: str
    file: str                # path as given to the engine (repo-relative)
    line: int
    message: str
    snippet: str = ""

    def key(self):
        return (self.rule, self.file, self.snippet)

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} {self.message}"


@dataclass
class SourceFile:
    """One parsed file plus the per-file facts rules share."""
    path: str                # as reported in violations
    text: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.lines:
            self.lines = self.text.splitlines()
        add_parents(self.tree)

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, rule: str, lineno: int) -> bool:
        """Inline opt-out: ``# graftlint: disable=GL00x[,GL00y]`` on the
        flagged line or the line directly above it."""
        for ln in (lineno, lineno - 1):
            text = self.line_at(ln)
            if "graftlint: disable=" in text:
                tail = text.split("graftlint: disable=", 1)[1]
                codes = tail.split()[0].split(",")
                if rule in codes or "all" in codes:
                    return True
        return False


class Project:
    """Cross-file context handed to every rule: where the repo root is
    (for docs lookups) and lazily-loaded shared artifacts."""

    def __init__(self, root: Optional[str] = None):
        self.root = root
        self._docs_text: Optional[str] = None

    def docs_text(self) -> str:
        """Concatenated text of every ``docs/*.md`` under the repo root
        (the declared-metric-name universe GL004 checks against)."""
        if self._docs_text is None:
            import glob
            import os
            chunks = []
            if self.root:
                for p in sorted(glob.glob(os.path.join(self.root, "docs",
                                                       "*.md"))):
                    try:
                        with open(p, encoding="utf-8") as f:
                            chunks.append(f.read())
                    except OSError:
                        pass
            self._docs_text = "\n".join(chunks)
        return self._docs_text


def is_library_path(path: str) -> bool:
    """Library code vs tests/scripts/examples — some rules (or subrules)
    only make sense for the former."""
    norm = path.replace("\\", "/")
    return not any(seg in norm for seg in ("tests/", "scripts/",
                                           "examples/"))


class Rule:
    """One named invariant.  ``library_only`` rules skip tests/ and
    scripts/ (e.g. a timing script *should* host-sync; a test loop
    float()ing a loss is the test's assertion, not a hot path)."""
    id = "GL000"
    title = "base rule"
    library_only = False

    def check(self, src: SourceFile, project: Project) -> List[Violation]:
        raise NotImplementedError

    def violation(self, src: SourceFile, node: ast.AST, message: str
                  ) -> Violation:
        line = getattr(node, "lineno", 1)
        return Violation(self.id, src.path, line, message,
                         src.line_at(line))


# --------------------------------------------------------------------- #
# AST helpers                                                           #
# --------------------------------------------------------------------- #
def add_parents(tree: ast.AST):
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            child._gl_parent = parent       # type: ignore[attr-defined]


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "_gl_parent", None)


def ancestors(node: ast.AST):
    p = parent(node)
    while p is not None:
        yield p
        p = parent(p)


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    for a in ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return a
    return None


def enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
    for a in ancestors(node):
        if isinstance(a, ast.ClassDef):
            return a
    return None


def dotted_name(node: ast.AST) -> str:
    """'jax.tree_util.tree_map' for the matching Attribute/Name chain,
    '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(call: ast.Call) -> str:
    return dotted_name(call.func)


def is_call_to(node: ast.AST, *names: str) -> bool:
    """True when ``node`` is a Call whose dotted name is one of ``names``
    or ends with ``.<name>`` (so ``rec.inc`` matches ``inc``)."""
    if not isinstance(node, ast.Call):
        return False
    dn = call_name(node)
    for n in names:
        if dn == n or dn.endswith("." + n):
            return True
    return False


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# --------------------------------------------------------------------- #
# jit-traced function discovery (GL002-A / GL005-A share this)          #
# --------------------------------------------------------------------- #
_JIT_NAMES = ("jit", "jax.jit", "pjit", "jax.pjit", "partial_jit")


def _is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` / ``functools.partial(jax.jit, ...)``."""
    dn = dotted_name(node)
    if dn in _JIT_NAMES or dn.endswith(".jit") or dn.endswith(".pjit"):
        return True
    if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn in _JIT_NAMES or fn.endswith(".jit") or fn.endswith(".pjit"):
            return True
        if fn == "partial" or fn.endswith(".partial"):
            return bool(node.args) and _is_jit_expr(node.args[0])
    return False


def traced_functions(tree: ast.AST) -> Set[ast.FunctionDef]:
    """Every function the module hands to a jit: decorated with
    ``@jax.jit`` (bare or partial), or whose name is later passed as the
    first argument of a ``jax.jit(...)`` call in the same module.  Code
    inside these runs under tracing — host syncs and wall-clock reads
    there are the GL002/GL005 hazards."""
    jitted_names: Set[str] = set()
    decorated: Set[ast.FunctionDef] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node) \
                and node.args and isinstance(node.args[0], ast.Name):
            jitted_names.add(node.args[0].id)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    decorated.add(node)
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name in jitted_names:
            decorated.add(node)
    return decorated


def in_traced_function(node: ast.AST, traced: Set[ast.FunctionDef]) -> bool:
    fn = enclosing_function(node)
    while fn is not None:
        if fn in traced:
            return True
        fn = enclosing_function(fn)
    return False


# --------------------------------------------------------------------- #
# lock discovery (GL003)                                                #
# --------------------------------------------------------------------- #
_LOCK_CTORS = ("Lock", "RLock", "Condition", "threading.Lock",
               "threading.RLock", "threading.Condition")


def lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """self.<attr> names assigned a threading lock/condition anywhere in
    the class (usually ``__init__``)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        dn = call_name(node.value)
        if not (dn in _LOCK_CTORS or dn.endswith(".Lock")
                or dn.endswith(".RLock") or dn.endswith(".Condition")):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                out.add(tgt.attr)
    return out


def under_with_lock(node: ast.AST, locks: Set[str]) -> bool:
    """True when ``node`` sits inside ``with self.<lock>:`` for any of
    the class's locks (or inside a method following the ``*_locked``
    naming convention — "caller holds the lock")."""
    for a in ancestors(node):
        if isinstance(a, ast.With):
            for item in a.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Attribute) \
                        and isinstance(ctx.value, ast.Name) \
                        and ctx.value.id == "self" and ctx.attr in locks:
                    return True
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if a.name.endswith("_locked"):
                return True
            return False        # stop at the method boundary
    return False


# --------------------------------------------------------------------- #
# self-attribute writes (GL003)                                         #
# --------------------------------------------------------------------- #
def self_attr_writes(fn: ast.AST):
    """Yield ``(attr_name, node)`` for every write to ``self.<attr>`` or
    ``self.<attr>[...]`` in ``fn`` (excluding nested defs' own self)."""
    for node in ast.walk(fn):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for tgt in targets:
            base = tgt
            if isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self":
                yield base.attr, node
