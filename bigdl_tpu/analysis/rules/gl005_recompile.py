"""GL005 — recompile & retrace hazards.

  GL005-a  wall-clock or host-RNG calls (``time.time`` /
           ``time.perf_counter`` / ``np.random.*`` / ``random.*``)
           inside a jitted function.  Under tracing these bake ONE value
           into the compiled program — the "why is my timestamp
           constant" class — and when closed over as static they force a
           retrace per call.  Use traced keys (``jax.random``) and time
           outside the jit.

  GL005-b  a function handed to ``jax.jit(..., static_argnums/names=...)``
           whose static parameter has a *mutable default* (list / dict /
           set literal).  Static args are hashed into the compile-cache
           key; unhashable values raise at best and at worst every call
           site builds a fresh object — a silent recompile per step.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional

from .base import (Project, Rule, SourceFile, Violation, call_name,
                   in_traced_function, traced_functions)

_CLOCK_RNG = ("time.time", "time.perf_counter", "time.monotonic",
              "datetime.now", "random.random", "random.randint",
              "random.uniform", "random.choice")


def _is_clock_or_rng(call: ast.Call) -> bool:
    name = call_name(call)
    if name in _CLOCK_RNG:
        return True
    return ".random." in name and not name.startswith("jax") \
        and "jax" not in name


class GL005Recompile(Rule):
    id = "GL005"
    title = "recompile & retrace hazards"

    def check(self, src: SourceFile, project: Project) -> List[Violation]:
        out: List[Violation] = []
        traced = traced_functions(src.tree)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call) and _is_clock_or_rng(node) \
                    and in_traced_function(node, traced):
                out.append(self.violation(
                    src, node,
                    f"{call_name(node)}() inside a jitted function is "
                    "traced once and baked into the program as a "
                    "constant; move clocks/host RNG outside the jit "
                    "(use jax.random for traced randomness)"))
        out.extend(self._check_static_args(src))
        return out

    # -- b: mutable defaults behind static args -------------------------- #
    def _check_static_args(self, src: SourceFile) -> List[Violation]:
        out: List[Violation] = []
        fns: Dict[str, ast.FunctionDef] = {
            n.name: n for n in ast.walk(src.tree)
            if isinstance(n, ast.FunctionDef)}
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not (name.endswith(".jit") or name == "jit"
                    or name.endswith(".pjit")):
                continue
            static_names, static_nums = [], []
            for kw in node.keywords:
                if kw.arg == "static_argnames":
                    static_names = _const_list(kw.value)
                elif kw.arg == "static_argnums":
                    static_nums = _const_list(kw.value)
            if not static_names and not static_nums:
                continue
            if not node.args or not isinstance(node.args[0], ast.Name):
                continue
            fn = fns.get(node.args[0].id)
            if fn is None:
                continue
            # positional params (posonly + regular) — static_argnums
            # indexes into exactly this sequence; defaults align with
            # its TAIL.  Keyword-only params (`*, cfg={}`) carry their
            # defaults separately and are the idiomatic static_argnames
            # spelling, so they must be inspected too
            params = [a.arg for a in (fn.args.posonlyargs
                                      + fn.args.args)]
            defaults = fn.args.defaults
            by_param = dict(zip(params[len(params) - len(defaults):],
                                defaults))
            for kwarg, dflt in zip(fn.args.kwonlyargs,
                                   fn.args.kw_defaults):
                if dflt is not None:
                    by_param[kwarg.arg] = dflt
            flagged = set()
            for sname in static_names:
                if isinstance(sname, str):
                    flagged.add(sname)
            for snum in static_nums:
                if isinstance(snum, int) and 0 <= snum < len(params):
                    flagged.add(params[snum])
            for pname in sorted(flagged):
                dflt = by_param.get(pname)
                if isinstance(dflt, (ast.List, ast.Dict, ast.Set)) or (
                        isinstance(dflt, ast.Call)
                        and call_name(dflt) in ("list", "dict", "set")):
                    out.append(self.violation(
                        src, node,
                        f"static arg {pname!r} of {fn.name}() defaults "
                        "to a mutable (unhashable) object; static args "
                        "are compile-cache keys — use a hashable "
                        "(tuple/frozen) value or a retrace per call is "
                        "the best case"))
        return out


def _const_list(node: ast.AST) -> List[Optional[object]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        return [getattr(e, "value", None) for e in node.elts]
    if isinstance(node, ast.Constant):
        return [node.value]
    return []
