"""GL002 — host sync in the hot path.

Two shapes:

  GL002-a  a host-forcing call (``float()`` / ``.item()`` /
           ``np.asarray`` / ``np.array`` / ``.block_until_ready()``) on a
           traced value *inside a jitted function*.  Under tracing these
           either raise (TracerConversionError) or — worse, inside
           helpers that sometimes run eagerly — silently fence the
           pipeline every call.

  GL002-b  ``float()`` / ``.item()`` on a step result inside a per-step
           loop in library code.  Each conversion is a device→host sync
           that serializes dispatch against execution; the pattern that
           keeps winning review comments is "collect device scalars,
           convert once at the end" (``[float(l) for l in losses]`` after
           the loop — see ``SpmdTrainer.fit``).  A deliberate
           once-per-step sync (the telemetry contract: ``end_step`` folds
           the floats sentinels already need) belongs in the baseline
           with its justification, not hidden.

``library_only``: timing scripts *must* sync (that is the measurement),
and a test loop float()ing a loss is the assertion itself.
"""
from __future__ import annotations

import ast
from typing import List

from .base import (Project, Rule, SourceFile, Violation, call_name,
                   in_traced_function, traced_functions)

def _is_host_sync(call: ast.Call) -> bool:
    name = call_name(call)
    if name == "float" and call.args \
            and not isinstance(call.args[0], ast.Constant):
        return True
    if name.endswith(".item") and not call.args:
        return True
    if name.endswith("block_until_ready"):
        return True
    # numpy (never jax.numpy: jnp.asarray is a traced op) conversions
    if name in ("np.asarray", "np.array", "numpy.asarray", "numpy.array"):
        return True
    return False


def _is_step_loop(loop: ast.For) -> bool:
    """A for-loop whose body drives training/serving steps: it calls
    ``*.step(...)`` or ``start_step``/``end_step``."""
    for node in ast.walk(loop):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name.endswith(".step") or name.endswith("start_step") \
                    or name.endswith("end_step"):
                return True
    return False


class GL002HostSync(Rule):
    id = "GL002"
    title = "host sync in the hot path"
    library_only = True

    def check(self, src: SourceFile, project: Project) -> List[Violation]:
        out: List[Violation] = []
        traced = traced_functions(src.tree)
        # (a) host syncs under tracing
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call) and _is_host_sync(node) \
                    and in_traced_function(node, traced):
                out.append(self.violation(
                    src, node,
                    f"{call_name(node)}(...) inside a jitted function "
                    "forces a host sync (or a tracer error) every call; "
                    "keep the value on device and convert outside the "
                    "traced region"))
        # (b) per-step float()/item() in step loops
        for loop in ast.walk(src.tree):
            if not isinstance(loop, ast.For) or not _is_step_loop(loop):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                sync = (name == "float" and node.args
                        and not isinstance(node.args[0], ast.Constant)) \
                    or name.endswith(".item")
                if sync and not in_traced_function(node, traced):
                    out.append(self.violation(
                        src, node,
                        "per-step host sync inside a step loop "
                        "serializes dispatch against execution; keep "
                        "device scalars and convert once after the loop "
                        "(or baseline the one deliberate telemetry sync "
                        "with its justification)"))
        return out
