"""GL004 — span/trace pairing and counter-name drift.

  GL004-a  ``jax.profiler.start_trace(...)`` whose enclosing function has
           no ``finally`` calling ``stop_trace``.  The PR-5 wedged-
           profiler bug exactly: an exception mid-traced-step left the
           session latched open forever, and every later capture
           silently no-opped.  Code that pairs the session across calls
           (a deliberate state machine like the Recorder's trace
           sessions) baselines with a pointer at its recovery logic.

  GL004-b  a trace/span *open* (``tr.open("name", ...)`` /
           ``start_span``) in a file that never closes: no ``close`` /
           ``terminal`` / ``discard`` call anywhere in the same file.
           Pairing across threads (the serving queue handoff) is legal
           but must be visible in the same file or justified in the
           baseline.

  GL004-c  a counter incremented (``rec.inc("name")``) under a constant
           name that no ``docs/*.md`` file declares.  The metrics tables
           in the docs are the operator contract — a counter that only
           exists in the source is a dashboard nobody will ever build.
           F-string names are skipped (not statically checkable);
           ``prefix/*`` in the docs declares a family.

``library_only``: fixtures and tests open fake spans on purpose, and
test-only counters are not an operator contract.
"""
from __future__ import annotations

import ast

from typing import List

from .base import (Project, Rule, SourceFile, Violation, call_name,
                   const_str, enclosing_function)


def _has_finally_with(fn: ast.AST, callee_suffix: str) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Try) and node.finalbody:
            for n in node.finalbody:
                for c in ast.walk(n):
                    if isinstance(c, ast.Call) and call_name(c).endswith(
                            callee_suffix):
                        return True
    return False


def _declared(name: str, doc_text: str) -> bool:
    """A counter is declared when its full name appears anywhere in the
    docs, or a family glob covers it: ``health/*`` in the docs declares
    every ``health/...`` counter."""
    if name in doc_text:
        return True
    parts = name.split("/")
    for i in range(1, len(parts)):
        if "/".join(parts[:i]) + "/*" in doc_text:
            return True
    return False


class GL004Spans(Rule):
    id = "GL004"
    title = "span/trace pairing & counter-name drift"
    library_only = True

    def check(self, src: SourceFile, project: Project) -> List[Violation]:
        out: List[Violation] = []
        text = src.text
        has_close = (".close(" in text or ".terminal(" in text
                     or ".discard(" in text or "stop_span" in text)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            # (a) profiler session without finally-guarded stop (exact
            # last segment: `_maybe_start_trace` is a wrapper, not the
            # session call)
            if name.split(".")[-1] == "start_trace":
                fn = enclosing_function(node)
                if fn is None or not _has_finally_with(fn, "stop_trace"):
                    out.append(self.violation(
                        src, node,
                        "profiler trace session started without a "
                        "finally-guarded stop_trace; an exception here "
                        "latches the session open and every later "
                        "capture silently no-ops (PR-5 wedged-profiler "
                        "shape)"))
            # (b) span open with no close anywhere in the file
            elif (name.endswith(".open") or name.endswith("start_span")) \
                    and node.args and const_str(node.args[0]) is not None \
                    and not has_close:
                out.append(self.violation(
                    src, node,
                    f"span {const_str(node.args[0])!r} opened but this "
                    "file never calls close/terminal/discard; pair it "
                    "(or justify the cross-file handoff in the "
                    "baseline)"))
            # (c) counters under names the docs never declare
            elif name.endswith(".inc") and node.args:
                cname = const_str(node.args[0])
                if cname is None:
                    continue        # f-string / computed: not checkable
                if not project.docs_text():
                    continue        # no docs tree (fixture runs)
                if not _declared(cname, project.docs_text()):
                    out.append(self.violation(
                        src, node,
                        f"counter {cname!r} is emitted but no docs/*.md "
                        "declares it; add it to the metrics table (the "
                        "operator contract) or drop the counter"))
        return out
