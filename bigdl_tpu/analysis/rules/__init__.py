"""Rule registry: one module per bug family, ordered by rule ID."""
from .base import Project, Rule, SourceFile, Violation
from .gl001_donation import GL001Donation
from .gl002_host_sync import GL002HostSync
from .gl003_locks import GL003Locks
from .gl004_spans import GL004Spans
from .gl005_recompile import GL005Recompile
from .gl006_retry import GL006Retry

ALL_RULES = (GL001Donation(), GL002HostSync(), GL003Locks(),
             GL004Spans(), GL005Recompile(), GL006Retry())

RULES_BY_ID = {r.id: r for r in ALL_RULES}

__all__ = ["ALL_RULES", "RULES_BY_ID", "Project", "Rule", "SourceFile",
           "Violation"]
