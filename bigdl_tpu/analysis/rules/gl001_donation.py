"""GL001 — donation / zero-copy aliasing.

The PR-3 corruption class, both directions of it:

  (a) device → host: ``np.asarray(jax_array)`` (or mapping ``np.asarray``
      over a tree of them) can return a zero-copy VIEW of the device
      buffer on CPU backends.  Hand that "snapshot" to an async writer
      while the donating step loop keeps running and the view is
      scribbled mid-write — the torn state even passes its own CRC,
      because the CRC was computed over the torn bytes.

  (b) host → device: ``jnp.asarray(host_buffer)`` can zero-copy ADOPT an
      aligned host buffer (``np.load`` results, depending on zip layout —
      which is why the original bug was flaky).  The first post-restore
      donated step then donates memory numpy still owns, and Adam moments
      fill with garbage.

The owning spellings are ``np.array(...)`` / ``jnp.array(..., copy=True)``
(see ``checkpoint.manager.host_snapshot`` and
``SpmdTrainer._finish_restore``).  The rule flags:

  GL001-a  ``tree_map(np.asarray, ...)`` — the exact shape the PR-3
           snapshot bug had
  GL001-b  ``np.asarray(x)`` inside a function that mentions donation or
           lives on a snapshot/restore path (name contains snapshot /
           restore / host_copy)
  GL001-c  ``jnp.asarray(x)`` (direct or tree_mapped) inside a
           restore/load-path function (name contains ``restore`` or
           ``load``) — the owning spelling there is
           ``jnp.array(..., copy=True)``

Scoping: (a)/(b) — the snapshot-view hazards — apply to library code
only; they need a concurrently-donating step, and tests materialize
trees after training completes.  (c) applies everywhere: test worker
harnesses genuinely restore and then train.

Known limitation (documented, not hidden): the restore-path test is the
function *name*, so a helper like ``_to_device`` called from a load path
is not flagged — name helpers on ownership-critical paths accordingly.
"""
from __future__ import annotations

import ast
from typing import List

from .base import (Project, Rule, SourceFile, Violation, call_name,
                   dotted_name, enclosing_function, is_library_path)

_SNAPSHOT_HINTS = ("snapshot", "host_copy", "to_host")
_RESTORE_HINTS = ("restore", "load")


def _mentions_donation(fn: ast.AST) -> bool:
    """The function itself passes donate_argnums/donate_argnames — not a
    docstring mention, which would flag every comment about the rule."""
    for node in ast.walk(fn):
        if isinstance(node, ast.keyword) and node.arg \
                and node.arg.startswith("donate"):
            return True
    return False


def _is_np_asarray(name: str) -> bool:
    """numpy's asarray only: ``jnp.asarray`` on traced values is a cast,
    not a host view — it is handled by the restore-path check (c)."""
    return name in ("np.asarray", "numpy.asarray")


def _is_jnp_asarray(name: str) -> bool:
    return name in ("jnp.asarray", "jax.numpy.asarray")


class GL001Donation(Rule):
    id = "GL001"
    title = "donation / zero-copy aliasing"

    def check(self, src: SourceFile, project: Project) -> List[Violation]:
        out: List[Violation] = []
        # the snapshot-VIEW hazards (a/b) need a concurrently-donating
        # step; tests materialize trees after training completes, so
        # those subrules are library-only.  The restore-ADOPTION hazard
        # (c) stays on everywhere: worker harnesses restore, then train.
        library = is_library_path(src.path)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            fn = enclosing_function(node)
            fname = fn.name.lower() if fn is not None else ""
            on_restore_path = any(h in fname for h in _RESTORE_HINTS)
            # tree_map(asarray, ...) — one line converts a whole tree;
            # which direction decides which subrule applies
            if (name == "tree_map" or name.endswith(".tree_map")) \
                    and node.args:
                mapped = dotted_name(node.args[0])
                if _is_np_asarray(mapped) and library:
                    out.append(self.violation(
                        src, node,
                        "tree_map(np.asarray, ...) maps zero-copy views "
                        "over a device tree; a donated step scribbles "
                        "them mid-use — map an owning np.array instead "
                        "(PR-3 snapshot corruption shape)"))
                elif _is_jnp_asarray(mapped) and on_restore_path:
                    out.append(self.violation(
                        src, node,
                        "tree_map(jnp.asarray, ...) on a restore path "
                        "can zero-copy ADOPT aligned host buffers; the "
                        "first donated step then corrupts state numpy "
                        "still owns — map jnp.array(..., copy=True) "
                        "(PR-3 restore corruption shape)"))
                continue
            # (b) np.asarray on a snapshot/donation path
            if _is_np_asarray(name) and library:
                hazardous = any(h in fname for h in _SNAPSHOT_HINTS) \
                    or (fn is not None and _mentions_donation(fn))
                if hazardous:
                    out.append(self.violation(
                        src, node,
                        "np.asarray on a snapshot/donation path may be a "
                        "zero-copy view of the device buffer; use "
                        "np.array so the host copy owns its memory"))
            # (c) jnp.asarray on a restore path without copy=True
            if _is_jnp_asarray(name) and on_restore_path:
                out.append(self.violation(
                    src, node,
                    "jnp.asarray on a restore path can zero-copy "
                    "adopt the host buffer; the first donated step "
                    "then corrupts state numpy still owns — use "
                    "jnp.array(..., copy=True) (PR-3 restore "
                    "corruption shape)"))
        return out
