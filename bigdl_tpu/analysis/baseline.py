"""Suppression baseline: the committed ledger of *known, justified*
violations.

The contract (mirrors the zero-new-violations CI gate):

  * every entry names its rule, file, the offending line's stripped text
    (the ``snippet`` — stable across unrelated line drift) and a
    human-readable ``justification``;
  * an entry suppresses every occurrence of that exact snippet in that
    file for that rule;
  * an entry that matches *nothing* is STALE and fails the run by
    default — a fixed bug must take its suppression with it, so the
    ledger can only shrink through fixes, never rot.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .rules import Violation

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


@dataclass
class BaselineEntry:
    rule: str
    file: str
    snippet: str
    justification: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.file.replace(os.sep, "/"), self.snippet)


@dataclass
class Baseline:
    entries: List[BaselineEntry] = field(default_factory=list)
    path: Optional[str] = None

    def __post_init__(self):
        self._by_key: Dict[Tuple[str, str, str], BaselineEntry] = {
            e.key(): e for e in self.entries}
        self._hits: Dict[Tuple[str, str, str], int] = {
            k: 0 for k in self._by_key}

    def match(self, v: Violation) -> Optional[BaselineEntry]:
        """The entry suppressing ``v``, counting the hit; None if new."""
        key = (v.rule, v.file.replace(os.sep, "/"), v.snippet)
        e = self._by_key.get(key)
        if e is not None:
            self._hits[key] += 1
        return e

    def stale_entries(self) -> List[BaselineEntry]:
        """Entries that matched nothing this run (call after matching)."""
        return [self._by_key[k] for k, n in self._hits.items() if n == 0]


def load_baseline(path: Optional[str] = None) -> Baseline:
    """Load ``path`` (default: the committed ``analysis/baseline.json``);
    a missing default file is an empty baseline, a missing explicit path
    is an error."""
    explicit = path is not None
    path = path or DEFAULT_BASELINE
    if not os.path.exists(path):
        if explicit:
            raise FileNotFoundError(f"baseline file not found: {path}")
        return Baseline([], path=path)
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    entries = []
    for e in raw.get("entries", []):
        missing = {"rule", "file", "snippet", "justification"} - set(e)
        if missing:
            raise ValueError(
                f"{path}: baseline entry {e.get('rule')}/{e.get('file')} "
                f"missing {sorted(missing)} — every suppression must be "
                "justified inline")
        if not str(e["justification"]).strip():
            raise ValueError(
                f"{path}: empty justification for {e['rule']} in "
                f"{e['file']} — say WHY the finding is safe")
        entries.append(BaselineEntry(rule=str(e["rule"]),
                                     file=str(e["file"]),
                                     snippet=str(e["snippet"]),
                                     justification=str(e["justification"])))
    return Baseline(entries, path=path)


def write_baseline(violations: List[Violation], path: str,
                   justification: str = "TODO: justify or fix"):
    """Bootstrap helper (``graftlint.py --write-baseline``): dump the
    current findings as a baseline skeleton.  Committed entries must
    replace the placeholder justification — load_baseline accepts it,
    review should not."""
    entries = [{"rule": v.rule, "file": v.file.replace(os.sep, "/"),
                "snippet": v.snippet, "justification": justification}
               for v in violations]
    payload = {"version": 1, "entries": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
