"""Runtime race-detection harness: instrumented locks + guarded fields.

The static rules (GL003) catch lock discipline a parser can see; this
module catches what only execution shows — the *order* locks nest in
and the writes that happen with no lock held at all.  Two instruments:

  :class:`CheckedLock`     a Lock/RLock wrapper recording, per thread,
                           the stack of currently-held checked locks.
                           Acquiring B while holding A (directly or
                           through intermediates) adds the edge A→B to
                           a global acquisition graph; any CYCLE in
                           that graph — a plain A→B/B→A pair, or a
                           longer ring spread across three threads —
                           is a **lock-order inversion**: threads
                           interleaving those paths deadlock.
                           Detection needs only the orders to *occur*,
                           not the deadlock itself, so a passing stress
                           run still proves the ordering.

  :func:`guard_fields`     swaps an object's class for a subclass whose
                           ``__setattr__`` records a **bare write**
                           whenever a guarded attribute is assigned
                           while the object's checked lock is NOT held
                           by the writing thread.

Wiring an object under test::

    rc = RaceCheck()
    wrap_lock(engine, "_lock", rc)            # Lock -> CheckedLock
    guard_fields(engine, "_lock", ["_closed", "_http_server"], rc)
    ... run the stress scenario (threads submitting / shutting down /
        scraping /metrics) ...
    rc.assert_clean()     # raises with stacks on inversion/bare write

The ServingEngine shutdown-vs-submit-vs-/metrics stress test in
``tests/test_racecheck.py`` is the canonical use; PR 4's watchdog lock
ordering and PR 2's engine teardown both earned their review passes the
hard way this harness now automates.
"""
from __future__ import annotations

import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple


def _sccs(adj: Dict[str, List[str]]) -> List[List[str]]:
    """Tarjan strongly-connected components, iterative (lock graphs are
    tiny, but no recursion limits on principle)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Dict[str, bool] = {}
    stack: List[str] = []
    counter = [0]
    out: List[List[str]] = []

    for root in adj:
        if root in index:
            continue
        work = [(root, iter(adj[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack[root] = True
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack[nxt] = True
                    work.append((nxt, iter(adj[nxt])))
                    advanced = True
                    break
                if on_stack.get(nxt):
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)
    return out


def _stack(skip: int = 2, limit: int = 8) -> List[str]:
    frames = traceback.extract_stack()[:-skip]
    return [f"{f.filename.rsplit('/', 1)[-1]}:{f.lineno} {f.name}"
            for f in frames[-limit:]]


@dataclass
class Inversion:
    """One lock-order cycle: ``cycle`` lists the lock names on it (a
    plain A/B inversion is the 2-name case; longer chains across three
    or more threads are genuine deadlocks too), ``edges`` the observed
    nestings inside the cycle with where each was first seen."""
    cycle: List[str]
    edges: List[Tuple[str, str, str]]       # (outer, inner, first site)

    def render(self) -> str:
        ring = " -> ".join(self.cycle + [self.cycle[0]])
        sites = "; ".join(f"{a}->{b} at {site}"
                          for a, b, site in self.edges)
        return f"lock-order inversion: {ring} ({sites})"


@dataclass
class BareWrite:
    obj: str
    attr: str
    lock: str
    thread: str
    stack: List[str]

    def render(self) -> str:
        return (f"bare shared-state write: {self.obj}.{self.attr} "
                f"assigned on thread {self.thread!r} without "
                f"{self.lock} held (at {self.stack[-1]})")


class RaceCheck:
    """One acquisition graph + finding sink shared by every instrument
    of a scenario."""

    def __init__(self):
        self._mu = threading.Lock()
        # held-lock stack per thread (thread-local to THIS harness)
        self._tls = threading.local()
        # edge (outer, inner) -> first observed stack
        self._edges: Dict[Tuple[str, str], List[str]] = {}
        self.bare_writes: List[BareWrite] = []
        self._names: Dict[str, int] = {}

    def unique_name(self, base: str) -> str:
        """``base`` on first use, ``base#2``/``base#3``… after: two
        instruments of the same class+attr must not share a graph node,
        or their mutual ordering degenerates into a self-edge."""
        with self._mu:
            n = self._names.get(base, 0) + 1
            self._names[base] = n
        return base if n == 1 else f"{base}#{n}"

    # -- CheckedLock plumbing -------------------------------------------- #
    def _held(self) -> List["CheckedLock"]:
        if not hasattr(self._tls, "stack"):
            self._tls.stack = []
        return self._tls.stack

    def _on_acquired(self, lock: "CheckedLock"):
        held = self._held()
        # edge from EVERY held lock, not just the innermost: holding A
        # while taking C through an intermediate B is still an A-before-
        # C ordering, and dropping it would hide A/C inversions
        new_edges = [(h.name, lock.name) for h in held if h is not lock]
        if new_edges:
            with self._mu:
                for edge in new_edges:
                    self._edges.setdefault(edge, _stack())
        held.append(lock)

    def _on_released(self, lock: "CheckedLock"):
        held = self._held()
        # release order may not mirror acquire order; drop the newest
        # matching entry
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    # -- findings -------------------------------------------------------- #
    def inversions(self) -> List[Inversion]:
        """Cycles in the acquisition-order graph.  Any strongly-
        connected component with two or more locks means some set of
        threads can each hold one lock of the component while waiting
        for the next — a deadlock needs only the orders to have been
        OBSERVED, across any threads, at any time."""
        with self._mu:
            edges = dict(self._edges)
        adj: Dict[str, List[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        out = []
        for comp in _sccs(adj):
            # size-1 components count when they carry a SELF-edge: two
            # distinct locks sharing one name (hand-built CheckedLocks;
            # wrap_lock disambiguates via unique_name) nested in both
            # orders collapse to exactly that shape — it must not pass
            if len(comp) < 2 and (comp[0], comp[0]) not in edges:
                continue
            cset = set(comp)
            cyc_edges = [(a, b, stk[-1]) for (a, b), stk in edges.items()
                         if a in cset and b in cset]
            out.append(Inversion(cycle=sorted(comp),
                                 edges=sorted(cyc_edges)))
        return out

    def report(self) -> Dict[str, Any]:
        return {"inversions": [vars(i) for i in self.inversions()],
                "bare_writes": [vars(w) for w in self.bare_writes],
                "edges": sorted(self._edges)}

    def assert_clean(self):
        problems = [i.render() for i in self.inversions()] \
            + [w.render() for w in self.bare_writes]
        if problems:
            raise AssertionError("racecheck found:\n  "
                                 + "\n  ".join(problems))


class CheckedLock:
    """Drop-in Lock/RLock wrapper feeding a :class:`RaceCheck`.

    Exposes acquire/release/locked and the context-manager protocol, so
    it substitutes for ``threading.Lock``/``RLock`` attributes and works
    inside ``threading.Condition(lock=...)``.
    """

    def __init__(self, name: str, rc: RaceCheck, rlock: bool = False):
        self.name = name
        self._rc = rc
        self._inner = threading.RLock() if rlock else threading.Lock()
        self._holders: Dict[int, int] = {}      # ident -> depth
        self._mu = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout) if timeout != -1 \
            else self._inner.acquire(blocking)
        if got:
            ident = threading.get_ident()
            with self._mu:
                depth = self._holders.get(ident, 0)
                self._holders[ident] = depth + 1
            if depth == 0:      # re-entrant re-acquire adds no edge
                self._rc._on_acquired(self)
        return got

    def release(self):
        ident = threading.get_ident()
        with self._mu:
            depth = self._holders.get(ident, 0)
            if depth <= 1:
                self._holders.pop(ident, None)
            else:
                self._holders[ident] = depth - 1
        if depth <= 1:
            self._rc._on_released(self)
        self._inner.release()

    def held_by_current_thread(self) -> bool:
        with self._mu:
            return self._holders.get(threading.get_ident(), 0) > 0

    def locked(self) -> bool:
        with self._mu:
            return bool(self._holders)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def wrap_lock(obj, attr: str, rc: RaceCheck,
              name: Optional[str] = None) -> CheckedLock:
    """Replace ``obj.<attr>`` (a threading.Lock/RLock) with a
    :class:`CheckedLock` reporting into ``rc``.  Must run while nothing
    holds the lock (instrument before starting the scenario threads)."""
    current = getattr(obj, attr)
    if isinstance(current, CheckedLock):
        return current
    rlock = "RLock" in type(current).__name__ \
        or "_RLock" in type(current).__name__
    lock = CheckedLock(
        rc.unique_name(name or f"{type(obj).__name__}.{attr}"), rc,
        rlock=rlock)
    setattr(obj, attr, lock)
    return lock


def guard_fields(obj, lock_attr: str, fields: Sequence[str],
                 rc: RaceCheck):
    """Record a :class:`BareWrite` whenever one of ``fields`` is
    assigned on ``obj`` without ``obj.<lock_attr>`` (a CheckedLock —
    call :func:`wrap_lock` first) held by the writing thread.

    Implementation: the object's class is swapped for a one-off subclass
    overriding ``__setattr__`` — instance state, methods and isinstance
    checks against the original class are untouched."""
    lock = getattr(obj, lock_attr)
    if not isinstance(lock, CheckedLock):
        raise TypeError(f"{lock_attr} is not a CheckedLock; call "
                        "wrap_lock(obj, lock_attr, rc) first")
    guarded = frozenset(fields)
    base = type(obj)

    def __setattr__(self, name, value):
        if name in guarded:
            lk = getattr(self, lock_attr, None)
            if isinstance(lk, CheckedLock) \
                    and not lk.held_by_current_thread():
                rc.bare_writes.append(BareWrite(
                    obj=type(self).__name__.replace("Guarded", "", 1),
                    attr=name, lock=lock_attr,
                    thread=threading.current_thread().name,
                    stack=_stack()))
        base.__setattr__(self, name, value)

    sub = type("Guarded" + base.__name__, (base,),
               {"__setattr__": __setattr__})
    obj.__class__ = sub
    return obj
