"""bigdl_tpu.analysis — invariant checkers for our recurring bug classes.

Three review-pass-tax bug families keep coming back in this codebase:
zero-copy/donation aliasing (PR 3 fixed two real corruption bugs in
snapshot/restore), lock- and signal-handler discipline (PR 4 took three
review passes for SIGTERM chaining, RLock re-entrancy and watchdog lock
ordering), and span/trace pairing (PR 5's wedged-profiler fix).  This
package turns each of them into a *named, machine-checked rule* so the
invariant is enforced by CI, not reviewer vigilance:

  GL001  donation / aliasing        zero-copy views of device or host
                                    buffers crossing an ownership line
  GL002  host sync in the hot path  float()/.item()/np.asarray on
                                    traced values or inside step loops
  GL003  lock & signal discipline   shared attributes mutated with and
                                    without the class lock; unchained
                                    signal-handler installs
  GL004  span / counter pairing     trace sessions opened without a
                                    guaranteed close; counters emitted
                                    under names the docs never declare
  GL005  recompile hazards          time/RNG calls inside traced code,
                                    mutable defaults behind static args

Entry points:

  :func:`run_lint` / ``scripts/graftlint.py``   the static checker
  :mod:`.racecheck`                             runtime lock-order and
                                                bare-shared-write harness
  ``analysis/baseline.json``                    the committed suppression
                                                baseline (every entry
                                                justified inline); the CI
                                                ``lint`` job fails on any
                                                *new* violation and on
                                                stale baseline entries
"""
from .baseline import Baseline, load_baseline
from .engine import LintResult, run_lint
from .racecheck import CheckedLock, RaceCheck, guard_fields, wrap_lock
from .rules import ALL_RULES, Violation

__all__ = ["ALL_RULES", "Baseline", "CheckedLock", "LintResult",
           "RaceCheck", "Violation", "guard_fields", "load_baseline",
           "run_lint", "wrap_lock"]
