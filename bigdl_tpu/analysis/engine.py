"""The lint driver: collect files, run rules, apply the baseline.

File scoping:

  * ``__pycache__`` and the golden fixtures
    (``tests/fixtures/graftlint``) are always skipped — the fixtures are
    deliberately violating;
  * ``library_only`` rules (GL002's hot-path heuristics, GL004's
    docs-contract check) skip ``tests/`` and ``scripts/`` — a timing
    script MUST host-sync and a test counter is not an operator
    contract;
  * files that fail to parse are reported as GL000 parse errors (a file
    the checker cannot read is a file the invariants do not cover).
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Sequence, Tuple

from .baseline import Baseline, BaselineEntry
from .rules import ALL_RULES, Project, Rule, SourceFile, Violation

EXCLUDE_PARTS = ("__pycache__", os.path.join("fixtures", "graftlint"))


def collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"]
            for fn in sorted(filenames):
                full = os.path.join(dirpath, fn)
                if fn.endswith(".py") and not any(
                        part in full for part in EXCLUDE_PARTS):
                    out.append(full)
    return out


from .rules.base import is_library_path as _is_library_file  # noqa: E402


@dataclass
class LintResult:
    violations: List[Violation] = field(default_factory=list)  # NEW ones
    suppressed: List[Tuple[Violation, BaselineEntry]] = \
        field(default_factory=list)
    stale_entries: List[BaselineEntry] = field(default_factory=list)
    files_checked: int = 0

    @property
    def ok(self) -> bool:
        return not self.violations and not self.stale_entries

    def to_json(self) -> dict:
        return {
            "ok": self.ok,
            "files_checked": self.files_checked,
            "violations": [vars(v) for v in self.violations],
            "suppressed": [
                {**vars(v), "justification": e.justification}
                for v, e in self.suppressed],
            "stale_baseline_entries": [vars(e) for e in
                                       self.stale_entries],
        }


def run_lint(paths: Sequence[str], rules: Optional[Iterable[Rule]] = None,
             baseline: Optional[Baseline] = None,
             root: Optional[str] = None) -> LintResult:
    """Lint ``paths`` with ``rules`` (default: all five families)
    against ``baseline``.  ``root`` anchors cross-file context (the
    docs/ tree for GL004-c); default: the common parent of ``paths``."""
    rules = list(rules) if rules is not None else list(ALL_RULES)
    baseline = baseline if baseline is not None else Baseline([])
    if root is None:
        root = _guess_root(paths)
    project = Project(root=root)
    result = LintResult()
    for path in collect_files(paths):
        rel = os.path.relpath(path, root) if root else path
        rel = rel.replace(os.sep, "/")
        text = None
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
            tree = ast.parse(text, filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            # GL000 goes through the SAME suppression/baseline path as
            # every other rule: an unparseable-but-known file (vendored,
            # templated) must be justifiable, not a permanent red
            line = getattr(e, "lineno", 1) or 1
            v = Violation("GL000", rel, line,
                          f"file does not parse: {e}")
            if text is not None:
                lines = text.splitlines()
                v.snippet = lines[line - 1].strip() \
                    if 1 <= line <= len(lines) else ""
                if _text_suppressed(lines, "GL000", line):
                    continue
            entry = baseline.match(v)
            if entry is not None:
                result.suppressed.append((v, entry))
            else:
                result.violations.append(v)
            continue
        src = SourceFile(path=rel, text=text, tree=tree)
        result.files_checked += 1
        library = _is_library_file(rel)
        for rule in rules:
            if rule.library_only and not library:
                continue
            for v in rule.check(src, project):
                if src.suppressed(v.rule, v.line):
                    continue
                entry = baseline.match(v)
                if entry is not None:
                    result.suppressed.append((v, entry))
                else:
                    result.violations.append(v)
    # staleness is judged only within this run's scope: a --rules or
    # single-directory run must not damn (or tempt anyone to delete)
    # entries belonging to rules/files it never looked at
    active = {r.id for r in rules} | {"GL000"}
    scopes = _rel_scopes(paths, root)
    result.stale_entries = [
        e for e in baseline.stale_entries()
        if e.rule in active and _in_scope(e.file, scopes)]
    result.violations.sort(key=lambda v: (v.file, v.line, v.rule))
    return result


def _text_suppressed(lines, rule: str, lineno: int) -> bool:
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            text = lines[ln - 1]
            if "graftlint: disable=" in text:
                codes = text.split("graftlint: disable=", 1)[1] \
                    .split()[0].split(",")
                if rule in codes or "all" in codes:
                    return True
    return False


def _rel_scopes(paths: Sequence[str], root: Optional[str]) -> List[str]:
    out = []
    for p in paths:
        rp = os.path.relpath(os.path.abspath(p),
                             root) if root else p
        rp = rp.replace(os.sep, "/").rstrip("/")
        out.append("" if rp == "." else rp)
    return out


def _in_scope(file: str, scopes: List[str]) -> bool:
    return any(s == "" or file == s or file.startswith(s + "/")
               for s in scopes)


def _guess_root(paths: Sequence[str]) -> str:
    """The repo root: walk up from the first path to the dir holding
    ``docs`` or ``.git``; fall back to the path's parent."""
    start = os.path.abspath(paths[0] if paths else ".")
    if os.path.isfile(start):
        start = os.path.dirname(start)
    cur = start
    for _ in range(8):
        if os.path.isdir(os.path.join(cur, "docs")) \
                or os.path.isdir(os.path.join(cur, ".git")):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            break
        cur = nxt
    return os.path.dirname(start) or "."
