"""Live train→serve weight streaming.

The TF system paper's core unification argument (arXiv:1605.08695) is
that training and serving should share one dataflow substrate so a
training job can *continuously* publish to its serving fleet; the
reference framework's Predictor-on-the-training-cluster
(arXiv:1804.05839) is the same idea at batch scale.  This module
closes that loop here:

    Optimizer / SpmdTrainer
        └─ set_weight_stream(WeightStreamPublisher(...))
             trigger fires (several_iteration / every_seconds / ...)
                └─ host_snapshot(params)      OWNING copies, taken
                   synchronously in the step loop — the PR-3 rule: the
                   next step donates these buffers, so the publish
                   thread must never hold views into them
                └─ publish worker (one in flight; a trigger that fires
                   while a publish is running is counted
                   ``stream/skipped_busy`` and the NEXT firing ships
                   fresher weights — streaming wants the latest
                   snapshot, not a backlog)
                     └─ CanaryPublisher.publish(...)  golden-decode
                        validation on a quiesced canary, fleet-wide
                        promotion, bit-identical rollback on rejection
                        — all PR-12 machinery, unchanged
                     └─ (or a bare ModelRegistry.swap_weights for a
                        single-engine target)

Counters (``stream/*``, registered in docs/observability.md):
``stream/snapshots``, ``stream/published``, ``stream/rejected``
(canary said no — training continues, the fleet serves the previous
snapshot), ``stream/skipped_busy``, ``stream/errors``.  Spans:
``stream.snapshot`` (the blocking device→host copy the step loop
pays) and ``stream.publish`` (the worker-thread side).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from ..observability import Recorder


class WeightStreamPublisher:
    """Trigger-gated params streaming from a live trainer to a serving
    target.

    ``target``   a :class:`~bigdl_tpu.serving.CanaryPublisher` (the
                 production path: golden-decode gate + rollback), a
                 :class:`~bigdl_tpu.serving.ModelRegistry` (direct
                 ``swap_weights`` — no gate, single engine), or any
                 callable ``(name, params, version) -> None``
    ``name``     the registry entry to publish under
    ``trigger``  an :class:`~bigdl_tpu.optim.Trigger` evaluated
                 against the trainer's state each step; or pass
                 ``every_steps=N``
    ``sync``     publish inline instead of on the worker thread
                 (tests / final-flush determinism)
    """

    def __init__(self, target: Any, name: str, *, trigger=None,
                 every_steps: Optional[int] = None,
                 recorder: Optional[Recorder] = None, sync: bool = False,
                 version_prefix: str = "stream"):
        if (trigger is None) == (every_steps is None):
            raise ValueError(
                "pass exactly one of trigger= / every_steps=")
        if every_steps is not None:
            from ..optim.trigger import Trigger
            trigger = Trigger.several_iteration(int(every_steps))
        self.target = target
        self.name = name
        self.trigger = trigger
        self.recorder = recorder if recorder is not None \
            else Recorder(annotate=False)
        self.sync = bool(sync)
        self.version_prefix = version_prefix
        self._lock = threading.Lock()
        self._busy = False
        self._thread: Optional[threading.Thread] = None
        #: (version, params) of the newest snapshot that actually
        #: published — what a smoke test compares decode output against
        self.last_published: Optional[tuple] = None
        #: version of the newest snapshot the canary REJECTED
        self.last_rejected: Optional[str] = None

    # -- trainer-side hook -------------------------------------------------- #
    def maybe_publish(self, params, state=None, step: Optional[int] = None,
                      loss=None) -> bool:
        """Called from the trainer's step loop.  Evaluates the trigger
        against ``state`` (an Optimizer ``TrainingState``) or a shim
        built from ``step``/``loss`` (the SpmdTrainer path); on fire,
        snapshots ``params`` synchronously (owning copies) and hands
        the publish to the worker.  Returns True when a snapshot was
        taken."""
        if state is None:
            state = _StreamState(int(step or 0), loss)
        if not self.trigger(state):
            return False
        rec = self.recorder
        with self._lock:
            if self._busy:
                # one publish in flight: skip — the next firing ships a
                # FRESHER snapshot, which is the point of streaming
                rec.inc("stream/skipped_busy")
                return False
            self._busy = True
        # anything failing between the busy-latch and the worker's own
        # finally must RELEASE the latch, or one transient snapshot/
        # thread-start failure silently kills streaming for the rest of
        # the training run (every later firing reads as skipped_busy)
        try:
            from ..checkpoint.manager import host_snapshot
            with rec.span("stream.snapshot"):
                snap = host_snapshot(params)
            rec.inc("stream/snapshots")
            version = f"{self.version_prefix}_iter{state.iteration}"
            if self.sync:
                self._publish(snap, version)
            else:
                t = threading.Thread(target=self._publish,
                                     args=(snap, version), daemon=True,
                                     name="weight-stream-publish")
                with self._lock:
                    self._thread = t
                t.start()
        except Exception as e:
            with self._lock:
                self._busy = False
            rec.inc("stream/errors")
            rec.emit_record("stream_event", kind="error",
                            model=self.name,
                            error=f"{type(e).__name__}: {e}")
            print(f"[stream] snapshot/dispatch failed: {e!r}",
                  flush=True)
        return True

    def wait(self, timeout: Optional[float] = None):
        """Block until the in-flight publish (if any) finishes."""
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout)
        return self

    # -- worker side --------------------------------------------------------- #
    def _publish(self, params, version: str):
        from .replicas import CanaryRejectedError
        rec = self.recorder
        try:
            with rec.span("stream.publish"):
                target = self.target
                if hasattr(target, "publish"):          # CanaryPublisher
                    target.publish(self.name, params, version=version)
                elif hasattr(target, "swap_weights"):   # bare registry
                    target.swap_weights(self.name, params,
                                        version=version)
                else:
                    target(self.name, params, version)
            rec.inc("stream/published")
            self.last_published = (version, params)
            rec.emit_record("stream_event", kind="published",
                            model=self.name, version=version)
        except CanaryRejectedError as e:
            # the gate worked: the fleet still serves the previous
            # snapshot, training is not interrupted
            rec.inc("stream/rejected")
            self.last_rejected = version
            rec.emit_record("stream_event", kind="rejected",
                            model=self.name, version=version,
                            reason=e.reason)
            print(f"[stream] canary rejected {self.name} {version} "
                  f"({e.reason}); fleet keeps the previous snapshot",
                  flush=True)
        except Exception as e:
            rec.inc("stream/errors")
            rec.emit_record("stream_event", kind="error",
                            model=self.name, version=version,
                            error=f"{type(e).__name__}: {e}")
            print(f"[stream] publish {version} failed: {e!r}",
                  flush=True)
        finally:
            with self._lock:
                self._busy = False


class _StreamState:
    """Trigger-state shim for trainers without a TrainingState (the
    SpmdTrainer path): exposes the fields the stock triggers read."""

    def __init__(self, iteration: int, loss=None):
        self.iteration = iteration
        self.epoch = 0
        self.loss = None if loss is None else float(loss)
        self.score = None
        self.epoch_finished = False


__all__ = ["WeightStreamPublisher"]
