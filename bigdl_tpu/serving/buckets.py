"""Shape buckets for static-shape serving.

XLA compiles one executable per input shape; on TPU a previously-unseen
batch size means a fresh compile measured in *seconds* — an SLO death
sentence for a request that arrived with a 50 ms deadline.  The fix is
the standard one (the original BigDL paper makes the same argument for
MKL-blocked shapes): pad every micro-batch up to one of a small, fixed
ladder of power-of-two sizes so any request mix lands on an executable
that already exists after :meth:`~bigdl_tpu.serving.ServingEngine.warmup`.

Powers of two keep the ladder short (log2(max_batch)+1 compiles cover
every size) while bounding pad waste below 50%; the measured waste is
the ``serving.batch_fill`` histogram.
"""
from __future__ import annotations

from typing import Tuple


class BucketLadder:
    """The fixed set of batch sizes the engine ever compiles:
    ``1, 2, 4, ..., max_batch`` (``max_batch`` is rounded up to a power
    of two).  Selection is deterministic: ``bucket_for(n)`` is the
    smallest bucket >= n, so a replayed request stream always hits the
    same executables."""

    def __init__(self, max_batch: int = 32):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = _next_pow2(max_batch)
        self.sizes: Tuple[int, ...] = tuple(
            2 ** i for i in range(self.max_batch.bit_length()))

    def bucket_for(self, n: int) -> int:
        if n < 1:
            raise ValueError(f"batch size must be >= 1, got {n}")
        if n > self.max_batch:
            raise ValueError(
                f"batch size {n} exceeds max_batch {self.max_batch}; "
                "split the request upstream (ServingEngine.predict does)")
        return _next_pow2(n)

    def __iter__(self):
        return iter(self.sizes)

    def __len__(self):
        return len(self.sizes)

    def __repr__(self):
        return f"BucketLadder({list(self.sizes)})"


def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length() if n > 1 else 1
