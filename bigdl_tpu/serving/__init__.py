"""bigdl_tpu.serving — dynamic-batching inference engine.

The reference stack serves through ``optim/PredictionService.scala`` /
``LocalPredictor.scala`` (a pool of module clones behind a thread-safe
facade); BigDL 2.0's pitch is "seamless scaling of AI pipelines" to
production traffic.  This package is that serving path rebuilt for
TPU/XLA reality, where throughput comes from large static-shape batches
and an unexpected shape means a multi-second recompile:

  * :class:`BucketLadder` — the fixed power-of-two batch sizes the
    engine ever compiles; requests pad up to the next bucket.
  * :class:`BatchingQueue` — bounded FIFO coalescing concurrent
    requests into micro-batches under a max-latency deadline, shedding
    (:class:`LoadShedError`) at admission when full.
  * :class:`ModelRegistry` — named, versioned models with immutable
    weight :class:`Snapshot`\\ s and atomic hot-swap.
  * :class:`ServingEngine` — warmup (pre-compile every bucket,
    optionally through the int8 path), per-request deadline
    propagation, graceful drain, and full
    :class:`~bigdl_tpu.observability.Recorder` wiring.
  * :class:`DecodeEngine` + :class:`PagedKVCache` — token-streaming
    continuous batching for LMs: requests join/leave the decode batch
    per step, the KV cache is paged from a device pool (LRU eviction +
    re-prefill, optional int8), per-token TTFT/inter-token SLO
    accounting.
  * :class:`WeightStreamPublisher` — Trigger-fired live train→serve
    weight streaming through the canary gate.

Quick start::

    from bigdl_tpu.serving import ModelRegistry, ServingEngine

    reg = ModelRegistry()
    reg.register("mnist", model, input_shape=(1, 28, 28))
    eng = ServingEngine(reg, max_batch=32, max_delay_ms=5.0)
    eng.warmup()                      # compile all buckets up front
    y = eng.predict("mnist", x)       # or submit(...) -> Future
    eng.shutdown(drain=True)

See ``docs/serving.md`` for architecture and tuning, and
``scripts/serve_bench.py`` for the closed-loop load generator.
"""
from __future__ import annotations

from . import arrivals
from .buckets import BucketLadder
from .decode import DecodeEngine, DecodeStream, build_decode_replica_set
from .engine import ServingEngine
from .kvcache import PagedKVCache, PagePoolError
from .queue import (BatchingQueue, EngineClosedError, LoadShedError,
                    Request)
from .registry import ModelEntry, ModelRegistry, Snapshot
from .replicas import (CanaryPublisher, CanaryRejectedError,
                       NoHealthyReplicaError, OverloadController,
                       ReplicaSet, build_replica_set)
from .stream import WeightStreamPublisher

__all__ = [
    "BucketLadder", "BatchingQueue", "Request",
    "LoadShedError", "EngineClosedError",
    "ModelRegistry", "ModelEntry", "Snapshot",
    "ServingEngine",
    "DecodeEngine", "DecodeStream", "PagedKVCache", "PagePoolError",
    "build_decode_replica_set", "WeightStreamPublisher",
    "ReplicaSet", "CanaryPublisher", "OverloadController",
    "CanaryRejectedError", "NoHealthyReplicaError",
    "build_replica_set", "arrivals",
]
