"""Paged KV cache for continuous-batching decode.

The contiguous ``TransformerLM.init_cache`` layout allocates
``max_len`` key/value rows per sequence up front — fine for a fixed
batch of equal-length generations, hopeless for a serving mix where a
12-token answer and a 900-token answer share the batch: the short
request strands ``max_len - 12`` rows of HBM for its whole lifetime.

Here the cache is a device-resident **pool of fixed-size pages**
(``page_size`` token rows each, one pool per attention layer) plus a
host-side allocator.  Each slot (one running request) owns an ordered
page table; a request's KV footprint is ``ceil(len / page_size)``
pages and grows one page at a time as it decodes.  The jitted decode
step never sees the allocator — it takes the page tables as a plain
``(slots, max_pages)`` int32 input and:

  * **writes** the new token's k/v rows at
    ``(table[len // page_size], len % page_size)`` — a fixed-shape
    scatter; dead slots carry table entries of ``-1``, whose writes
    XLA **drops** (out-of-bounds scatter, ``mode="drop"``),
  * **gathers** each slot's pages back into a contiguous attention
    window ``(slots, heads, max_pages * page_size, head_dim)`` —
    a fixed-shape gather; ``-1`` entries **fill** with zeros
    (``mode="fill"``), exactly the zero rows an unwritten contiguous
    cache would hold, which is what keeps paged logits bitwise equal
    to the ``init_cache`` path (tests/test_decode.py pins this).

Page tables are data, not shapes: admissions, retirements and
evictions change *values* only, so one compiled decode program serves
every batch composition — the zero-recompile discipline of the PR-2
bucket ladder extended to the token-streaming path.

``int8=True`` stores the pool as int8 with a per-(page, position,
head) fp32 scale over the head_dim channel — the
:func:`bigdl_tpu.quantized.quantize_rows` per-channel quantizer run
inside the decode step — halving (vs bf16; 4x vs fp32) the KV bytes
each decode step streams from HBM.  Drift is bounded and measured,
never hidden (see docs/serving.md § Token streaming).

Telemetry (``kv/*`` family, registered in docs/observability.md):
``kv/page_allocs`` / ``kv/page_frees`` / ``kv/evictions`` counters,
``kv/pages_in_use`` / ``kv/pool_fill`` / ``kv/peak_fill`` gauges.
"""
from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import Recorder
from ..quantized import dequantize_rows, quantize_rows


class PagePoolError(RuntimeError):
    """Allocator invariant violation (double free, foreign page)."""


class PagedKVCache:
    """Device page pool + host allocator + the jitted write/gather fns.

    ``layer_names``   attention-module names (one k/v pool each)
    ``n_heads`` / ``head_dim``  per-layer KV row geometry
    ``n_pages``       pool size, in pages, shared by all slots
    ``page_size``     token rows per page
    ``n_slots``       concurrent sequences (page-table rows)
    ``max_context``   longest sequence a slot may hold; rounded up to a
                      page multiple; fixes the gather window
                      ``max_pages_per_slot * page_size``
    ``dtype``         pool dtype for the fp path (int8 path stores
                      int8 + fp32 scales)
    ``int8``          quantize KV rows on write, dequantize on gather

    The allocator side (``alloc_for`` / ``free_slot``) is guarded by
    one lock and keeps the invariant ``free + sum(owned) == n_pages``
    with every page owned by at most one slot — tests/test_decode.py
    asserts it across alloc/free/evict churn.
    """

    def __init__(self, layer_names: Sequence[str], *, n_heads: int,
                 head_dim: int, n_pages: int, page_size: int = 16,
                 n_slots: int = 8, max_context: int = 256,
                 dtype=jnp.float32, int8: bool = False,
                 recorder: Optional[Recorder] = None):
        if page_size < 1 or n_pages < 1 or n_slots < 1:
            raise ValueError("page_size, n_pages and n_slots must be >= 1")
        self.layer_names = list(layer_names)
        self.n_heads = int(n_heads)
        self.head_dim = int(head_dim)
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.n_slots = int(n_slots)
        self.max_pages_per_slot = math.ceil(max_context / page_size)
        self.max_context = self.max_pages_per_slot * self.page_size
        self.window = self.max_pages_per_slot * self.page_size
        self.dtype = jnp.dtype(dtype)
        self.int8 = bool(int8)
        self.recorder = recorder if recorder is not None else Recorder(
            annotate=False, enabled=False)
        self._lock = threading.Lock()
        # deterministic allocation order: lowest free page first
        self._free: List[int] = list(range(self.n_pages))
        self._owned: Dict[int, List[int]] = {s: [] for s in
                                             range(self.n_slots)}
        self.tables = np.full((self.n_slots, self.max_pages_per_slot),
                              -1, np.int32)

    # -- device pool ------------------------------------------------------ #
    def init_pool(self):
        """Zeroed device pool pytree: ``{layer: {"k", "v"[, "k_scale",
        "v_scale"]}}`` with pages laid out ``(n_pages, page_size,
        n_heads, head_dim)`` (scales ``(n_pages, page_size, n_heads,
        1)``).  Zero pages read back as the zero rows of a fresh
        contiguous cache."""
        shape = (self.n_pages, self.page_size, self.n_heads, self.head_dim)
        sshape = shape[:-1] + (1,)

        def one():
            if self.int8:
                return {"k": jnp.zeros(shape, jnp.int8),
                        "v": jnp.zeros(shape, jnp.int8),
                        "k_scale": jnp.zeros(sshape, jnp.float32),
                        "v_scale": jnp.zeros(sshape, jnp.float32)}
            return {"k": jnp.zeros(shape, self.dtype),
                    "v": jnp.zeros(shape, self.dtype)}

        return {name: one() for name in self.layer_names}

    # -- host allocator --------------------------------------------------- #
    def pages_for(self, n_tokens: int) -> int:
        return math.ceil(max(int(n_tokens), 0) / self.page_size)

    def can_fit(self, n_tokens: int) -> bool:
        with self._lock:
            return self.pages_for(n_tokens) <= len(self._free)

    def alloc_for(self, slot: int, n_tokens: int) -> bool:
        """Grow ``slot``'s table to cover ``n_tokens`` token rows.
        All-or-nothing: returns False (allocating nothing) when the
        free list cannot cover the growth — the caller then evicts or
        backpressures."""
        need_pages = self.pages_for(n_tokens)
        if need_pages > self.max_pages_per_slot:
            raise ValueError(
                f"slot {slot}: {n_tokens} tokens need {need_pages} pages "
                f"> max_pages_per_slot {self.max_pages_per_slot} "
                f"(max_context {self.max_context})")
        with self._lock:
            owned = self._owned[slot]
            grow = need_pages - len(owned)
            if grow <= 0:
                return True
            if grow > len(self._free):
                return False
            for _ in range(grow):
                page = self._free.pop(0)
                self.tables[slot, len(owned)] = page
                owned.append(page)
            self.recorder.inc("kv/page_allocs", grow)
            self._publish_gauges_locked()
            return True

    def free_slot(self, slot: int, evict: bool = False) -> int:
        """Return every page ``slot`` owns to the free list (retirement
        or eviction); the table row resets to ``-1`` so in-flight
        gathers read zeros and writes drop.  Returns the page count."""
        with self._lock:
            owned = self._owned[slot]
            for page in owned:
                if page in self._free:
                    raise PagePoolError(
                        f"double free: page {page} of slot {slot} is "
                        "already on the free list")
                self._free.append(page)
            n = len(owned)
            self._free.sort()
            self._owned[slot] = []
            self.tables[slot, :] = -1
            if n:
                self.recorder.inc("kv/page_frees", n)
            if evict:
                self.recorder.inc("kv/evictions")
            self._publish_gauges_locked()
            return n

    def pages_in_use(self) -> int:
        with self._lock:
            return self.n_pages - len(self._free)

    def fill(self) -> float:
        """Pool fill fraction in [0, 1] — the ``kv/pool_fill`` gauge."""
        with self._lock:
            return (self.n_pages - len(self._free)) / self.n_pages

    def check_invariants(self):
        """Every page owned at most once and free+owned == n_pages
        (test seam; raises :class:`PagePoolError` on violation)."""
        with self._lock:
            seen = list(self._free)
            for slot, owned in self._owned.items():
                seen += owned
                for i, page in enumerate(owned):
                    if self.tables[slot, i] != page:
                        raise PagePoolError(
                            f"table/ledger disagree at slot {slot}[{i}]")
            if sorted(seen) != list(range(self.n_pages)):
                raise PagePoolError(
                    f"page ledger broken: {sorted(seen)} != "
                    f"0..{self.n_pages - 1}")

    def _publish_gauges_locked(self):
        used = self.n_pages - len(self._free)
        rec = self.recorder
        rec.gauge("kv/pages_in_use", used)
        fill = used / self.n_pages
        rec.gauge("kv/pool_fill", fill)
        if fill > rec.gauge_value("kv/peak_fill", 0.0):
            rec.gauge("kv/peak_fill", fill)

    # -- jitted write/gather (fixed shapes, traced) ------------------------ #
    def _oob(self, idx):
        """Map the host tables' ``-1`` free markers to ``n_pages`` —
        genuinely out of bounds.  jax scatter/gather WRAP negative
        indices (numpy semantics) *before* the drop/fill bounds check,
        so a raw ``-1`` would silently alias the pool's LAST page: a
        dead slot's write clobbered whichever request owned it.  A
        positive out-of-range index is what ``mode="drop"`` /
        ``mode="fill"`` actually drop/fill."""
        return jnp.where(idx < 0, self.n_pages, idx)

    def gather_window(self, layer_pool, tables):
        """(k_win, v_win) each ``(slots, heads, window, head_dim)``
        gathered from ``layer_pool`` through ``tables`` (slots,
        max_pages); ``-1`` entries fill with zeros.  Pages concatenate
        in table order, so a slot's window is exactly the contiguous
        cache a ``init_cache``-path request would hold."""
        tables = self._oob(tables)

        def one(q, scale):
            pages = jnp.take(q, tables, axis=0, mode="fill",
                             fill_value=0)   # (S, P, page, H, Dh)
            if scale is not None:
                sc = jnp.take(scale, tables, axis=0, mode="fill",
                              fill_value=0)
                pages = dequantize_rows(pages, sc)
            s, p, pg, h, d = pages.shape
            return pages.transpose(0, 3, 1, 2, 4).reshape(s, h, p * pg, d)

        return (one(layer_pool["k"], layer_pool.get("k_scale")),
                one(layer_pool["v"], layer_pool.get("v_scale")))

    def write_token(self, layer_pool, tables, lengths, k_new, v_new):
        """Scatter one new k/v row per slot into the pool at
        ``(table[len // page], len % page)``.  k_new/v_new are
        ``(slots, heads, 1, head_dim)`` (the
        :meth:`~bigdl_tpu.models.transformer.MultiHeadAttention.project_qkv_rows`
        output); dead slots' ``-1`` page indices drop."""
        pidx = self._oob(jnp.take_along_axis(
            tables, (lengths // self.page_size)[:, None], axis=1)[:, 0])
        off = lengths % self.page_size
        out = dict(layer_pool)
        for key, new in (("k", k_new), ("v", v_new)):
            row = new[:, :, 0, :]                     # (S, H, Dh)
            if self.int8:
                q, sc = quantize_rows(row, axis=-1)
                out[key] = layer_pool[key].at[pidx, off].set(
                    q, mode="drop")
                out[key + "_scale"] = layer_pool[key + "_scale"].at[
                    pidx, off].set(sc, mode="drop")
            else:
                out[key] = layer_pool[key].at[pidx, off].set(
                    row.astype(layer_pool[key].dtype), mode="drop")
        return out

    def write_prefill(self, layer_pool, table, k, v):
        """Scatter a contiguous prefill's k/v ``(1, heads, Lb, head_dim)``
        into the pages of ``table`` (``ceil(Lb / page_size)`` entries,
        ``-1``-padded past the slot's allocation — those pages hold
        only prompt-padding rows, which the per-slot attention mask
        never exposes, so dropping them is exact)."""
        pg = self.page_size
        table = self._oob(table)
        out = dict(layer_pool)
        for key, arr in (("k", k), ("v", v)):
            rows = jnp.transpose(arr[0], (1, 0, 2))   # (Lb, H, Dh)
            lb = rows.shape[0]
            n_pages = math.ceil(lb / pg)
            if lb % pg:
                rows = jnp.concatenate(
                    [rows, jnp.zeros((n_pages * pg - lb,) + rows.shape[1:],
                                     rows.dtype)], axis=0)
            pages = rows.reshape(n_pages, pg, self.n_heads, self.head_dim)
            if self.int8:
                q, sc = quantize_rows(pages, axis=-1)
                out[key] = layer_pool[key].at[table].set(q, mode="drop")
                out[key + "_scale"] = layer_pool[key + "_scale"].at[
                    table].set(sc, mode="drop")
            else:
                out[key] = layer_pool[key].at[table].set(
                    pages.astype(layer_pool[key].dtype), mode="drop")
        return out


__all__ = ["PagedKVCache", "PagePoolError"]
