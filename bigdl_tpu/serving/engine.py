"""Dynamic-batching inference engine.

The serving pipeline, end to end::

    submit(name, x)                       client threads
       └─ BatchingQueue.put              admission control: full -> shed
            └─ batcher thread            one per model
                 gather <= max_batch rows, flush on deadline
                 drop requests whose SLO already expired
                 pad rows -> power-of-two bucket
                 run the bucket's PRE-COMPILED executable
                 scatter results back to per-request futures

Every request therefore executes inside an already-jitted program:
after :meth:`ServingEngine.warmup` a mixed-size request stream hits
**zero** new XLA compilations (the ``serving.recompiles`` counter is
the proof, and a test asserts it stays 0).  Compilation is AOT
(``jit -> lower -> compile``) so an executable can *never* silently
retrace — a shape the cache doesn't know is a counted cache miss, not
a hidden multi-second stall inside a jitted call.

Telemetry goes through the PR-1 observability
:class:`~bigdl_tpu.observability.Recorder`:

  counters    ``serving.requests`` / ``serving.rows`` /
              ``serving.batches`` / ``serving.shed_queue_full`` /
              ``serving.shed_deadline`` / ``serving.recompiles`` /
              ``serving.warmup_compiles`` / ``serving.errors``
  gauges      ``serving.queue_depth.<model>``
  histograms  ``serving.latency_ms`` (p50/p95/p99 via
              ``Recorder.hist_quantiles``), ``serving.batch_fill``

Attribution (observability.profile) rides on top of the metrics:
every admitted request carries a trace ID and a span timeline
(admit → queue → batch_gather → compute → reply, shed requests ending
in a terminal cause span) collected in a bounded ring —
:meth:`ServingEngine.dump_chrome_trace` / the ``/trace`` route render
it as Chrome-trace/Perfetto JSON.  Each AOT-compiled bucket's XLA
cost/memory analysis is harvested at compile time into
``entry.cost[bucket]`` and emitted as a ``profile`` record, so an
operator can read FLOPs-per-bucket next to batch-fill and decide
whether the ladder wastes compute on padding.
"""
from __future__ import annotations

import threading
import time
import weakref
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import faults as faultplane
from ..observability import Recorder
from .buckets import BucketLadder
from .queue import (BatchingQueue, EngineClosedError, LoadShedError,
                    Request)
from .registry import ModelEntry, ModelRegistry


class ServingEngine:
    """Batches concurrent requests across a :class:`ModelRegistry`.

    ``max_batch``      largest bucket (rounded up to a power of two)
    ``max_delay_ms``   longest a request waits for batch company
    ``max_queue_rows`` admission cap per model, in rows; beyond it
                       requests shed with :class:`LoadShedError`
    ``recorder``       a Recorder; defaults to a fresh enabled one
                       (metrics are part of the serving contract)
    ``trace_requests`` per-request span tracing into a bounded ring of
                       ``trace_capacity`` completed traces (a few
                       appends per request; the /trace export source)
    """

    def __init__(self, registry: ModelRegistry, *, max_batch: int = 32,
                 max_delay_ms: float = 5.0, max_queue_rows: int = 256,
                 recorder: Optional[Recorder] = None,
                 trace_requests: bool = True, trace_capacity: int = 512):
        from ..observability.profile import TraceRing
        self.registry = registry
        self.ladder = BucketLadder(max_batch)
        self.max_delay = float(max_delay_ms) / 1e3
        self.max_queue_rows = int(max_queue_rows)
        self.recorder = recorder if recorder is not None \
            else Recorder(annotate=False)
        if self.recorder.enabled and self.recorder.get_ledger() is None:
            # goodput attribution: each executed batch folds its
            # interval by fill (padding rows are idle capacity), warmup
            # and recompiles land in compile_warmup via ledger phases
            from ..observability.goodput import GoodputLedger
            self.recorder.set_ledger(GoodputLedger(name="serving",
                                                   devices=1))
        self.trace_ring = TraceRing(trace_capacity) if trace_requests \
            else None
        self._queues: Dict[str, BatchingQueue] = {}
        self._threads: Dict[str, threading.Thread] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._http_server = None
        # if the engine is dropped without shutdown(), closing its
        # queues unparks the (weakly-bound) worker threads so they exit
        # instead of waiting forever on work that can never arrive
        self._finalizer = weakref.finalize(self, _close_queues,
                                           self._queues)

    # -- lifecycle -------------------------------------------------------- #
    def warmup(self, name: Optional[str] = None):
        """Pre-compile every bucket for ``name`` (or all models).  This
        is the SLO line in the sand: compiles that happen here are
        ``serving.warmup_compiles``; any compile after it is a counted
        ``serving.recompiles`` — and on a real TPU, a blown deadline."""
        entries = [self.registry.get(name)] if name is not None \
            else self.registry.entries()
        for entry in entries:
            if entry.input_shape is None:
                raise ValueError(
                    f"warmup({entry.name!r}): register with input_shape= "
                    "so dummy batches can be built")
            from ..observability.goodput import ledger_phase
            with self.recorder.span("serving.warmup"), \
                    ledger_phase(self.recorder, "compile_warmup"):
                for bucket in self.ladder:
                    if bucket not in entry.compiled:
                        self._compile(entry, bucket, entry.input_shape,
                                      warm=True)
            entry.warmed = True
        return self

    def telemetry_sources(self):
        """``[("serving", recorder)]`` — the aggregator attachment hook
        (``aggregator.add(engine, name=...)`` scrapes the ``serving.*``
        request/shed/latency families)."""
        return [("serving", self.recorder)]

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1"):
        """Start the live introspection server for this engine's
        recorder: ``/metrics`` (Prometheus — request/shed/recompile
        counters, per-model queue-depth gauges, latency/batch-fill
        summaries), ``/healthz`` (includes the shed rate), ``/records``,
        and ``/trace`` (Chrome-trace JSON of recent per-request span
        timelines).  ``port=0`` binds an ephemeral port (the returned
        server's ``.port``); ``shutdown()`` stops it."""
        from ..observability.http import IntrospectionServer
        trace_source = self.dump_chrome_trace \
            if self.trace_ring is not None else None
        server = IntrospectionServer(
            self.recorder, port=port, host=host,
            trace_source=trace_source).start()
        # _http_server is shared with shutdown(): every read/write under
        # self._lock (GL003), but stop() — which joins the serving
        # thread — always runs outside it.  Last caller wins (the
        # documented reconfigure semantics), shutdown wins terminally —
        # and a raced caller gets an exception, never a dead server
        # whose .port a scraper would be pointed at
        while True:
            with self._lock:
                if self._closed:
                    break
                prev = self._http_server
                if prev is None:
                    self._http_server = server
                    return server
                self._http_server = None
            prev.stop()     # reconfigure: no leaked thread/socket
        server.stop()
        raise EngineClosedError(
            "engine shut down while serve_metrics was binding")

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop admissions, then either finish queued work (``drain=True``,
        graceful) or fail it fast with :class:`EngineClosedError`."""
        with self._lock:
            self._closed = True
            queues = dict(self._queues)
            threads = dict(self._threads)
            server, self._http_server = self._http_server, None
        if server is not None:
            server.stop()
        for q in queues.values():
            q.close()
        if not drain:
            for q in queues.values():
                _fail_batch(q.dump(),
                            EngineClosedError("engine shut down before "
                                              "this request ran"),
                            ring=self.trace_ring, span="closed")
        for t in threads.values():
            t.join(timeout)
        return self

    # -- request path ----------------------------------------------------- #
    def submit(self, name: str, x, deadline_ms: Optional[float] = None,
               trace_ctx=None) -> Future:
        """Enqueue one request; returns its Future.

        ``x`` is one sample ``input_shape`` or a batch
        ``(n, *input_shape)`` with ``n <= max_batch``.  ``deadline_ms``
        propagates an SLO: requests still queued past it are shed
        instead of executed.  Raises :class:`LoadShedError` immediately
        when the queue is full (backpressure, not tail collapse).
        ``trace_ctx`` (a
        :class:`~bigdl_tpu.observability.context.TraceContext`) lets an
        upstream hop — the ReplicaSet front door — thread its trace id
        into this request's timeline.
        """
        t_admit = time.monotonic()
        entry = self.registry.get(name)
        x, n, single = self._normalize(entry, x)
        if n > self.ladder.max_batch:
            raise ValueError(
                f"submit: {n} rows > max_batch {self.ladder.max_batch}; "
                "use predict() which splits")
        deadline = None if deadline_ms is None \
            else time.monotonic() + float(deadline_ms) / 1e3
        ring = self.trace_ring
        tr = ring.new_trace(entry.name, ctx=trace_ctx) \
            if ring is not None else None
        req = Request(x, n, deadline=deadline, trace=tr)
        if tr is not None:
            tr.meta["rows"] = n
        # the worker always completes req.future (batched); a single-
        # sample caller gets a view that strips the batch dim back off
        fut = _UnbatchingFuture(req.future) if single else req.future
        rec = self.recorder
        rec.inc("serving.requests")
        q = self._ensure_worker(entry)
        if tr is not None:
            # every trace write BEFORE the put: the batcher may pop the
            # request the instant it lands, and the queue handoff is the
            # only ordering between this thread and the worker
            now = time.monotonic()
            tr.add_span("admit", t_admit, now)
            tr.open("queue", now)   # closed by the batcher at pop
        try:
            q.put(req)
        except LoadShedError:
            rec.inc("serving.shed_queue_full")
            if tr is not None:
                now = time.monotonic()
                tr.discard("queue")   # never entered the queue
                tr.terminal("queue_full", now)
                ring.finish(tr)
            raise
        except EngineClosedError:
            if tr is not None:
                tr.discard("queue")
                tr.terminal("engine_closed", time.monotonic(),
                            name="closed")
                ring.finish(tr)
            raise
        rec.gauge(f"serving.queue_depth.{entry.name}", q.depth())
        return fut

    def predict(self, name: str, x, timeout: Optional[float] = None,
                deadline_ms: Optional[float] = None):
        """Synchronous convenience: splits oversized inputs into
        ``max_batch`` chunks, submits them all (they batch and execute
        concurrently), and reassembles the outputs in order."""
        entry = self.registry.get(name)
        x, n, single = self._normalize(entry, x)
        if single:
            return self.submit(name, x[0], deadline_ms=deadline_ms) \
                       .result(timeout)
        futs = [self.submit(name, x[i:i + self.ladder.max_batch],
                            deadline_ms=deadline_ms)
                for i in range(0, n, self.ladder.max_batch)]
        parts = [f.result(timeout) for f in futs]
        if len(parts) == 1:
            return parts[0]
        return jax.tree_util.tree_map(
            lambda *ps: np.concatenate(ps, axis=0), *parts)

    def pending_rows(self) -> int:
        """Rows queued across this engine's models — the queue-depth
        input to replica health scoring and saturation accounting."""
        with self._lock:
            queues = list(self._queues.values())
        return sum(q.depth() for q in queues)

    def max_queue_fill(self) -> float:
        """Fill fraction of this engine's MOST saturated model queue,
        in [0, 1] — the admission-pressure signal replica saturation
        accounting uses.  The max (not a sum over queues) keeps the
        signal stable when queues are created lazily: a brownout
        spinning up the int8 entry's queue must not dilute — or
        double — the denominator it is controlled by."""
        with self._lock:
            queues = list(self._queues.values())
        if not queues:
            return 0.0
        return max(q.depth() for q in queues) / self.max_queue_rows

    def stats(self) -> Dict[str, Any]:
        """One flat dict of the serving counters plus latency
        percentiles and mean batch fill — what ``serve_bench`` prints."""
        rec = self.recorder
        out = {k: rec.counter_value(f"serving.{k}")
               for k in ("requests", "rows", "batches", "shed_queue_full",
                         "shed_deadline", "recompiles", "warmup_compiles",
                         "errors")}
        lat = rec.hist_summary("serving.latency_ms")
        if lat:
            out.update({"p50_ms": lat.get("p50"), "p95_ms": lat.get("p95"),
                        "p99_ms": lat.get("p99"),
                        "mean_latency_ms": lat.get("mean")})
        fill = rec.hist_summary("serving.batch_fill")
        if fill:
            out["batch_fill"] = fill.get("mean")
        return out

    # -- internals -------------------------------------------------------- #
    def _normalize(self, entry: ModelEntry, x):
        """-> (batched ndarray, n_rows, was_single_sample)."""
        x = np.asarray(x, entry.dtype)
        if entry.input_shape is not None:
            if x.shape == tuple(entry.input_shape):
                return x[None], 1, True
            if x.shape[1:] != tuple(entry.input_shape):
                raise ValueError(
                    f"{entry.name}: expected {entry.input_shape} or "
                    f"(n, *{entry.input_shape}), got {x.shape}")
            return x, x.shape[0], False
        if x.ndim == 0:
            raise ValueError("scalar input needs input_shape= at register")
        return x, x.shape[0], False

    def _ensure_worker(self, entry: ModelEntry) -> BatchingQueue:
        with self._lock:
            if self._closed:
                raise EngineClosedError("engine is shut down")
            q = self._queues.get(entry.name)
            if q is None:
                q = BatchingQueue(max_pending_rows=self.max_queue_rows,
                                  max_delay=self.max_delay)
                # the thread holds the engine only weakly: a dropped,
                # never-shut-down engine must be collectable (the
                # finalizer then closes its queues so workers exit)
                t = threading.Thread(
                    target=_worker_loop,
                    args=(weakref.ref(self), entry.name, q,
                          self.ladder.max_batch),
                    daemon=True, name=f"serving-{entry.name}")
                self._queues[entry.name] = q
                self._threads[entry.name] = t
                t.start()
            return q

    def _run_batch(self, entry: ModelEntry, q: BatchingQueue,
                   batch: List[Request]):
        rec = self.recorder
        ring = self.trace_ring
        now = time.monotonic()
        live = []
        for r in batch:
            tr = r.trace
            if tr is not None:
                tr.close("queue", now)
            if r.expired(now):
                rec.inc("serving.shed_deadline")
                if tr is not None:
                    tr.terminal("deadline", now)
                    ring.finish(tr)
                r.future.set_exception(LoadShedError(
                    "deadline", "expired before execution"))
            else:
                if tr is not None:
                    tr.open("batch_gather", now)
                live.append(r)
        if not live:
            return
        rows = sum(r.n for r in live)
        bucket = self.ladder.bucket_for(rows)
        x = np.concatenate([r.x for r in live], axis=0)
        if bucket > rows:
            x = np.concatenate(
                [x, np.zeros((bucket - rows,) + x.shape[1:], x.dtype)],
                axis=0)
        ex = entry.compiled.get(bucket)
        if ex is None:
            # post-warmup compile: the SLO violation the ladder exists
            # to prevent — counted, never silent
            rec.inc("serving.recompiles")
            from ..observability.goodput import ledger_phase
            with ledger_phase(rec, "compile_warmup"):
                ex = self._compile(entry, bucket, x.shape[1:])
        led = rec.get_ledger()
        if led is not None:
            # flush the inter-batch gap to the background phase so the
            # batch fold below attributes only its own interval
            led.note_step_begin()
        t_exec = time.monotonic()
        for r in live:
            tr = r.trace
            if tr is not None:
                # batch/bucket attribution: which company this request
                # kept, and how much padding it paid for
                tr.meta.update(bucket=bucket, batch_rows=rows,
                               batch_requests=len(live))
                tr.close("batch_gather", t_exec)
                tr.open("compute", t_exec)
        # chaos seam: the per-batch compute fault site.  ``err`` fails
        # the batch (counted serving.errors, requests complete
        # exceptionally — a ReplicaSet fails them over), ``delay``
        # wedges this batcher thread the way a stuck device call would
        # (chunked sleep, so it stays abortable) — the shape the
        # replica watchdog's wedge ejection exists for
        faultplane.inject("serving.compute", rec)
        snap = entry.snapshot          # one atomic read per batch
        with rec.span("serving.execute"):
            y = ex(snap.params, snap.state, jnp.asarray(x))
            y = jax.tree_util.tree_map(np.asarray, y)   # host sync point
        done = time.monotonic()
        off = 0
        for r in live:
            tr = r.trace
            if tr is not None:
                tr.close("compute", done)
                tr.open("reply", done)
            sl = jax.tree_util.tree_map(
                lambda a, o=off, n=r.n: a[o:o + n], y)
            off += r.n
            if tr is not None:
                # finish the trace BEFORE completing the future (same
                # contract as _fail_batch and the shed paths): a client
                # unblocked by .result() that immediately scrapes
                # /trace must see its own request
                tr.close("reply", time.monotonic())
                ring.finish(tr)
            r.future.set_result(sl)
            rec.observe("serving.latency_ms", (done - r.arrival) * 1e3)
        rec.inc("serving.batches")
        rec.inc("serving.rows", rows)
        rec.observe("serving.batch_fill", rows / bucket)
        if led is not None:
            # the batch's interval splits by fill: real rows are
            # goodput, padding rows are capacity idling in the bucket
            led.fold_split({"goodput": rows, "idle": bucket - rows})
        rec.gauge(f"serving.queue_depth.{entry.name}", q.depth())

    def _compile(self, entry: ModelEntry, bucket: int, feature_shape,
                 warm: bool = False):
        """AOT-compile ``entry``'s eval fn at ``(bucket, *feature_shape)``
        and cache the executable.  Falls back to a per-bucket ``jax.jit``
        wrapper on backends without the lower/compile AOT API (the
        bucket cache still makes our recompile counter exact)."""
        model = entry.model

        def fn(params, state, xx):
            y, _ = model.run(params, xx, state=state, training=False)
            return y

        snap = entry.snapshot
        dummy = jnp.asarray(np.zeros((bucket,) + tuple(feature_shape),
                                     entry.dtype))
        jitted = jax.jit(fn)
        with self.recorder.span("serving.compile"):
            try:
                ex = jitted.lower(snap.params, snap.state, dummy).compile()
            except (AttributeError, NotImplementedError):
                # jax version/backend without the AOT lower/compile API:
                # the jitted wrapper still serves, and the bucket-keyed
                # cache keeps the recompile counter exact.  Genuine
                # trace/compile FAILURES must propagate — warmup
                # reporting success over a broken model would make the
                # zero-recompile contract vacuous
                ex = jitted
        entry.compiled[bucket] = ex
        self._capture_bucket_cost(entry, bucket, ex)
        if entry.input_shape is None:
            entry.input_shape = tuple(feature_shape)
        if warm:
            self.recorder.inc("serving.warmup_compiles")
        return ex

    def _capture_bucket_cost(self, entry: ModelEntry, bucket: int, ex):
        """Harvest XLA cost/memory analysis from a freshly compiled
        bucket executable (AOT path only — the jit fallback exposes no
        analysis) into ``entry.cost[bucket]`` plus one ``profile``
        record, so per-bucket compute cost is attributable next to the
        batch-fill metrics.  Best-effort: never raises."""
        from ..observability import profile as _profile
        if not _profile.capture_enabled():
            return
        if not (hasattr(ex, "cost_analysis")
                or hasattr(ex, "memory_analysis")):
            return              # jit-fallback wrapper, nothing to read
        try:
            cost = _profile.capture_compiled(ex)
        except Exception:
            return
        entry.cost[bucket] = cost
        self.recorder.emit_record("profile", kind="serving_bucket",
                                  model=entry.name, bucket=bucket,
                                  cost=cost)

    # -- per-request trace export ------------------------------------------ #
    def dump_chrome_trace(self) -> str:
        """Chrome-trace/Perfetto JSON of the recent completed request
        traces (one track per request, B/E span pairs, trace IDs and
        batch/bucket attribution in args).  Save to a file and load in
        chrome://tracing or https://ui.perfetto.dev; also served live
        by the ``/trace`` route of :meth:`serve_metrics`."""
        from ..observability.profile import dump_chrome_trace
        traces = self.trace_ring.traces() if self.trace_ring is not None \
            else []
        meta = {"dropped_traces": getattr(self.trace_ring, "dropped", 0)}
        return dump_chrome_trace(traces, extra_meta=meta)


def _close_queues(queues: Dict[str, BatchingQueue]):
    for q in queues.values():
        q.close()


def _worker_loop(engine_ref, name: str, q: BatchingQueue, max_rows: int):
    """One model's batcher.  Holds the engine weakly (see
    ``_ensure_worker``) and re-resolves the registry entry per batch so
    an ``unregister`` + ``register`` under the same name serves the NEW
    model instead of a stale closure capture."""
    while True:
        batch = q.get_batch(max_rows)
        if batch is None:
            return
        if not batch:
            continue
        eng = engine_ref()
        if eng is None:
            q.close()
            # engine (and its trace ring) already collected: the traces
            # die with it, nothing left to export them from
            _fail_batch(batch, EngineClosedError(
                "engine was garbage-collected before this request ran"))
            return
        try:
            try:
                entry = eng.registry.get(name)
            except KeyError as e:
                _fail_batch(batch, e, ring=eng.trace_ring)
                continue
            try:
                eng._run_batch(entry, q, batch)
            except Exception as e:   # the batcher thread must survive
                eng.recorder.inc("serving.errors")
                _fail_batch(batch, e, ring=eng.trace_ring)
        finally:
            del eng       # never hold the engine across a blocking wait


def _fail_batch(batch: List[Request], exc: BaseException, ring=None,
                span: str = "error"):
    """Complete every still-pending request exceptionally AND finish its
    trace with a terminal cause span — the error path is exactly where
    an operator reads /trace, so it must not go dark there.  Requests
    already completed (e.g. deadline-shed inside a failed _run_batch,
    traces already finished) are skipped via future.done()."""
    for r in batch:
        if r.future.done():
            continue
        tr = r.trace
        if ring is not None and tr is not None:
            # finish the trace BEFORE completing the future: a client
            # that reacts to the exception by scraping /trace must see
            # this request's track
            tr.terminal(type(exc).__name__, time.monotonic(), name=span)
            ring.finish(tr)
        r.future.set_exception(exc)


class _UnbatchingFuture(Future):
    """Future view that strips the batch dim the engine added for a
    single-sample submit, so clients get back the shape they sent."""

    def __init__(self, inner: Future):
        super().__init__()
        inner.add_done_callback(self._propagate)

    def _propagate(self, inner: Future):
        e = inner.exception()
        if e is not None:
            self.set_exception(e)
        else:
            self.set_result(jax.tree_util.tree_map(
                lambda a: a[0], inner.result()))
