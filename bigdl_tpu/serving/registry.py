"""Multi-model registry with immutable weight snapshots and atomic
hot-swap.

Why snapshots: the Torch-shell modules mutate ``self._params`` in place
(``set_weights``, ``load_weights``, a training loop), and any serving
path that captures that dict once then reads it forever serves *stale*
weights — the exact bug class ``Module.predict_image`` had with its
one-time sub-model snapshot.  Here the unit of truth is an immutable
:class:`Snapshot` (params, state, version); readers grab
``entry.snapshot`` once per micro-batch (a single attribute read —
atomic under the GIL) and swaps publish a *new* Snapshot only after the
replacement tree has been validated leaf-by-leaf against the old one.
A batch therefore runs against exactly one weight version, never a
half-swapped mix, and a failed swap changes nothing.

Shape/dtype validation on swap is not bureaucracy: the engine's
compiled executables are keyed by input bucket and assume fixed
parameter avals — admitting a differently-shaped tree would either
crash mid-batch or silently trigger the recompile the bucket ladder
exists to prevent.
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from .. import faults as faultplane
from ..nn.module import Module
from ..utils.retry import RetryPolicy


class Snapshot:
    """Immutable (params, state, version) triple; swaps replace the
    whole object, never mutate one."""

    __slots__ = ("params", "state", "version")

    def __init__(self, params, state, version: str):
        object.__setattr__(self, "params", params)
        object.__setattr__(self, "state", state)
        object.__setattr__(self, "version", version)

    def __setattr__(self, *a):
        raise AttributeError("Snapshot is immutable; publish a new one")

    def __repr__(self):
        return f"Snapshot(version={self.version!r})"


class ModelEntry:
    """One served model: the module, its live Snapshot, input spec, and
    the per-bucket compiled-executable cache the engine fills."""

    def __init__(self, name: str, model: Module, snapshot: Snapshot,
                 input_shape: Optional[Tuple[int, ...]],
                 dtype, inference_only: bool = False,
                 calibration_data=None):
        self.name = name
        self.model = model
        self.snapshot = snapshot
        self.input_shape = input_shape
        self.dtype = dtype
        # int8-rewritten modules carry frozen weights as jitted-in
        # constants, so a weight swap cannot reuse the compiled buckets
        self.inference_only = inference_only
        # the calibration batches an int8 entry was quantized with —
        # kept so a canary promotion can re-quantize the degrade entry
        # from the NEW weights with the same activation scales
        self.calibration_data = calibration_data
        self.compiled: Dict[int, Any] = {}     # bucket -> executable
        # bucket -> XLA cost/memory capture (observability.profile):
        # what one execution of that bucket costs, harvested at compile
        self.cost: Dict[int, Any] = {}
        self.warmed = False
        self.swap_lock = threading.Lock()
        # auto versions start at v2: v1 is the registration snapshot
        self._version_counter = itertools.count(2)

    def next_version(self) -> str:
        return f"v{next(self._version_counter)}"


class ModelRegistry:
    """Named, versioned models behind one serving engine
    (≙ optim/PredictionService.scala's model pool, grown multi-model)."""

    def __init__(self):
        self._entries: Dict[str, ModelEntry] = {}
        self._lock = threading.Lock()
        # a hot-swap that hits a transient blip (weights streamed off
        # storage, an injected serving.swap fault) retries briefly
        # before the publisher sees a failure; the old snapshot keeps
        # serving throughout, so retrying here is free of risk
        self._swap_retry = RetryPolicy(max_attempts=3, base=0.01,
                                       max_delay=0.2, name="serving.swap")

    # -- registration ----------------------------------------------------- #
    def register(self, name: str, model: Module, *,
                 input_shape: Optional[Tuple[int, ...]] = None,
                 dtype=np.float32, version: Optional[str] = None,
                 quantize_int8: bool = False,
                 calibration_data=None) -> ModelEntry:
        """Add ``model`` under ``name``.

        ``input_shape`` is one sample's feature shape (no batch dim);
        it is required for :meth:`~bigdl_tpu.serving.ServingEngine.warmup`
        to pre-compile the bucket ladder (without it the first request
        of each bucket pays — and counts — a recompile).

        ``quantize_int8=True`` routes through
        :func:`bigdl_tpu.quantized.quantize_for_serving` first; pass
        ``calibration_data`` (input batches) to bake static activation
        scales.  Int8 entries are inference-only: hot-swap requires
        :meth:`swap_model` + re-warm, since the int8 weights are
        compile-time constants.
        """
        inference_only = False
        if quantize_int8:
            from ..quantized import quantize_for_serving
            model = quantize_for_serving(model,
                                         calibration_data=calibration_data)
            inference_only = True
        model.ensure_initialized()
        entry = ModelEntry(
            name, model,
            Snapshot(model._params, dict(model._state or {}),
                     version or "v1"),
            None if input_shape is None else tuple(input_shape),
            np.dtype(dtype), inference_only=inference_only,
            calibration_data=calibration_data if quantize_int8
            else None)
        with self._lock:
            if name in self._entries:
                raise ValueError(f"model {name!r} already registered; "
                                 "use swap_weights/swap_model to update")
            self._entries[name] = entry
        return entry

    def unregister(self, name: str) -> ModelEntry:
        with self._lock:
            return self._entries.pop(name)

    def get(self, name: str) -> ModelEntry:
        with self._lock:
            try:
                return self._entries[name]
            except KeyError:
                raise KeyError(
                    f"no model {name!r}; registered: "
                    f"{sorted(self._entries)}") from None

    def names(self):
        with self._lock:
            return sorted(self._entries)

    def entries(self):
        with self._lock:
            return list(self._entries.values())

    # -- hot swap --------------------------------------------------------- #
    def swap_weights(self, name: str, params=None, state=None,
                     version: Optional[str] = None) -> Snapshot:
        """Atomically publish new weights for ``name``.

        The replacement tree must match the live snapshot leaf-for-leaf
        in structure, shape, and dtype (validated *before* publishing,
        so a bad swap leaves the old snapshot serving).  In-flight
        micro-batches finish on whichever snapshot they grabbed; new
        batches see the new one — no half-updated state is ever visible.
        """
        entry = self.get(name)
        if entry.inference_only:
            raise ValueError(
                f"model {name!r} is int8/inference-only: its weights are "
                "compiled-in constants; use swap_model() and re-warm")
        with entry.swap_lock:
            old = entry.snapshot
            new_params = old.params if params is None else params
            new_state = old.state if state is None else state

            def validate():
                faultplane.inject("serving.swap")
                _check_same_avals(f"{name}.params", old.params,
                                  new_params)
                _check_same_avals(f"{name}.state", old.state, new_state)
                return Snapshot(new_params, new_state,
                                version or entry.next_version())
            # transient-only retries; a ValueError (shape/dtype drift)
            # is fatal and raises with the old snapshot still serving
            snap = self._swap_retry.run(validate)
            entry.snapshot = snap          # the atomic publish
            # keep the shell module coherent for non-serving callers
            entry.model._params = new_params
            entry.model._state = dict(new_state)
            return snap

    def sync_from_model(self, name: str,
                        version: Optional[str] = None) -> Snapshot:
        """Republish from the module's own ``_params``/``_state`` —
        the bridge for code that updated weights through the Torch shell
        (``set_weights``, ``load_weights``, an in-process trainer)."""
        entry = self.get(name)
        return self.swap_weights(name, entry.model._params,
                                 dict(entry.model._state or {}),
                                 version=version)

    def swap_model(self, name: str, model: Module,
                   version: Optional[str] = None) -> ModelEntry:
        """Replace the module itself (new architecture or a fresh int8
        rewrite).  Invalidates the compiled-bucket cache — call
        ``engine.warmup(name)`` before taking traffic or the next
        request per bucket pays a counted recompile."""
        entry = self.get(name)
        model.ensure_initialized()
        with entry.swap_lock:
            entry.model = model
            entry.snapshot = Snapshot(model._params,
                                      dict(model._state or {}),
                                      version or entry.next_version())
            entry.compiled = {}
            entry.warmed = False
        return entry


def _check_same_avals(label: str, old, new):
    ol = jax.tree_util.tree_flatten(old)
    nl = jax.tree_util.tree_flatten(new)
    if ol[1] != nl[1]:
        raise ValueError(f"swap {label}: tree structure changed "
                         f"({ol[1]} != {nl[1]})")
    for i, (a, b) in enumerate(zip(ol[0], nl[0])):
        sa, da = _aval(a)
        sb, db = _aval(b)
        if sa != sb or da != db:
            raise ValueError(
                f"swap {label}: leaf {i} changed from {sa}/{da} to "
                f"{sb}/{db}; compiled executables assume fixed avals")


def _aval(x):
    """(shape, dtype) from metadata only — the OLD snapshot's buffers
    may already be donated/deleted by a training step, and metadata
    survives deletion while materializing the values would not."""
    dt = getattr(x, "dtype", None)
    if dt is None:
        dt = np.asarray(x).dtype
    return np.shape(x), np.dtype(dt)
