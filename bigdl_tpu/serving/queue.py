"""Bounded request queue with deadline-driven micro-batching.

One :class:`BatchingQueue` feeds one model's batcher thread.  The
contract is built around two SLO rules:

  * **Shed at the door, not at the tail.**  A full queue rejects the
    incoming request immediately (:class:`LoadShedError`) instead of
    letting every queued request's latency collapse together — explicit
    backpressure the client can retry against, the reject-over-collapse
    policy of every production serving stack.
  * **A batch waits at most ``max_delay`` for company.**  The batcher
    flushes when it has ``max_rows`` rows *or* when the oldest queued
    request has waited ``max_delay`` seconds, whichever comes first, so
    a lone request's latency is bounded by ``max_delay`` + one model
    execution rather than "until the queue happens to fill".

Per-request deadlines ride on the :class:`Request` and are enforced by
the engine when the batch is popped (a request that is already dead is
completed exceptionally without wasting device time on it).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import List, Optional


class LoadShedError(RuntimeError):
    """Request rejected for SLO protection.  ``reason`` is
    ``"queue_full"`` (shed at admission) or ``"deadline"`` (expired
    before execution)."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"request shed ({reason}){': ' if detail else ''}"
                         f"{detail}")
        self.reason = reason


class EngineClosedError(RuntimeError):
    """Submit after shutdown began."""


class Request:
    """One in-flight prediction: ``x`` is ``(n, *feature_shape)``.

    ``trace`` optionally carries a
    :class:`~bigdl_tpu.observability.profile.RequestTrace` — the
    per-request span timeline (admit → queue → batch_gather → compute →
    reply) the engine exports as Chrome-trace JSON via ``/trace``."""

    __slots__ = ("x", "n", "future", "arrival", "deadline", "trace")

    def __init__(self, x, n: int, deadline: Optional[float] = None,
                 trace=None):
        self.x = x
        self.n = int(n)
        self.future: Future = Future()
        self.arrival = time.monotonic()
        self.deadline = deadline        # absolute monotonic seconds, or None
        self.trace = trace

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now if now is not None else time.monotonic())
                > self.deadline)


class BatchingQueue:
    """Thread-safe bounded FIFO of :class:`Request` with batch gather.

    ``max_pending_rows`` bounds the queue in *rows* (single-sample
    requests and size-17 requests cost what they cost), the unit the
    SLO math actually works in.
    """

    def __init__(self, max_pending_rows: int = 256,
                 max_delay: float = 0.005):
        if max_pending_rows < 1:
            raise ValueError("max_pending_rows must be >= 1")
        self.max_pending_rows = int(max_pending_rows)
        self.max_delay = float(max_delay)
        self._items: deque = deque()
        self._rows = 0
        self._cond = threading.Condition()
        self._closed = False

    # -- producer side --------------------------------------------------- #
    def put(self, req: Request):
        """Admit ``req`` or shed it.  Raises :class:`LoadShedError` when
        the queue is at capacity and :class:`EngineClosedError` after
        :meth:`close`."""
        with self._cond:
            if self._closed:
                raise EngineClosedError("serving queue is closed")
            if self._rows + req.n > self.max_pending_rows:
                raise LoadShedError(
                    "queue_full",
                    f"{self._rows} rows pending, cap "
                    f"{self.max_pending_rows}")
            self._items.append(req)
            self._rows += req.n
            self._cond.notify()

    def depth(self) -> int:
        """Pending rows (the queue-depth gauge)."""
        with self._cond:
            return self._rows

    def close(self):
        """Stop admissions; queued requests still drain via
        :meth:`get_batch` until it returns ``None``."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def dump(self) -> List[Request]:
        """Remove and return everything still queued (fast-shutdown
        path: the caller fails the dumped requests explicitly)."""
        with self._cond:
            items = list(self._items)
            self._items.clear()
            self._rows = 0
            self._cond.notify_all()
            return items

    # -- consumer side ---------------------------------------------------- #
    def get_batch(self, max_rows: int) -> Optional[List[Request]]:
        """Block for the next micro-batch.

        Returns up to ``max_rows`` rows of FIFO-ordered requests, never
        splitting a request.  Flushes when full, when the oldest request
        has waited ``max_delay``, or immediately on :meth:`close`.
        Returns ``None`` once closed *and* empty (drain complete).
        """
        with self._cond:
            while not self._items:
                if self._closed:
                    return None
                self._cond.wait()
            flush_at = self._items[0].arrival + self.max_delay
            batch: List[Request] = []
            rows = 0
            while True:
                head_blocked = False
                while self._items:
                    nxt = self._items[0]
                    if batch and rows + nxt.n > max_rows:
                        # head doesn't fit: nothing behind it may jump
                        # the FIFO, so this batch is as full as it gets
                        head_blocked = True
                        break
                    self._items.popleft()
                    self._rows -= nxt.n
                    rows += nxt.n
                    batch.append(nxt)
                if rows >= max_rows or head_blocked or self._closed:
                    break
                remaining = flush_at - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            if self._items:
                self._cond.notify()   # more work for the next get_batch
            return batch
