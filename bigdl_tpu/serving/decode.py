"""Continuous-batching decode engine: token streaming over a paged KV
cache.

The PR-2 :class:`~bigdl_tpu.serving.ServingEngine` batches *fixed-shape*
forward passes — the right contract for classification, the wrong one
for token streaming, where a request's cost is per generated token and
a static batch idles every chip on its slowest member.  This engine
decodes at **slot** granularity instead:

    submit(prompt)                      client threads
       └─ bounded waiting queue         shed at the door when full
            └─ decode loop (one thread, owns the device pool)
                 admit  → free slot + pages → PREFILL (bucketed prompt
                          length through the PR-2 BucketLadder: one
                          AOT-compiled program per bucket, so a mixed
                          prompt stream compiles NOTHING post-warmup)
                 step   → ONE jitted fixed-shape program advances every
                          live slot by one token (per-slot positions,
                          page-table gather/scatter — kvcache.py)
                 retire → eos / max_new / deadline: free the slot's
                          pages, complete the future, recycle the slot
                 evict  → a slot that cannot grow a page when the pool
                          saturates evicts the YOUNGEST other admission
                          (never an older one — the oldest request
                          always completes, which is what makes the
                          dance livelock-free); the victim re-queues
                          and on readmission RE-PREFILLS its prompt
                          then REPLAYS its recorded tokens through the
                          decode program (same programs, same inputs →
                          the rebuilt KV is bitwise the evicted one,
                          so greedy decode continues exactly)

Slot membership changes every step, shapes never do: dead slots ride
along as masked rows (page-table ``-1`` = gather zeros / scatter
drops), so join/leave churn is data, not a recompile.  Measured decode
throughput scales with slot occupancy, not with the slowest request in
a static batch — ``scripts/decode_smoke.py`` pins the ≥ 1.5× CPU-proxy
win (BENCH_r09) and zero post-warmup recompiles under churn.

Per-token SLO accounting (families in docs/observability.md):
``decode/ttft_ms`` (submit → first token) and ``decode/intertoken_ms``
histograms, ``decode/*`` counters, ``kv/*`` pool gauges, and a
per-request PR-5 trace (admit → queue → prefill → one ``token`` span
per decode batch) in the same bounded :class:`TraceRing` /trace serves.
Shed requests finish their trace with a terminal cause span *before*
their future fails — the ServingEngine contract, kept on the decode
path too.

The engine speaks the ServingEngine replica protocol (``submit`` /
``predict`` / ``warmup`` / ``shutdown`` / ``pending_rows`` /
``max_queue_fill`` / ``stats`` / ``registry`` / ``recorder``), so a
:class:`~bigdl_tpu.serving.ReplicaSet` fronts decode replicas
unchanged — health scoring reads the per-token ``serving.rows``
progress, wedge ejection and failover re-decode on a peer, and
:class:`~bigdl_tpu.serving.CanaryPublisher` golden-DECODE-validates
weight publications (bit-identical rollback included).  Pair with
:class:`~bigdl_tpu.serving.stream.WeightStreamPublisher` for live
train→serve weight streaming.

Fault site: ``serving.decode_step`` fires ahead of every decode-step
dispatch (``delay`` = a wedged decode step — what the chaos leg arms;
``err`` = the step fails, live requests complete exceptionally and a
ReplicaSet fails them over).
"""
from __future__ import annotations

import queue as queue_mod
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import faults as faultplane
from ..observability import Recorder
from .buckets import BucketLadder
from .kvcache import PagedKVCache
from .queue import EngineClosedError, LoadShedError
from .registry import ModelRegistry

_END = object()


class DecodeStream:
    """One streaming decode: iterate :meth:`tokens` as they are emitted
    (ints), or wait for :attr:`future` — the full ``prompt + generated``
    int32 array.  A shed/failed request raises from both."""

    def __init__(self):
        self.future: Future = Future()
        self._q: "queue_mod.Queue" = queue_mod.Queue()

    def tokens(self):
        while True:
            t = self._q.get()
            if t is _END:
                # the future resolves before the end marker lands, so a
                # shed/failed request raises HERE too — a truncated
                # stream must never look like a short success
                exc = self.future.exception() if self.future.done() \
                    else None
                if exc is not None:
                    raise exc
                return
            yield t

    def result(self, timeout: Optional[float] = None):
        return self.future.result(timeout)


class _DecodeRequest:
    """One request across its whole lifecycle (including evictions)."""

    __slots__ = ("prompt", "max_new", "temperature", "eos_id", "deadline",
                 "arrival", "stream", "generated", "trace", "slot",
                 "first_token_at", "last_token_at", "evictions",
                 "replay_i")

    def __init__(self, prompt: np.ndarray, max_new: int,
                 temperature: float, eos_id: Optional[int],
                 deadline: Optional[float], stream: DecodeStream,
                 trace=None):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.deadline = deadline     # absolute monotonic seconds or None
        self.arrival = time.monotonic()
        self.stream = stream
        self.generated: List[int] = []
        self.trace = trace
        self.slot: Optional[int] = None
        self.first_token_at: Optional[float] = None
        self.last_token_at: Optional[float] = None
        self.evictions = 0
        # readmission replay cursor: > 0 while the slot is re-feeding
        # its recorded tokens through the decode program to rebuild the
        # evicted KV bitwise (see DecodeEngine._prefill)
        self.replay_i = 0

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline


class DecodeEngine:
    """Slot-based continuous-batching decode over one TransformerLM.

    ``registry`` / ``model_name``  the served entry; its module must be
                    a :class:`~bigdl_tpu.models.transformer.TransformerLM`
                    (``apply_with_cache`` prefill + ``decode_tokens``).
                    Weight hot-swap goes through the registry
                    (``swap_weights`` / CanaryPublisher) — the decode
                    loop picks up a new snapshot at the next step.
    ``slots``       concurrent sequences in the step batch
    ``page_size`` / ``pool_pages``  paged-KV geometry (kvcache.py);
                    ``pool_pages`` defaults to ``slots * max_context /
                    page_size`` (no eviction pressure); smaller pools
                    evict
    ``max_context`` longest prompt+generation a slot may hold
    ``max_prompt``  admission cap on client prompt length
                    (readmissions may re-prefill up to max_context)
    ``max_new_tokens``  default generation budget per request
    ``max_waiting`` waiting-queue bound, in requests — beyond it
                    submit sheds with :class:`LoadShedError`
                    (pool-exhaustion backpressure reaches the client
                    as queue growth, then as sheds)
    ``int8_kv``     store KV pages int8 with per-channel scales
    ``eos_id``      default stop token (None = run to max_new)
    ``seed``        sampling RNG seed (temperature > 0 requests)
    """

    #: a "row" here is one token of a SEQUENCE: ReplicaSet.predict must
    #: submit prompts whole, never slice them into batch chunks
    row_splittable = False

    def __init__(self, registry: ModelRegistry, model_name: str = "lm", *,
                 slots: int = 8, page_size: int = 16,
                 pool_pages: Optional[int] = None,
                 max_context: Optional[int] = None,
                 max_prompt: Optional[int] = None,
                 max_new_tokens: int = 32, max_waiting: int = 64,
                 int8_kv: bool = False, kv_dtype=None,
                 eos_id: Optional[int] = None, seed: int = 0,
                 recorder: Optional[Recorder] = None,
                 trace_requests: bool = True, trace_capacity: int = 512,
                 report_every: int = 32):
        from ..observability.profile import TraceRing
        self.registry = registry
        self.model_name = model_name
        entry = registry.get(model_name)
        model = entry.model
        if not hasattr(model, "apply_with_cache") \
                or not hasattr(model, "decode_tokens"):
            raise TypeError(
                f"DecodeEngine serves TransformerLM-style models with "
                f"apply_with_cache/decode_tokens; got "
                f"{type(model).__name__}")
        self.model = model
        cfg = model.cfg
        self.slots = int(slots)
        self.max_context = int(cfg.max_len if max_context is None
                               else max_context)
        if not 1 < self.max_context <= cfg.max_len:
            raise ValueError(f"max_context {self.max_context} must be in "
                             f"(1, max_len={cfg.max_len}]")
        self.max_prompt = int(self.max_context - 1 if max_prompt is None
                              else max_prompt)
        if not 0 < self.max_prompt < self.max_context:
            raise ValueError(f"max_prompt {self.max_prompt} must be in "
                             f"(0, max_context={self.max_context})")
        self.max_new_tokens = int(max_new_tokens)
        self.max_waiting = int(max_waiting)
        self.eos_id = eos_id
        self.recorder = recorder if recorder is not None \
            else Recorder(annotate=False)
        if self.recorder.enabled and self.recorder.get_ledger() is None:
            # goodput attribution: the decode loop folds every elapsed
            # interval by slot occupancy (goodput/queue_wait/idle), so
            # the engine owns its device (1 until multi-device decode)
            from ..observability.goodput import GoodputLedger
            self.recorder.set_ledger(GoodputLedger(
                name=f"decode:{model_name}", devices=1))
        self.trace_ring = TraceRing(trace_capacity) if trace_requests \
            else None
        self.report_every = int(report_every)
        # prefill buckets only ever see client prompts: a readmission
        # re-prefills its PROMPT and replays the generated tail through
        # the decode program, so the ladder tops out at max_prompt —
        # compiling buckets up to max_context would burn minutes of
        # warmup on programs nothing can reach
        self.ladder = BucketLadder(self.max_prompt)
        self.kv = PagedKVCache(
            [blk.attn.name for blk in model.blocks],
            n_heads=cfg.n_heads, head_dim=cfg.head_dim,
            n_pages=pool_pages if pool_pages is not None
            else self.slots * -(-self.max_context // page_size),
            page_size=page_size, n_slots=self.slots,
            max_context=self.max_context,
            dtype=kv_dtype or jnp.dtype(cfg.dtype), int8=int8_kv,
            recorder=self.recorder)
        self._base_key = jax.random.PRNGKey(int(seed))
        self._pool = self.kv.init_pool()
        self._pool_avals = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), self._pool)
        # slot state — mutated only by the decode thread
        self._lengths = np.zeros(self.slots, np.int32)
        self._last_tokens = np.zeros(self.slots, np.int32)
        self._admitted_at = np.zeros(self.slots, np.float64)
        self._live: Dict[int, _DecodeRequest] = {}
        self._steps = 0
        self._cached_snap = None
        self._cached_params = None
        # shared state — every read/write under self._lock (a Condition)
        self._lock = threading.Condition()
        self._waiting: List[_DecodeRequest] = []
        self._programs: Dict[Any, Any] = {}
        self._warmed = False
        self._closed = False
        self._drain = True
        self._thread: Optional[threading.Thread] = None
        self._http_server = None

    # -- lifecycle -------------------------------------------------------- #
    def warmup(self, name: Optional[str] = None):
        """AOT-compile every prefill bucket plus the decode step — the
        zero-recompile line in the sand: compiles here count
        ``decode/warmup_compiles``, any compile after it counts
        ``decode/recompiles`` (and on a TPU, a blown token SLO)."""
        if name is not None and name != self.model_name:
            raise KeyError(f"DecodeEngine serves {self.model_name!r}, "
                           f"not {name!r}")
        from ..observability.goodput import ledger_phase
        with self.recorder.span("decode.warmup"), \
                ledger_phase(self.recorder, "compile_warmup"):
            for bucket in self.ladder:
                self._program("prefill", bucket)
            self._program("decode")
        with self._lock:
            self._warmed = True
        return self

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop admissions; ``drain=True`` finishes live + queued work,
        ``drain=False`` fails it fast with :class:`EngineClosedError`."""
        with self._lock:
            self._closed = True
            self._drain = bool(drain)
            t = self._thread
            server, self._http_server = self._http_server, None
            self._lock.notify_all()
        if server is not None:
            server.stop()
        if t is not None:
            t.join(timeout)
        return self

    def telemetry_sources(self):
        """``[(model_name, recorder)]`` — the aggregator attachment
        hook (``aggregator.add(engine)`` scrapes the ``decode/*`` +
        ``kv/*`` SLO families)."""
        return [(self.model_name, self.recorder)]

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1"):
        """Live introspection for this engine's recorder: ``/metrics``
        (``decode/*`` + ``kv/*`` per-token SLO families), ``/healthz``,
        ``/records`` and ``/trace`` — same routes as ServingEngine."""
        from ..observability.http import IntrospectionServer
        trace_source = self.dump_chrome_trace \
            if self.trace_ring is not None else None
        server = IntrospectionServer(
            self.recorder, port=port, host=host,
            trace_source=trace_source).start()
        while True:
            with self._lock:
                if self._closed:
                    break
                prev = self._http_server
                if prev is None:
                    self._http_server = server
                    return server
                self._http_server = None
            prev.stop()
        server.stop()
        raise EngineClosedError(
            "engine shut down while serve_metrics was binding")

    def dump_chrome_trace(self) -> str:
        from ..observability.profile import dump_chrome_trace
        traces = self.trace_ring.traces() if self.trace_ring is not None \
            else []
        meta = {"dropped_traces": getattr(self.trace_ring, "dropped", 0)}
        return dump_chrome_trace(traces, extra_meta=meta)

    # -- request path ----------------------------------------------------- #
    def submit(self, name: str, x, deadline_ms: Optional[float] = None,
               max_new_tokens: Optional[int] = None,
               temperature: float = 0.0,
               eos_id: Optional[int] = None, trace_ctx=None) -> Future:
        """Enqueue one prompt; returns the Future of the full
        ``prompt + generated`` int32 array.  ``deadline_ms`` sheds the
        request when it expires before OR during decode (terminal
        ``deadline`` trace span, then the future fails).  ``trace_ctx``
        threads an upstream
        :class:`~bigdl_tpu.observability.context.TraceContext` into the
        slot-lifetime trace, so one trace id covers admission through
        every per-token step."""
        return self.stream(name, x, deadline_ms=deadline_ms,
                           max_new_tokens=max_new_tokens,
                           temperature=temperature, eos_id=eos_id,
                           trace_ctx=trace_ctx).future

    def stream(self, name: str, x, deadline_ms: Optional[float] = None,
               max_new_tokens: Optional[int] = None,
               temperature: float = 0.0,
               eos_id: Optional[int] = None,
               trace_ctx=None) -> DecodeStream:
        """Like :meth:`submit` but returns the :class:`DecodeStream`,
        whose :meth:`~DecodeStream.tokens` iterator yields tokens as
        the decode loop emits them."""
        t_admit = time.monotonic()
        if name != self.model_name:
            raise KeyError(f"DecodeEngine serves {self.model_name!r}, "
                           f"not {name!r}")
        prompt = np.asarray(x, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.size > self.max_prompt:
            raise ValueError(f"prompt length {prompt.size} exceeds "
                             f"max_prompt {self.max_prompt}")
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self.max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new > self.max_context:
            raise ValueError(
                f"prompt({prompt.size}) + max_new({max_new}) exceeds "
                f"max_context {self.max_context}")
        if self.kv.pages_for(prompt.size + max_new) > self.kv.n_pages:
            # a request the whole pool cannot hold would self-evict
            # forever once it ran alone — reject loudly at the door
            raise ValueError(
                f"request needs {self.kv.pages_for(prompt.size + max_new)}"
                f" pages at full length, pool has {self.kv.n_pages}; "
                "grow pool_pages or shrink max_new_tokens")
        rec = self.recorder
        rec.inc("decode/requests")
        rec.inc("serving.requests")
        ring = self.trace_ring
        tr = ring.new_trace(self.model_name, ctx=trace_ctx) \
            if ring is not None else None
        if tr is not None:
            tr.meta.update(prompt_len=int(prompt.size), max_new=max_new)
        deadline = None if deadline_ms is None \
            else t_admit + float(deadline_ms) / 1e3
        stream = DecodeStream()
        req = _DecodeRequest(prompt, max_new, temperature,
                             eos_id if eos_id is not None else self.eos_id,
                             deadline, stream, trace=tr)
        if tr is not None:
            now = time.monotonic()
            tr.add_span("admit", t_admit, now)
            tr.open("queue", now)
        with self._lock:
            if self._closed:
                if tr is not None:
                    tr.discard("queue")
                    tr.terminal("engine_closed", time.monotonic(),
                                name="closed")
                    ring.finish(tr)
                raise EngineClosedError("decode engine is shut down")
            if len(self._waiting) >= self.max_waiting:
                rec.inc("decode/shed_queue_full")
                if tr is not None:
                    tr.discard("queue")
                    tr.terminal("queue_full", time.monotonic())
                    ring.finish(tr)
                raise LoadShedError(
                    "queue_full",
                    f"{len(self._waiting)} requests waiting, cap "
                    f"{self.max_waiting}")
            self._waiting.append(req)
            self._ensure_loop_locked()
            self._lock.notify_all()
            depth = len(self._waiting)
        rec.gauge("decode/queue_depth", depth)
        return stream

    def predict(self, name: str, x, timeout: Optional[float] = None,
                deadline_ms: Optional[float] = None, **kw):
        """Synchronous decode (the CanaryPublisher golden-decode path):
        greedy by default, deterministic, so two predictions from the
        same snapshot are bitwise equal."""
        return self.submit(name, x, deadline_ms=deadline_ms,
                           **kw).result(timeout)

    # -- replica-protocol introspection ------------------------------------ #
    def pending_rows(self) -> int:
        """Outstanding work in tokens: queued prompts + generation
        budgets, plus what live slots still owe.  Zero means fully
        idle — the canary quiesce gate."""
        with self._lock:
            waiting = list(self._waiting)
            live = list(self._live.values())
        n = sum(int(r.prompt.size) + r.max_new for r in waiting)
        n += sum(max(r.max_new - len(r.generated), 1) for r in live)
        return n

    def max_queue_fill(self) -> float:
        with self._lock:
            return len(self._waiting) / self.max_waiting

    def stats(self) -> Dict[str, Any]:
        rec = self.recorder
        out = {k: rec.counter_value(f"decode/{k}")
               for k in ("requests", "prefills", "readmissions", "steps",
                         "tokens", "finished", "shed_queue_full",
                         "shed_deadline", "recompiles", "warmup_compiles",
                         "errors")}
        steps = max(out["steps"], 1.0)
        out["occupancy"] = out["tokens"] / (steps * self.slots)
        out["kv_pool_fill"] = self.kv.fill()
        out["kv_peak_fill"] = rec.gauge_value("kv/peak_fill")
        out["evictions"] = rec.counter_value("kv/evictions")
        for h, label in (("decode/ttft_ms", "ttft"),
                         ("decode/intertoken_ms", "intertoken")):
            q = rec.hist_quantiles(h, (50.0, 99.0))
            if q:
                out[f"{label}_p50_ms"] = q.get("p50")
                out[f"{label}_p99_ms"] = q.get("p99")
        return out

    # -- program cache ----------------------------------------------------- #
    def _program(self, kind: str, bucket: Optional[int] = None):
        key = (kind, bucket)
        with self._lock:
            prog = self._programs.get(key)
            warmed = self._warmed
        if prog is not None:
            return prog
        if warmed:
            # post-warmup compile: the token-SLO violation the bucket
            # ladder exists to prevent — counted, never silent
            self.recorder.inc("decode/recompiles")
        from ..observability.goodput import ledger_phase
        with ledger_phase(self.recorder, "compile_warmup"):
            prog = self._compile(kind, bucket)
        with self._lock:
            self._programs[key] = prog
        return prog

    def _aval_params(self):
        snap = self.registry.get(self.model_name).snapshot
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a),
                                           getattr(a, "dtype", None)
                                           or np.asarray(a).dtype),
            snap.params)

    def _compile(self, kind: str, bucket: Optional[int]):
        """AOT jit → lower → compile (at avals, so no buffers move and
        nothing is donated at build time); falls back to the plain
        jitted callable on backends without the AOT API — the program
        cache still keeps the recompile counter exact."""
        model, kv = self.model, self.kv
        base_key = self._base_key
        if kind == "decode":
            def fn(params, pool, tokens, lengths, tables, temps, step):
                new_pool = dict(pool)

                def kv_io(name, k_new, v_new):
                    new_pool[name] = kv.write_token(
                        new_pool[name], tables, lengths, k_new, v_new)
                    return kv.gather_window(new_pool[name], tables)

                logits = model.decode_tokens(params, tokens, lengths,
                                             kv_io)
                tok = _select_tokens(logits, temps, step, base_key)
                # poisoned-weights sentinel: argmax of NaN logits is a
                # VALID token id, so without this a poisoned publish
                # would stream plausible garbage; per-slot flags let
                # the engine fail exactly the affected requests (and a
                # canary golden-decode reject the publication)
                bad = ~jnp.isfinite(logits).all(axis=-1)
                return tok, bad, new_pool

            args = (self._aval_params(), self._pool_avals,
                    jax.ShapeDtypeStruct((self.slots,), jnp.int32),
                    jax.ShapeDtypeStruct((self.slots,), jnp.int32),
                    jax.ShapeDtypeStruct(
                        (self.slots, self.kv.max_pages_per_slot),
                        jnp.int32),
                    jax.ShapeDtypeStruct((self.slots,), jnp.float32),
                    jax.ShapeDtypeStruct((), jnp.int32))
        else:
            n_pages = -(-bucket // kv.page_size)
            cache_dtype = kv.dtype if not kv.int8 \
                else jnp.dtype(model.cfg.dtype)

            def fn(params, pool, tokens, true_len, table, temp, step):
                cache = model.init_cache(1, dtype=cache_dtype,
                                         cache_len=bucket)
                logits, cache = model.apply_with_cache(
                    params, tokens, cache, 0)
                new_pool = dict(pool)
                for name in kv.layer_names:
                    new_pool[name] = kv.write_prefill(
                        new_pool[name], table, cache[name]["k"],
                        cache[name]["v"])
                last = jnp.take(logits[0], true_len - 1, axis=0)
                tok = _select_tokens(last[None, :], temp[None], step,
                                     base_key)[0]
                bad = ~jnp.isfinite(last).all()
                return tok, bad, new_pool

            args = (self._aval_params(), self._pool_avals,
                    jax.ShapeDtypeStruct((1, bucket), jnp.int32),
                    jax.ShapeDtypeStruct((), jnp.int32),
                    jax.ShapeDtypeStruct((n_pages,), jnp.int32),
                    jax.ShapeDtypeStruct((), jnp.float32),
                    jax.ShapeDtypeStruct((), jnp.int32))
        jitted = jax.jit(fn, donate_argnums=(1,))
        with self.recorder.span("decode.compile"):
            try:
                prog = jitted.lower(*args).compile()
            except (AttributeError, NotImplementedError):
                # no AOT lower/compile on this backend/jax: the jitted
                # wrapper still serves and the program cache keeps the
                # recompile counter exact.  Genuine trace failures
                # propagate — warmup must not report success over a
                # broken model
                prog = jitted
        if not self._warmed:
            self.recorder.inc("decode/warmup_compiles")
        return prog

    def _params_for_step(self, entry):
        """Device-placed params of the CURRENT snapshot, cached per
        snapshot object: a hot-swap/canary publish lands at the next
        step without re-placing every step."""
        snap = entry.snapshot
        if snap is not self._cached_snap:
            self._cached_params = jax.device_put(snap.params)
            self._cached_snap = snap
        return self._cached_params

    # -- decode loop ------------------------------------------------------- #
    def _ensure_loop_locked(self):
        if self._thread is None or not self._thread.is_alive():
            # the thread holds the engine weakly so a dropped engine is
            # collectable; _decode_loop fails stranded requests then
            t = threading.Thread(
                target=_decode_loop,
                args=(weakref.ref(self), self._lock, self._waiting,
                      self._live, self.trace_ring),
                daemon=True, name=f"decode-{self.model_name}")
            self._thread = t
            t.start()

    def _tick(self) -> bool:
        """One scheduling round; returns False when the loop should
        exit (closed and nothing left to do)."""
        with self._lock:
            has_work = bool(self._waiting) or bool(self._live)
            closed, drain = self._closed, self._drain
            if closed and not drain:
                stranded = list(self._waiting) + list(self._live.values())
                self._waiting[:] = []
                live_slots = list(self._live)
                self._live.clear()
            elif not has_work:
                if closed:
                    return False
                # zero the load gauges while parked: occupancy is only
                # written from live steps, so without this an idle
                # engine scrapes its LAST in-flight value forever — a
                # phantom load that wedges the autoscaler's
                # calm/scale-down detection (same reasoning as the
                # queue_depth gauge in _admit)
                self.recorder.gauge("decode/live_slots", 0)
                self.recorder.gauge("decode/occupancy", 0.0)
                led = self.recorder.get_ledger()
                if led is not None:
                    # parked time folds to the background phase (idle,
                    # or whatever a producer declared) instead of being
                    # smeared into the next step's occupancy split
                    led.note_step_begin()
                self._lock.wait(0.1)
                return True
        if closed and not drain:
            exc = EngineClosedError("engine shut down before this "
                                    "request finished")
            for slot in live_slots:
                self.kv.free_slot(slot)
            for req in stranded:
                self._finish(req, exc=exc, cause="closed")
            self.recorder.gauge("decode/queue_depth", 0)
            return False
        try:
            self._admit()
            self._step_live()
        except Exception as e:       # the decode loop must survive
            self.recorder.inc("decode/errors")
            self._recover_pool(e)
        return True

    def _admit(self):
        """Move waiting requests into free slots (expired ones shed);
        each admission is one bucketed prefill."""
        while True:
            with self._lock:
                if not self._waiting:
                    return
                free = [s for s in range(self.slots)
                        if s not in self._live]
                if not free:
                    return
                req = self._waiting[0]
                now = time.monotonic()
                if req.expired(now):
                    self._waiting.pop(0)
                    shed = True
                else:
                    prompt = req.prompt
                    if not self.kv.can_fit(prompt.size):
                        # pool-exhaustion backpressure: admissions NEVER
                        # evict (an admission that evicts a live slot
                        # invites eviction ping-pong — the live set must
                        # shrink through completions, not grow through
                        # preemption); the request waits for pages, and
                        # sustained saturation surfaces to clients as
                        # queue growth, then queue_full sheds
                        return
                    self._waiting.pop(0)
                    shed = False
                # gauge tracks the queue as it DRAINS too, or an idle
                # engine scrapes a phantom backlog forever
                self.recorder.gauge("decode/queue_depth",
                                    len(self._waiting))
            if shed:
                self._shed_deadline(req, at="queue")
                continue
            slot = free[0]
            if not self.kv.alloc_for(slot, prompt.size):
                with self._lock:        # raced below can_fit: wait
                    self._waiting.insert(0, req)
                    depth = len(self._waiting)
                self.recorder.gauge("decode/queue_depth", depth)
                return
            try:
                self._prefill(slot, req, prompt)
            except Exception as e:
                self.recorder.inc("decode/errors")
                self._live.pop(slot, None)
                self.kv.free_slot(slot)
                self._finish(req, exc=e)
                self._recover_pool(e)

    def _evict_for(self, needy_slot: int, n_tokens: int) -> bool:
        """Evict slots YOUNGER than ``needy_slot`` (most recent
        admission first) until it can hold ``n_tokens``; the victims
        re-queue and re-prefill + replay on readmission.  Returns False
        when no younger victim remains — the needy slot then yields
        itself.

        Why youngest-first and never anyone older: the oldest live
        admission must NEVER lose its pages, so it always runs to
        completion — a strictly-decreasing potential that makes the
        eviction dance livelock-free.  (The obvious opposite — evict
        the least-recently-admitted — deadlocks a tight pool: each
        fresh admission's first page growth steals the pages of a
        mid-replay victim, whose replay then restarts from zero,
        forever.  Measured: 8.7k evictions, zero completions.)"""
        while not self.kv.alloc_for(needy_slot, n_tokens):
            victims = [s for s in self._live
                       if s != needy_slot
                       and self._admitted_at[s]
                       > self._admitted_at[needy_slot]]
            if not victims:
                return False
            victim = max(victims, key=lambda s: self._admitted_at[s])
            self._evict(victim)
        return True

    def _evict(self, slot: int):
        req = self._live.pop(slot)
        self.kv.free_slot(slot, evict=True)
        req.slot = None
        req.evictions += 1
        if req.trace is not None:
            req.trace.meta["evictions"] = req.evictions
        with self._lock:
            self._waiting.append(req)
            depth = len(self._waiting)
        # the gauge must see evicted re-queues too: saturation is when
        # the runbook reads it
        self.recorder.gauge("decode/queue_depth", depth)

    def _prefill(self, slot: int, req: _DecodeRequest, prompt: np.ndarray):
        rec = self.recorder
        t0 = time.monotonic()
        if req.trace is not None:
            req.trace.close("queue", t0)
            req.trace.open("prefill", t0)
        bucket = self.ladder.bucket_for(prompt.size)
        prog = self._program("prefill", bucket)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :prompt.size] = prompt
        # the prompt bucket may round up past max_context, so its page
        # span can exceed the slot's table row: pad with -1 (dropped
        # writes of padding-only pages)
        n_pages = -(-bucket // self.kv.page_size)
        table = np.full(n_pages, -1, np.int32)
        m = min(n_pages, self.kv.max_pages_per_slot)
        table[:m] = self.kv.tables[slot, :m]
        entry = self.registry.get(self.model_name)
        with rec.span("decode.prefill"):
            tok, bad, self._pool = prog(
                self._params_for_step(entry), self._pool,
                jnp.asarray(toks), jnp.int32(prompt.size),
                jnp.asarray(table), jnp.float32(req.temperature),
                jnp.int32(self._steps))
            token = int(tok)
        if bool(bad):
            # poisoned-weights sentinel: the program call SUCCEEDED
            # (self._pool was reassigned), so this is one request's
            # failure, not a donation hazard — fail it alone; the other
            # live slots' KV is intact and must survive (_recover_pool
            # would collaterally error every in-flight request)
            rec.inc("decode/nonfinite")
            if req.trace is not None:
                req.trace.close("prefill", time.monotonic(),
                                bucket=bucket)
            self.kv.free_slot(slot)
            self._finish(req, exc=RuntimeError(
                f"non-finite prefill logits serving "
                f"{entry.snapshot.version} — poisoned weights?"),
                cause="nonfinite")
            return
        now = time.monotonic()
        rec.inc("decode/prefills")
        led = rec.get_ledger()
        if led is not None:
            # a prefill is productive single-sequence compute
            led.fold_split({"goodput": 1.0})
        req.slot = slot
        self._live[slot] = req
        # slot arrays (_lengths/_last_tokens/_admitted_at) are decode-
        # thread-only by construction (single mutator: every writer
        # runs on the decode loop); cross-thread reads go through
        # stats()/pending_rows(), which read queue/live under the lock
        self._lengths[slot] = prompt.size   # graftlint: disable=GL003
        self._admitted_at[slot] = now
        if req.trace is not None:
            req.trace.close("prefill", now, bucket=bucket,
                            prompt_rows=int(prompt.size))
        if req.generated:
            # READMISSION: the prompt prefill above is the same program
            # at the same bucket as the original admission, so its KV
            # (and the token it re-predicts, which we discard) are
            # bitwise the originals.  The recorded generated tokens now
            # REPLAY through the decode program — the exact program
            # that wrote their KV the first time — so the rebuilt cache
            # is bitwise identical and greedy decode continues exactly
            # where the eviction cut it off.  (Re-prefilling
            # prompt+generated instead would recompute the generated
            # rows' KV through a different batched-matmul program,
            # whose last-ulp drift can flip a later argmax.)
            rec.inc("decode/readmissions")
            req.replay_i = 1
            # decode-thread-only slot array (see _lengths note above)
            self._last_tokens[slot] = req.generated[0]  # graftlint: disable=GL003
        else:
            self._emit_token(slot, req, token, now)

    def _step_live(self):
        """One fixed-shape decode step over every live slot."""
        if not self._live:
            return
        rec = self.recorder
        now = time.monotonic()
        # deadline sheds + page growth happen BEFORE the step so the
        # step's inputs are consistent
        for slot in list(self._live):
            req = self._live.get(slot)
            if req is None:
                continue            # evicted by an earlier slot's growth
            if req.expired(now):
                self._live.pop(slot)
                self.kv.free_slot(slot)
                self._shed_deadline(req, at="decode")
                continue
            if not self.kv.alloc_for(slot, int(self._lengths[slot]) + 1):
                if not self._evict_for(slot, int(self._lengths[slot]) + 1):
                    # nothing else to evict: this slot itself yields
                    self._evict(slot)
        if not self._live:
            return
        live_slots = sorted(self._live)
        tokens = self._last_tokens.copy()
        lengths = self._lengths.copy()
        temps = np.zeros(self.slots, np.float32)
        for s in live_slots:
            temps[s] = self._live[s].temperature
        dead = [s for s in range(self.slots) if s not in self._live]
        for s in dead:
            tokens[s] = 0
            lengths[s] = 0
        entry = self.registry.get(self.model_name)
        prog = self._program("decode")
        # chaos seam: delay = a wedged decode step (the replica wedge
        # verdict's shape), err = the step fails and live requests
        # complete exceptionally (a ReplicaSet fails them over)
        faultplane.inject("serving.decode_step", rec)
        with rec.span("decode.step"):
            tok, bad, self._pool = prog(
                self._params_for_step(entry), self._pool,
                jnp.asarray(tokens), jnp.asarray(lengths),
                jnp.asarray(self.kv.tables), jnp.asarray(temps),
                jnp.int32(self._steps))
            toks = np.asarray(tok)     # the per-step host sync — the
            # serving contract: every emitted token crosses to the host
            bads = np.asarray(bad)
        now = time.monotonic()
        for slot in list(self._live):
            if slot in self._live and bads[slot]:
                rec.inc("decode/nonfinite")
                req = self._live.pop(slot)
                self.kv.free_slot(slot)
                self._finish(req, exc=RuntimeError(
                    f"non-finite decode logits serving "
                    f"{entry.snapshot.version} — poisoned weights?"),
                    cause="nonfinite")
        live_slots = [s for s in live_slots if s in self._live]
        if not live_slots:
            return
        self._steps += 1
        n_live = len(live_slots)
        rec.inc("decode/steps")
        rec.inc("decode/tokens", n_live)
        rec.inc("serving.rows", n_live)   # per-token progress: replica
        # health must see a long generation as work, not a wedge
        rec.gauge("decode/live_slots", n_live)
        rec.gauge("decode/occupancy", n_live / self.slots)
        led = rec.get_ledger()
        if led is not None:
            # the goodput fold: this step's interval splits by slot
            # occupancy — live slots are goodput, spare slots backed by
            # queued work are queue_wait (capacity idling while admitted
            # work waits on pages), the rest is honest idle
            with self._lock:
                depth = len(self._waiting)
            spare = self.slots - n_live
            led.fold_split({"goodput": n_live,
                            "queue_wait": min(spare, depth),
                            "idle": max(spare - depth, 0)})
        for slot in live_slots:
            self._lengths[slot] += 1
            req = self._live[slot]
            if req.replay_i and req.replay_i < len(req.generated):
                # replaying a readmitted slot: this step's prediction
                # was already emitted before the eviction — feed the
                # recorded token onward, emit nothing
                self._last_tokens[slot] = req.generated[req.replay_i]
                req.replay_i += 1
                rec.inc("decode/replayed_tokens")
                continue
            if req.replay_i:
                req.replay_i = 0       # caught up: prediction is fresh
            self._emit_token(slot, req, int(toks[slot]), now)
        if self.report_every and self._steps % self.report_every == 0:
            self._emit_decode_event()

    def _emit_token(self, slot: int, req: _DecodeRequest, token: int,
                    now: float):
        rec = self.recorder
        req.generated.append(token)
        self._last_tokens[slot] = token
        if req.first_token_at is None:
            req.first_token_at = now
            rec.observe("decode/ttft_ms", (now - req.arrival) * 1e3)
        elif req.last_token_at is not None:
            rec.observe("decode/intertoken_ms",
                        (now - req.last_token_at) * 1e3)
        if req.trace is not None:
            # one span per token batch this request took part in
            req.trace.add_span("token",
                               req.last_token_at or req.first_token_at,
                               now)
        req.last_token_at = now
        req.stream._q.put(token)
        done = len(req.generated) >= req.max_new \
            or (req.eos_id is not None and token == req.eos_id)
        if done:
            self._live.pop(slot, None)
            self.kv.free_slot(slot)
            self._finish(req, result=np.concatenate(
                [req.prompt, np.asarray(req.generated, np.int32)]))

    def _finish(self, req: _DecodeRequest, result=None,
                exc: Optional[BaseException] = None,
                cause: Optional[str] = None):
        rec = self.recorder
        now = time.monotonic()
        tr = req.trace
        ring = self.trace_ring
        if tr is not None and ring is not None:
            # finish the trace BEFORE completing the future (the
            # ServingEngine contract): a client unblocked by .result()
            # that immediately scrapes /trace must see its request
            if exc is None:
                tr.meta["tokens"] = len(req.generated)
                ring.finish(tr)
            else:
                tr.terminal(cause or type(exc).__name__, now)
                ring.finish(tr)
        # future resolves BEFORE the stream's end marker: a consumer
        # whose tokens() iterator just ended may immediately call
        # result(0) and must not race the completion
        if exc is None:
            rec.inc("decode/finished")
            lat = (now - req.arrival) * 1e3
            rec.observe("decode/request_ms", lat)
            rec.observe("serving.latency_ms", lat)
            req.stream.future.set_result(result)
        else:
            req.stream.future.set_exception(exc)
        req.stream._q.put(_END)

    def _shed_deadline(self, req: _DecodeRequest, at: str):
        """Deadline shed: the terminal ``deadline`` span lands before
        the future fails — on the decode path exactly as at the queue
        pop (the ServingEngine shed-at-pop contract)."""
        self.recorder.inc("decode/shed_deadline")
        self._finish(req, exc=LoadShedError(
            "deadline", f"expired during {at}"), cause="deadline")

    def _fail_live(self, exc: BaseException):
        for slot in list(self._live):
            req = self._live.pop(slot)
            self.kv.free_slot(slot)
            self._finish(req, exc=exc)

    def _recover_pool(self, exc: BaseException):
        """After a prefill/decode program call fails: the pool args were
        DONATED, so on a donating backend ``self._pool`` may now point
        at deleted buffers — every later call would fail forever.  Live
        requests' KV is unrecoverable either way: fail them, release
        their pages, and rebuild a fresh zeroed pool so the engine (and
        its replica, via probe readmission) recovers from a transient
        step failure instead of black-holing 100% of traffic."""
        self._fail_live(exc)
        self._pool = self.kv.init_pool()

    def _emit_decode_event(self):
        rec = self.recorder
        counters = {k: rec.counter_value(k) for k in (
            "decode/requests", "decode/prefills", "decode/readmissions",
            "decode/steps", "decode/tokens", "decode/finished",
            "decode/shed_deadline", "decode/shed_queue_full",
            "decode/recompiles", "kv/page_allocs", "kv/page_frees",
            "kv/evictions")}
        with self._lock:
            depth = len(self._waiting)
        rec.emit_record(
            "decode_event", step=self._steps, live=len(self._live),
            slots=self.slots, occupancy=len(self._live) / self.slots,
            kv_fill=self.kv.fill(), queue_depth=depth,
            ttft=rec.hist_quantiles("decode/ttft_ms", (50.0, 99.0)),
            intertoken=rec.hist_quantiles("decode/intertoken_ms",
                                          (50.0, 99.0)),
            counters=counters)


def _select_tokens(logits, temps, step, base_key):
    """Greedy argmax (temperature 0 — deterministic, the golden-decode
    path) or softmax sampling at per-slot temperature off a
    step-folded key."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    key = jax.random.fold_in(base_key, step)
    sampled = jax.random.categorical(
        key, logits / jnp.maximum(temps, 1e-6)[:, None],
        axis=-1).astype(jnp.int32)
    return jnp.where(temps > 0.0, sampled, greedy)


def _decode_loop(engine_ref, cond, waiting, live, ring):
    """The decode thread.  Holds the engine weakly so a dropped,
    never-shut-down engine stays collectable; stranded requests then
    fail instead of hanging their clients forever."""
    while True:
        eng = engine_ref()
        if eng is None:
            exc = EngineClosedError(
                "decode engine was garbage-collected before this "
                "request ran")
            with cond:
                stranded = list(waiting) + list(live.values())
                waiting[:] = []
                live.clear()
            for req in stranded:
                if ring is not None and req.trace is not None:
                    req.trace.terminal("engine_closed", time.monotonic(),
                                       name="closed")
                    ring.finish(req.trace)
                if not req.stream.future.done():
                    req.stream.future.set_exception(exc)
                req.stream._q.put(_END)
            return
        try:
            alive = eng._tick()
        except Exception:
            alive = True           # _tick already contains per-request
            # failure handling; a bug here must not kill the loop
        finally:
            del eng                # never hold the engine across waits
        if not alive:
            return


def build_decode_replica_set(model, n: int, *, name: str = "lm",
                             probe_prompt=None,
                             engine_kw: Optional[Dict[str, Any]] = None,
                             **rs_kw):
    """N decode replicas behind one :class:`ReplicaSet`: one registry +
    DecodeEngine + Recorder per replica, all serving ``name``; the
    golden probe defaults to a short fixed prompt so ejected replicas
    can re-admit.  CanaryPublisher over the returned set golden-decode
    validates weight publications."""
    from .replicas import ReplicaSet
    engine_kw = dict(engine_kw or {})
    engine_kw.pop("recorder", None)
    engines = []
    for _ in range(int(n)):
        reg = ModelRegistry()
        reg.register(name, model)
        engines.append(DecodeEngine(reg, name,
                                    recorder=Recorder(annotate=False),
                                    **engine_kw))
    rs = ReplicaSet(engines, **rs_kw)
    probe = probe_prompt if probe_prompt is not None \
        else np.arange(1, 5, dtype=np.int32)
    rs.set_probe(name, probe)
    return rs


__all__ = ["DecodeEngine", "DecodeStream", "build_decode_replica_set"]
