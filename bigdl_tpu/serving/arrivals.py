"""Seeded open-loop arrival processes in VIRTUAL time.

Extracted from ``scripts/serve_bench.py`` so library consumers (the
autoscaler's trace replay, tests) can import the machinery without
executing the benchmark's argument parser.  The core contract is
unchanged: the offered sequence — arrival times and how many there are
— is exactly (seed, shape, rate, duration)-determined, because the
phase/diurnal multiplier and termination read *virtual* time only and
each yielded arrival consumes exactly ONE ``rng.exponential`` draw.
Wall clock only paces the replay, so two runs with the same seed offer
bit-identical traces regardless of host speed.

Shapes:

  * ``TRACES`` — the step-function phase shapes (``steady`` / ``burst``
    / ``overload``) as ``(start_fraction, rate_multiplier)`` tuples,
    applied via :func:`mult_at`;
  * :func:`diurnal_mult` — one smooth day-cycle over the run: a raised
    cosine from ``trough`` at the run's edges to ``peak`` mid-run, the
    slow rate swell an autoscaler must track (step bursts test
    *reaction*, the diurnal swell tests *anticipation*).

``serve_bench.py --arrivals diurnal`` composes it with any ``--trace``
phases (multipliers multiply).

Determinism / replay contract
-----------------------------
An arrival trace is a pure function of ``(seed, phases, rate,
duration, arrivals-shape)``.  :func:`trace_record` captures exactly
that tuple plus the realised arrival timestamps into a JSON-ready
dict; :func:`replay_arrivals` iterates the recorded timestamps
verbatim.  Because :func:`virtual_arrivals` consumes exactly one
``rng.exponential`` per arrival and reads only virtual time, a replay
from the artifact and a fresh generation from the recorded seed
produce the **same** offered sequence — the artifact exists so a run
can be reproduced without the generating code (autoscale smoke
fixtures, cross-version bisects), not because regeneration drifts.
Anything that perturbs the rng draw ORDER (an extra draw per arrival,
a reordered size draw) breaks regeneration — replay from the artifact
stays correct even then, which is why the smoke tests replay.
"""
from __future__ import annotations

import math
from typing import (Any, Callable, Dict, Iterable, Iterator, List,
                    Optional, Sequence, Tuple)

#: --trace shapes as (start_fraction_of_run, rate_multiplier) phases
TRACES = {
    "steady": ((0.0, 1.0),),
    "burst": ((0.0, 1.0), (0.4, 6.0), (0.6, 1.0)),
    "overload": ((0.0, 1.0), (0.3, 4.0)),
}

Phases = Sequence[Tuple[float, float]]


def mult_at(phases: Phases, frac: float) -> float:
    """The step-function rate multiplier at ``frac`` of the run."""
    m = phases[0][1]
    for start, mult in phases:
        if frac >= start:
            m = mult
    return m


def diurnal_mult(frac: float, peak: float = 3.0,
                 trough: float = 0.25) -> float:
    """Raised-cosine day cycle mapped onto the run: ``trough`` at
    ``frac`` 0 and 1, ``peak`` at 0.5 — pure arithmetic on the virtual
    fraction, so it is deterministic by construction."""
    return trough + (peak - trough) * 0.5 * (1.0 - math.cos(
        2.0 * math.pi * frac))


def virtual_arrivals(rng, rate: float, phases: Phases, duration: float,
                     rate_fn: Optional[Callable[[float], float]] = None
                     ) -> Iterator[float]:
    """Seeded Poisson arrival times in VIRTUAL time — the phase
    multiplier and termination read virtual time only, so the offered
    sequence (arrival times + however many there are) is exactly
    (seed, trace, rate, duration)-determined; wall clock only paces
    the replay.  Exactly ONE rng.exponential per yielded arrival, so
    callers interleave their own size/payload draws off the same rng
    without perturbing the arrival sequence — both the request
    open-loop and the decode bench share this generator so their
    replay disciplines can never diverge.  ``rate_fn`` (e.g.
    :func:`diurnal_mult`) multiplies on top of the phase shape,
    making the instantaneous rate ``rate * mult_at(...) *
    rate_fn(frac)``."""
    t_virtual = 0.0
    while True:
        frac = t_virtual / duration
        r = rate * mult_at(phases, frac)
        if rate_fn is not None:
            r *= rate_fn(frac)
        t_virtual += rng.exponential(1.0 / r)
        if t_virtual >= duration:
            return
        yield t_virtual


def rate_at(frac: float, rate: float, phases: Phases,
            rate_fn: Optional[Callable[[float], float]] = None
            ) -> float:
    """The instantaneous offered rate at ``frac`` of the run — the
    same ``rate * mult_at(...) * rate_fn(frac)`` product
    :func:`virtual_arrivals` samples from, exposed so trace artifacts
    and the autoscale timeline can annotate load without re-deriving
    the composition rule."""
    r = rate * mult_at(phases, frac)
    if rate_fn is not None:
        r *= rate_fn(frac)
    return r


def trace_record(seed: int, rate: float, phases: Phases,
                 duration: float, arrivals: Iterable[float], *,
                 shape: str = "steady", rate_ticks: int = 32,
                 rate_fn: Optional[Callable[[float], float]] = None
                 ) -> Dict[str, Any]:
    """The JSON-ready replay artifact for one offered trace: the
    generating tuple (seed / rate / phases / duration / shape), a
    ``rate_ticks``-point sample of the instantaneous rate curve (for
    plotting — NOT needed for replay), and the realised arrival
    timestamps in virtual seconds.  ``arrivals`` is materialised, so
    pass the same list the run consumed."""
    ts: List[float] = [float(t) for t in arrivals]
    ticks = [{"frac": i / max(rate_ticks - 1, 1),
              "rate": rate_at(i / max(rate_ticks - 1, 1), rate, phases,
                              rate_fn)}
             for i in range(rate_ticks)]
    return {
        "version": 1,
        "seed": int(seed),
        "rate": float(rate),
        "shape": str(shape),
        "phases": [[float(s), float(m)] for s, m in phases],
        "duration": float(duration),
        "n_arrivals": len(ts),
        "rate_curve": ticks,
        "arrivals_s": ts,
    }


def replay_arrivals(record: Dict[str, Any]) -> Iterator[float]:
    """Iterate a :func:`trace_record` artifact's arrival timestamps
    verbatim — the replay half of the determinism contract.  Raises
    ``ValueError`` on an artifact this version cannot replay."""
    if record.get("version") != 1:
        raise ValueError(
            f"unsupported arrival-trace version {record.get('version')!r}")
    for t in record["arrivals_s"]:
        yield float(t)
