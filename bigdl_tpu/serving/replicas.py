"""Serving resilience: replica set, canary publication, brownout.

One :class:`~bigdl_tpu.serving.ServingEngine` is one failure domain: a
wedged batcher or one bad weight publication takes the model offline,
and overload handling is a single fixed queue bound.  This module is
the fleet-of-replicas layer on top — the serving analog of what the
elastic supervisor + fleet scheduler do for training:

:class:`ReplicaSet`
    Fronts N engines (each with its own registry and Recorder) behind
    one ``submit``/``predict`` API.  A health loop scores every replica
    from its own telemetry — windowed error rate, queue depth, latency
    p99 — and **ejects outliers** from rotation; ejected replicas are
    **probed** with a golden request and re-admitted when they answer
    finitely again.  A replica whose oldest in-flight request exceeds
    the wedge budget is treated as hung (the serving analog of the
    stall watchdog's verdict): it is ejected and its in-flight requests
    **fail over** to healthy peers — under a token-bucket retry budget,
    so a mass failover can never amplify an overload into a retry
    storm.  Responses are delivered exactly once: a wedged replica's
    late result is dropped (``replica/stale_results``), never a second
    completion.

:class:`OverloadController`
    Deadline-aware admission with priority classes (interactive /
    normal / batch shed at increasing saturation), a predictive shed
    for requests whose deadline cannot be met at the current service
    rate, and a **brownout ladder**: sustained saturation degrades
    requests to the registry's int8 entry (cheaper compute, the
    ``degrade=`` mapping) before anything is shed.  Pure state machine
    — every method is called under the ReplicaSet lock with an
    injectable clock, so the ladder is unit-testable without load.

:class:`CanaryPublisher`
    Stages every ``swap_weights``/``sync_from_model`` rollout through
    ONE canary replica: the canary is quiesced (taken out of rotation,
    in-flight drained), the new snapshot is published to it alone, and
    a **golden batch** is re-run — outputs must be finite and within
    drift bounds of the pre-publication outputs.  Only then is the
    snapshot promoted fleet-wide; otherwise the canary rolls back to
    the old snapshot (bit-identical — the same arrays republished) and
    :class:`CanaryRejectedError` raises.  Client traffic serves the old
    snapshot throughout validation, so a NaN-poisoned publication is
    never visible to a single request.

Fault sites: ``serving.compute`` fires in every engine batch execution
(how a chaos test wedges or errors one replica), ``serving.publish``
fires in the canary staging step (transient blips retried through
``RetryPolicy(name="serving.publish")``; a failed validation is fatal
and rolls back).  See ``docs/serving.md`` for the lifecycle diagrams
and the overloaded-cluster runbook.
"""
from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import faults as faultplane
from ..observability import Recorder
from ..observability import tracing as trace_spine
from ..observability.context import TraceContext
from ..utils.retry import RetryPolicy
from .engine import ServingEngine
from .queue import EngineClosedError, LoadShedError
from .registry import ModelRegistry, Snapshot

#: priority classes, most to least latency-sensitive.  The admission
#: thresholds below are the saturation level at which each class sheds.
PRIORITY_CLASSES = ("interactive", "normal", "batch")

#: terminal ejection reasons — the probe loop never resurrects these.
#: "killed" is the chaos/operator hard-kill; "scaled_down" is the
#: autoscaler's graceful decommission (the engine drained first).
TERMINAL_REASONS = ("killed", "scaled_down")


class NoHealthyReplicaError(RuntimeError):
    """Every replica is ejected/killed — a total outage, distinct from
    backpressure (:class:`~bigdl_tpu.serving.LoadShedError`)."""


class CanaryRejectedError(RuntimeError):
    """A staged weight publication failed canary validation and was
    rolled back; the fleet never saw the rejected snapshot."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"canary rejected ({reason})"
                         f"{': ' if detail else ''}{detail}")
        self.reason = reason


class _Flight:
    """One client request tracked across failover attempts.  The client
    future completes exactly once; late results from abandoned
    dispatches are dropped via the Future's own set-once contract."""

    __slots__ = ("name", "serve_name", "x", "rows", "deadline",
                 "priority", "future", "attempts", "browned", "tried",
                 "ctx")

    def __init__(self, name: str, serve_name: str, x, rows: int,
                 deadline: Optional[float], priority: str,
                 browned: bool, ctx: Optional[TraceContext] = None):
        self.name = name
        self.serve_name = serve_name
        self.x = x
        self.rows = rows
        self.deadline = deadline      # absolute monotonic seconds or None
        self.priority = priority
        self.future: Future = Future()
        self.attempts = 0             # failover re-dispatches so far
        self.browned = browned
        self.tried: set = set()       # replica indices already tried —
        # a failover must not bounce back to the replica that failed it
        self.ctx = ctx                # root TraceContext for this
        # request — every dispatch (and failover re-dispatch) derives a
        # child, so ONE trace id names the request across hops

    def remaining_ms(self, now: Optional[float] = None) -> Optional[float]:
        if self.deadline is None:
            return None
        now = time.monotonic() if now is None else now
        return max((self.deadline - now) * 1e3, 0.0)


class _Replica:
    """One engine's slot in the set: rotation state + the health-window
    bookkeeping the scoring loop keeps between ticks.  All mutable
    fields are guarded by the owning ReplicaSet's lock."""

    __slots__ = ("index", "engine", "state", "reason", "ejected_at",
                 "inflight", "ok_total", "fail_total", "last_ok",
                 "last_fail", "last_rows", "last_progress_at",
                 "window_requests", "error_rate", "p99_ms",
                 "queue_rows", "probe", "last_probe_at")

    HEALTHY = "healthy"
    CANARY = "canary"           # quiesced for a canary validation
    EJECTED = "ejected"

    def __init__(self, index: int, engine: ServingEngine):
        self.index = index
        self.engine = engine
        self.state = self.HEALTHY
        self.reason: Optional[str] = None
        self.ejected_at: Optional[float] = None
        self.inflight: Dict[int, tuple] = {}    # token -> (flight, t0)
        # dispatch OUTCOMES observed by the set (per request, not per
        # engine batch — a failed batch of k coalesced requests is k
        # failures here, so the ejection rate is request-weighted)
        self.ok_total = 0
        self.fail_total = 0
        self.last_ok = 0
        self.last_fail = 0
        self.last_rows = 0.0
        self.last_progress_at = time.monotonic()
        self.window_requests = 0.0
        self.error_rate = 0.0
        self.p99_ms: Optional[float] = None
        self.queue_rows = 0
        self.probe: Optional[Future] = None
        self.last_probe_at = 0.0


class OverloadController:
    """Admission + brownout state machine over a saturation signal.

    ``saturation`` is pending rows across healthy replicas divided by
    their combined queue capacity (0 = idle, 1 = every queue full).
    Not thread-safe by itself: every method is called under the owning
    ReplicaSet's lock, and ``time_fn`` is injectable so the hold timers
    are unit-testable without wall-clock sleeps.

    The ladder, in order of escalation:

      1. **priority shed** — each class has a saturation threshold
         beyond which its new requests shed at admission
         (``LoadShedError("overload")``): batch first, interactive
         last.
      2. **predictive shed** — a request whose deadline cannot be met
         at the measured service rate sheds immediately
         (``LoadShedError("predicted")``) instead of wasting queue
         space to die at the pop.
      3. **brownout** — saturation above ``brownout_enter`` sustained
         for ``hold_s`` flips the set to serving the registry's int8
         degrade entries (cheaper compute, slightly lower fidelity);
         it exits after ``hold_s`` below ``brownout_exit``.  Brownout
         precedes shedding in spirit: it raises the service rate so the
         thresholds above stop triggering.
    """

    def __init__(self, *, shed_thresholds: Optional[Dict[str, float]] = None,
                 brownout_enter: float = 0.75, brownout_exit: float = 0.35,
                 hold_s: float = 1.0,
                 time_fn: Callable[[], float] = time.monotonic):
        self.shed_thresholds = dict(shed_thresholds or {
            "batch": 0.50, "normal": 0.85, "interactive": 1.01})
        for cls in PRIORITY_CLASSES:
            if cls not in self.shed_thresholds:
                raise ValueError(f"shed_thresholds missing {cls!r}")
        self.brownout_enter = float(brownout_enter)
        self.brownout_exit = float(brownout_exit)
        self.hold_s = float(hold_s)
        self._time = time_fn
        self.browned = False
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None

    def admits(self, priority: str, saturation: float) -> bool:
        """Whether a request of ``priority`` is admitted at
        ``saturation`` (threshold check only; the caller counts)."""
        return saturation < self.shed_thresholds[priority]

    def update(self, saturation: float) -> Optional[str]:
        """Advance the brownout timers; returns ``"enter"``/``"exit"``
        on a transition, else None."""
        now = self._time()
        if not self.browned:
            self._below_since = None
            if saturation >= self.brownout_enter:
                if self._above_since is None:
                    self._above_since = now
                elif now - self._above_since >= self.hold_s:
                    self.browned = True
                    self._above_since = None
                    return "enter"
            else:
                self._above_since = None
        else:
            self._above_since = None
            if saturation <= self.brownout_exit:
                if self._below_since is None:
                    self._below_since = now
                elif now - self._below_since >= self.hold_s:
                    self.browned = False
                    self._below_since = None
                    return "exit"
            else:
                self._below_since = None
        return None


class ReplicaSet:
    """N serving engines behind one submit API with health-gated
    routing, wedge failover, and overload control.

    ``engines``          the replicas; each wraps its OWN registry and
                         Recorder (per-replica health needs per-replica
                         telemetry).  Register the same model names in
                         all of them — :func:`build_replica_set` does.
    ``recorder``         the set's own Recorder (``replica/*`` and
                         ``serving/*`` counters, ``replica_event``
                         records); defaults to a fresh enabled one
    ``wedge_after``      oldest-in-flight age (s) past which a replica
                         is declared wedged, ejected, and failed over
    ``max_failovers``    re-dispatch budget per request
    ``failover_rate``    token-bucket refill (failovers/s) across the
                         whole set — the retry-storm cap
    ``failover_burst``   bucket capacity
    ``degrade``          ``{model: int8_model}`` brownout mapping
    ``controller``       an :class:`OverloadController` (default-built)
    ``health_interval``  scoring-loop period (s); the loop starts with
                         the first submit and stops on shutdown
    ``eject_error_rate`` windowed error-rate ejection threshold
    ``eject_min_requests``  window floor below which the rate is noise
    ``p99_outlier_factor``/``p99_floor_ms``  eject a replica whose p99
                         exceeds ``factor`` × the median p99 of the
                         OTHER healthy replicas AND the floor (needs
                         >= 2 healthy peers besides the suspect, i.e.
                         a 3-replica set at full strength)
    """

    def __init__(self, engines: Sequence[ServingEngine], *,
                 recorder: Optional[Recorder] = None,
                 wedge_after: float = 5.0,
                 max_failovers: int = 2,
                 failover_rate: float = 64.0, failover_burst: int = 32,
                 degrade: Optional[Dict[str, str]] = None,
                 controller: Optional[OverloadController] = None,
                 health_interval: float = 0.1,
                 probe_interval: float = 0.25,
                 probe_deadline_ms: float = 1000.0,
                 eject_error_rate: float = 0.5,
                 eject_min_requests: int = 4,
                 p99_outlier_factor: float = 8.0,
                 p99_floor_ms: float = 250.0,
                 tracer: Optional["trace_spine.Tracer"] = None):
        if not engines:
            raise ValueError("ReplicaSet needs at least one engine")
        self.replicas = [_Replica(i, e) for i, e in enumerate(engines)]
        self.recorder = recorder if recorder is not None \
            else Recorder(annotate=False)
        if self.recorder.enabled and self.recorder.get_ledger() is None:
            # control-plane ledger (one host "device"): failover
            # re-dispatch, golden-probe readmission, and brownout windows
            # land here; per-device serving time lives on each engine's
            # OWN recorder ledger, so the two never double-book
            from ..observability.goodput import GoodputLedger
            self.recorder.set_ledger(GoodputLedger(name="serve",
                                                   devices=1))
        self.tracer = tracer          # None -> process default at use
        self.wedge_after = float(wedge_after)
        self.max_failovers = int(max_failovers)
        self.failover_rate = float(failover_rate)
        self.failover_burst = float(failover_burst)
        self.degrade = dict(degrade or {})
        self.controller = controller or OverloadController()
        self.health_interval = float(health_interval)
        self.probe_interval = float(probe_interval)
        self.probe_deadline_ms = float(probe_deadline_ms)
        self.eject_error_rate = float(eject_error_rate)
        self.eject_min_requests = int(eject_min_requests)
        self.p99_outlier_factor = float(p99_outlier_factor)
        self.p99_floor_ms = float(p99_floor_ms)
        self._lock = threading.Lock()
        self._tokens = itertools.count()
        self._failover_tokens = self.failover_burst
        self._refilled_at = time.monotonic()
        self._service_rate: Optional[float] = None  # rows/s EWMA, set-wide
        self._probe_inputs: Dict[str, Any] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._http_server = None

    # -- lifecycle --------------------------------------------------------- #
    def warmup(self) -> "ReplicaSet":
        for rep in self.replicas:
            rep.engine.warmup()
        return self

    def start(self) -> "ReplicaSet":
        """Start the health/scoring loop (idempotent; submit() calls
        this lazily)."""
        with self._lock:
            if self._closed:
                raise EngineClosedError("replica set is shut down")
            if self._thread is None or not self._thread.is_alive():
                self._stop = threading.Event()
                self._thread = threading.Thread(
                    target=self._health_loop, args=(self._stop,),
                    daemon=True, name="replica-health")
                self._thread.start()
        return self

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = 5.0) -> "ReplicaSet":
        with self._lock:
            self._closed = True
            stop = self._stop
            t, self._thread = self._thread, None
            server, self._http_server = self._http_server, None
        stop.set()
        if t is not None:
            t.join(timeout)
        if server is not None:
            server.stop()
        for rep in self.replicas:
            rep.engine.shutdown(drain=drain, timeout=timeout)
        return self

    def telemetry_sources(self):
        """``[(name, recorder), ...]`` for the fleet
        :class:`~bigdl_tpu.observability.aggregate.MetricsAggregator`:
        the set's own recorder (``replica/*`` rotation gauges) plus one
        per replica — ``aggregator.add(replica_set, name="serve")``
        attaches the whole set in one call.  Terminally removed
        replicas (killed / scaled down) are excluded; callers that
        re-attach after a rescale should pair this with the
        aggregator's ``remove_member`` for the departed names."""
        with self._lock:
            live = [rep for rep in self.replicas
                    if not (rep.state == _Replica.EJECTED
                            and rep.reason in TERMINAL_REASONS)]
        return [("set", self.recorder)] + \
            [(f"replica{rep.index}", rep.engine.recorder)
             for rep in live]

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1"):
        """One aggregated introspection server for the whole set: the
        set's own recorder is the base source (``replica/*`` health
        gauges land in ``/healthz``), each replica's recorder is a
        ``job="replica<i>"``-labeled source on ``/metrics``, and the
        worst-of verdict is 503 on total outage (no healthy replica —
        the set registers itself as the health monitor)."""
        from ..observability.http import IntrospectionServer
        server = IntrospectionServer(self.recorder, port=port, host=host,
                                     monitor=self)
        for rep in self.replicas:
            server.add_job(f"replica{rep.index}", rep.engine.recorder)
        server.start()
        with self._lock:
            if self._closed:
                pass                    # fall through to stop below
            else:
                prev, self._http_server = self._http_server, server
                if prev is not None:
                    server = prev       # stop the displaced one
                else:
                    return self._http_server
        server.stop()
        with self._lock:
            if self._closed:
                raise EngineClosedError(
                    "replica set shut down while serve_metrics was "
                    "binding")
            return self._http_server

    @property
    def healthy(self) -> bool:
        """True while at least one replica is in rotation — the
        monitor verdict ``/healthz`` folds into the aggregate ``ok``."""
        with self._lock:
            return bool(self._routable_locked())

    # -- request path ------------------------------------------------------ #
    @property
    def _tracer(self) -> "trace_spine.Tracer":
        return self.tracer if self.tracer is not None \
            else trace_spine.get_tracer()

    def submit(self, name: str, x, deadline_ms: Optional[float] = None,
               priority: str = "normal",
               trace_ctx: Optional[TraceContext] = None) -> Future:
        """Admit one request and dispatch it to the healthiest replica.

        Sheds with :class:`LoadShedError` reason ``"overload"`` when
        ``priority``'s saturation threshold is crossed, ``"predicted"``
        when ``deadline_ms`` cannot be met at the measured service
        rate, or ``"queue_full"`` when every healthy replica's queue is
        full; raises :class:`NoHealthyReplicaError` on total outage.

        The front door is where a request's trace begins: a root
        :class:`TraceContext` is minted here (or adopted from
        ``trace_ctx``), every dispatch and failover hop derives a child
        of it, and the engine-side request timeline records under the
        SAME trace id.
        """
        if priority not in PRIORITY_CLASSES:
            raise ValueError(f"priority {priority!r} not in "
                             f"{PRIORITY_CLASSES}")
        self.start()
        rec = self.recorder
        rec.inc("serving/requests")
        ctx = trace_ctx if trace_ctx is not None \
            else TraceContext.new_root()
        admit = self._tracer.begin("rs.admit", ctx, child=False,
                                   subsystem="replicaset")
        now = time.monotonic()
        deadline = None if deadline_ms is None \
            else now + float(deadline_ms) / 1e3
        try:
            rows = self._rows_of(name, x)
            with self._lock:
                routable = self._routable_locked()
                if not routable:
                    raise NoHealthyReplicaError(
                        "no healthy replica in rotation "
                        f"({[(r.index, r.state, r.reason) for r in self.replicas]})")
                sat = self._saturation_locked(routable)
                rec.gauge("serving/saturation", sat)
                if not self.controller.admits(priority, sat):
                    rec.inc("serving/shed_overload")
                    raise LoadShedError(
                        "overload", f"saturation {sat:.2f} sheds priority "
                                    f"class {priority!r}")
                if deadline_ms is not None and self._service_rate:
                    # _service_rate is the FLEET rows/s; the request will
                    # be served by one replica at ~rate/N, against the
                    # least-loaded replica's backlog
                    per_rate = self._service_rate / len(routable)
                    pending = min(r.engine.pending_rows() for r in routable)
                    wait_ms = (pending + rows) / per_rate * 1e3
                    if wait_ms > float(deadline_ms):
                        rec.inc("serving/shed_predicted")
                        raise LoadShedError(
                            "predicted",
                            f"predicted wait {wait_ms:.0f}ms exceeds the "
                            f"{deadline_ms:.0f}ms deadline at "
                            f"{per_rate:.0f} rows/s/replica")
                browned = self.controller.browned and name in self.degrade
                serve_name = self.degrade[name] if browned else name
        except BaseException as e:
            admit.end(shed=repr(e))
            raise
        if browned:
            rec.inc("serving/brownout_requests")
        flight = _Flight(name, serve_name, x, rows, deadline, priority,
                         browned, ctx=ctx)
        admit.end(model=name, priority=priority, rows=rows)
        self._dispatch(flight)
        return flight.future

    def predict(self, name: str, x, timeout: Optional[float] = None,
                deadline_ms: Optional[float] = None,
                priority: str = "normal"):
        """Synchronous convenience; splits inputs larger than the
        bucket ladder across submits like ``ServingEngine.predict``."""
        import jax
        eng0 = self.replicas[0].engine
        max_batch = eng0.ladder.max_batch
        rows = self._rows_of(name, x)
        if rows <= max_batch or not getattr(eng0, "row_splittable", True):
            # engines whose "rows" are a SEQUENCE (the decode engine: a
            # prompt's tokens) must never be sliced into independent
            # requests — a concatenation of three unrelated decodes is
            # not a decode of the prompt.  Submit whole; the engine
            # rejects over-long prompts loudly.
            return self.submit(name, x, deadline_ms=deadline_ms,
                               priority=priority).result(timeout)
        x = np.asarray(x)
        futs = [self.submit(name, x[i:i + max_batch],
                            deadline_ms=deadline_ms, priority=priority)
                for i in range(0, rows, max_batch)]
        parts = [f.result(timeout) for f in futs]
        return jax.tree_util.tree_map(
            lambda *ps: np.concatenate(ps, axis=0), *parts)

    # -- introspection ----------------------------------------------------- #
    def health(self) -> Dict[int, Dict[str, Any]]:
        """Per-replica health snapshot (what the scoring loop saw at
        its last tick)."""
        with self._lock:
            return {r.index: {
                "state": r.state, "reason": r.reason,
                "error_rate": r.error_rate, "p99_ms": r.p99_ms,
                "queue_rows": r.queue_rows,
                "inflight": len(r.inflight)} for r in self.replicas}

    def stats(self) -> Dict[str, Any]:
        """Set-level counters plus each replica's engine stats."""
        rec = self.recorder
        out: Dict[str, Any] = {
            k.rsplit("/", 1)[1]: rec.counter_value(k)
            for k in ("serving/requests", "serving/shed_overload",
                      "serving/shed_predicted",
                      "serving/brownout_requests",
                      "replica/dispatches", "replica/failovers",
                      "replica/failover_exhausted", "replica/ejected",
                      "replica/readmitted", "replica/wedged",
                      "replica/stale_results", "replica/scaled_up",
                      "replica/scaled_down")}
        out["brownout"] = bool(self.controller.browned)
        out["replicas"] = {r.index: r.engine.stats()
                           for r in self.replicas}
        return out

    def set_probe(self, name: str, x) -> "ReplicaSet":
        """Install the golden probe input for ``name`` (defaults to a
        zeros batch derived from the registered ``input_shape``)."""
        with self._lock:
            self._probe_inputs[name] = np.asarray(x)
        return self

    # -- chaos / operator actions ------------------------------------------ #
    def kill(self, index: int) -> "ReplicaSet":
        """Hard-kill one replica (chaos seam / operator drain): its
        engine shuts down without draining, it leaves rotation for
        good (never probed back), and its in-flight requests fail over
        through the normal budgeted path."""
        rep = self.replicas[index]
        with self._lock:
            already = rep.state == _Replica.EJECTED \
                and rep.reason == "killed"
            if not already:
                if rep.state == _Replica.EJECTED:
                    # already out (wedged/errors): escalate the reason
                    # so the probe loop stops resurrecting a dead engine
                    rep.reason = "killed"
                    rep.probe = None
                else:
                    self._eject_locked(rep, "killed")
                self.recorder.inc("replica/killed")
        if not already:
            rep.engine.shutdown(drain=False, timeout=1.0)
        return self

    # -- scaling seams ------------------------------------------------------ #
    def add_replica(self, engine: ServingEngine, *,
                    warm: bool = False) -> int:
        """Admit a new engine into the set (the autoscaler's scale-up
        seam).  The replica joins EJECTED with reason ``"joining"`` and
        enters rotation only after the health loop's golden probe
        passes — the same readmission path an ejected replica takes, so
        a half-warmed engine never takes live traffic.  Returns the new
        replica's index."""
        if warm:
            engine.warmup()     # compile outside the set lock
        with self._lock:
            if self._closed:
                raise EngineClosedError("replica set is shut down")
            index = len(self.replicas)
            rep = _Replica(index, engine)
            rep.state = _Replica.EJECTED
            rep.reason = "joining"
            rep.ejected_at = time.monotonic()
            self.replicas.append(rep)
        self.recorder.inc("replica/scaled_up")
        self.recorder.emit_record("replica_event", kind="join",
                                  replica=index)
        print(f"[serving] replica {index} joining (probe-gated)",
              flush=True)
        return index

    def decommission(self, index: int, *, drain: bool = True,
                     timeout: Optional[float] = 5.0) -> "ReplicaSet":
        """Gracefully remove one replica (the autoscaler's scale-down
        seam): it leaves rotation for good — reason ``"scaled_down"``
        is terminal, never probed back — and its engine drains before
        shutdown so accepted work completes.  In-flight requests the
        set already dispatched fail over through the normal budgeted
        path.  Refuses to remove the last routable replica."""
        rep = self.replicas[index]
        with self._lock:
            if rep.state == _Replica.EJECTED \
                    and rep.reason in TERMINAL_REASONS:
                return self                 # idempotent
            if rep.state == _Replica.HEALTHY \
                    and len(self._routable_locked()) <= 1:
                raise ValueError(
                    f"refusing to decommission replica {index}: it is "
                    "the last replica in rotation")
            if rep.state == _Replica.EJECTED:
                # already out (probing back in): escalate to terminal
                rep.reason = "scaled_down"
                rep.probe = None
                self.recorder.emit_record(
                    "replica_event", kind="eject", replica=index,
                    reason="scaled_down")
            else:
                self._eject_locked(rep, "scaled_down")
            self.recorder.inc("replica/scaled_down")
        rep.engine.shutdown(drain=drain, timeout=timeout)
        return self

    # -- internals: routing ------------------------------------------------ #
    def _rows_of(self, name: str, x) -> int:
        """Row count for queue math, via any live registry's entry."""
        shape = np.shape(x)
        for rep in self.replicas:
            try:
                entry = rep.engine.registry.get(name)
            except KeyError:
                continue
            if entry.input_shape is not None \
                    and shape == tuple(entry.input_shape):
                return 1
            break
        return int(shape[0]) if shape else 1

    def _routable_locked(self) -> List[_Replica]:
        return [r for r in self.replicas if r.state == _Replica.HEALTHY]

    def _saturation_locked(self, routable: List[_Replica]) -> float:
        """Mean over routable replicas of each engine's most-saturated
        queue fill — 1.0 means every replica's hottest admission point
        is full."""
        if not routable:
            return 1.0
        return sum(r.engine.max_queue_fill()
                   for r in routable) / len(routable)

    def _dispatch(self, flight: _Flight):
        """Send ``flight`` to the least-loaded healthy replica; on a
        full queue try the next one, on a closed engine eject it and
        keep going.  Raises the last shed error when every healthy
        replica refused."""
        last_shed: Optional[LoadShedError] = None
        retried_all = False
        while True:
            with self._lock:
                healthy = self._routable_locked()
                candidates = [r for r in healthy
                              if r.index not in flight.tried]
                if not candidates and healthy and not retried_all \
                        and last_shed is None:
                    # every healthy replica already failed this flight
                    # once; allow ONE more pass (a single-replica set
                    # must still be able to retry a transient)
                    retried_all = True
                    flight.tried.clear()
                    candidates = healthy
                candidates.sort(key=lambda r: r.engine.pending_rows())
            if not candidates:
                if last_shed is not None:
                    raise last_shed
                raise NoHealthyReplicaError(
                    "no healthy replica accepted the request")
            rep = candidates[0]
            flight.tried.add(rep.index)
            try:
                inner = rep.engine.submit(
                    flight.serve_name, flight.x,
                    deadline_ms=flight.remaining_ms(),
                    trace_ctx=flight.ctx.child()
                    if flight.ctx is not None else None)
            except LoadShedError as e:
                last_shed = e
                continue
            except EngineClosedError:
                with self._lock:
                    self._eject_locked(rep, "closed")
                continue
            token = next(self._tokens)
            with self._lock:
                rep.inflight[token] = (flight, time.monotonic())
            self.recorder.inc("replica/dispatches")
            inner.add_done_callback(
                lambda f, rep=rep, token=token, flight=flight:
                self._on_inner_done(rep, token, flight, f))
            return

    def _on_inner_done(self, rep: _Replica, token: int, flight: _Flight,
                       inner: Future):
        exc = inner.exception()
        with self._lock:
            rep.inflight.pop(token, None)
            if exc is None:
                rep.ok_total += 1
            elif not isinstance(exc, LoadShedError):
                # deadline sheds are the request's SLO failing, not
                # evidence against the replica; real errors are
                rep.fail_total += 1
        if exc is None:
            if not self._complete(flight, result=inner.result()):
                self.recorder.inc("replica/stale_results")
            return
        if isinstance(exc, LoadShedError) and exc.reason == "deadline":
            # the SLO already failed; a retry would only waste compute
            self._complete(flight, exc=exc)
            return
        if flight.future.done():
            self.recorder.inc("replica/stale_results")
            return
        self._failover(flight, exc)

    def _failover(self, flight: _Flight, cause: BaseException):
        """Re-dispatch a failed/abandoned flight under the budget; the
        cause propagates to the client when the budget says no."""
        rec = self.recorder
        eligible = flight.attempts < self.max_failovers \
            and not flight.future.done() \
            and (flight.deadline is None
                 or time.monotonic() < flight.deadline)
        if eligible and not self._take_failover_token():
            rec.inc("replica/failover_exhausted")
            eligible = False
        if not eligible:
            self._complete(flight, exc=cause)
            return
        flight.attempts += 1
        rec.inc("replica/failovers")
        if flight.ctx is not None:
            # zero-length hop marker in the request's own trace: the
            # merged timeline shows WHERE the retry happened between
            # the failed replica's terminal span and the re-dispatch
            self._tracer.event("rs.failover", flight.ctx,
                               subsystem="replicaset",
                               attempt=flight.attempts,
                               cause=repr(cause))
        from ..observability.goodput import ledger_phase
        try:
            with ledger_phase(rec, "failover"):
                self._dispatch(flight)
        except Exception as e:
            self._complete(flight, exc=e)

    def _take_failover_token(self) -> bool:
        with self._lock:
            now = time.monotonic()
            self._failover_tokens = min(
                self.failover_burst,
                self._failover_tokens
                + (now - self._refilled_at) * self.failover_rate)
            self._refilled_at = now
            if self._failover_tokens >= 1.0:
                self._failover_tokens -= 1.0
                return True
            return False

    @staticmethod
    def _complete(flight: _Flight, result=None,
                  exc: Optional[BaseException] = None) -> bool:
        """Deliver exactly once; False when the flight already
        completed (a late result from an abandoned dispatch)."""
        try:
            if exc is not None:
                flight.future.set_exception(exc)
            else:
                flight.future.set_result(result)
            return True
        except InvalidStateError:
            return False

    # -- internals: health loop -------------------------------------------- #
    def _health_loop(self, stop: threading.Event):
        while not stop.wait(self.health_interval):
            try:
                self.check_health()
            except Exception as e:  # the scorer must never die silently
                print(f"[serving] replica health check failed: {e!r}",
                      flush=True)

    def check_health(self):
        """One scoring tick.  Public so tests (and operators in a
        debugger) can drive the verdict synchronously."""
        now = time.monotonic()
        to_failover: List[_Flight] = []
        probes: List[_Replica] = []
        with self._lock:
            rate = 0.0
            busy = False
            for rep in self.replicas:
                erec = rep.engine.recorder
                rows = erec.counter_value("serving.rows")
                d_ok = rep.ok_total - rep.last_ok
                d_fail = rep.fail_total - rep.last_fail
                d_rows = max(rows - rep.last_rows, 0.0)
                rate += d_rows
                rep.last_ok, rep.last_fail = rep.ok_total, rep.fail_total
                rep.last_rows = rows
                if d_rows > 0 or not rep.inflight:
                    # serving rows (or idle) is progress: only a
                    # replica that is BOTH old-in-flight and serving
                    # nothing reads as wedged — a deep backlog alone
                    # must not
                    rep.last_progress_at = now
                rep.window_requests = d_ok + d_fail
                if rep.window_requests > 0:
                    rep.error_rate = d_fail / rep.window_requests
                q = erec.hist_quantiles("serving.latency_ms")
                rep.p99_ms = q.get("p99") if q else None
                rep.queue_rows = rep.engine.pending_rows()
                busy = busy or rep.window_requests > 0 \
                    or rep.queue_rows > 0 or bool(rep.inflight)
            # only fold windows with actual traffic into the rate EWMA:
            # an idle gap is not evidence of slow service, and decaying
            # toward zero would make the predictive shed reject every
            # deadline-bearing request after the gap
            if busy:
                self._update_rate_locked(rate)
            healthy = self._routable_locked()
            peers_p99 = [(r.index, r.p99_ms) for r in healthy
                         if r.p99_ms is not None]
            remaining = len(healthy)
            for rep in healthy:
                verdict = self._eject_verdict_locked(rep, now, peers_p99,
                                                     len(healthy))
                if verdict is None:
                    continue
                if remaining <= 1:
                    # NEVER health-eject the last replica in rotation:
                    # a degraded sole survivor (requests shed by
                    # deadline) beats a self-inflicted total outage on
                    # a noisy verdict.  kill() still removes it.
                    self.recorder.inc("replica/eject_deferred")
                    continue
                remaining -= 1
                self._eject_locked(rep, verdict)
                if verdict == "wedged":
                    self.recorder.inc("replica/wedged")
                    # abandon the wedge's in-flight work: pop it here,
                    # fail it over outside the lock
                    for token in list(rep.inflight):
                        flight, _ = rep.inflight.pop(token)
                        if not flight.future.done():
                            to_failover.append(flight)
            for rep in self.replicas:
                if rep.state == _Replica.EJECTED \
                        and rep.reason not in TERMINAL_REASONS:
                    probes.append(rep)
            routable = self._routable_locked()
            sat = self._saturation_locked(routable) if routable else 1.0
            self.recorder.gauge("serving/saturation", sat)
            transition = self.controller.update(sat)
            self._publish_gauges_locked()
        rec = self.recorder
        if transition == "enter":
            rec.inc("serving/brownout_enter")
            rec.gauge("serving/brownout", 1)
            rec.emit_record("replica_event", kind="brownout_enter",
                            saturation=sat)
            led = rec.get_ledger()
            if led is not None:
                # browned wall time is badput on the set's control-plane
                # ledger until the exit flips the background back
                led.declare("brownout")
        elif transition == "exit":
            rec.inc("serving/brownout_exit")
            rec.gauge("serving/brownout", 0)
            rec.emit_record("replica_event", kind="brownout_exit",
                            saturation=sat)
            led = rec.get_ledger()
            if led is not None:
                led.declare("idle")
        for flight in to_failover:
            self._failover(flight, LoadShedError(
                "wedged", "replica ejected as wedged mid-request"))
        for rep in probes:
            self._probe(rep, now)

    def _update_rate_locked(self, window_rows: float):
        rate = window_rows / max(self.health_interval, 1e-3)
        if self._service_rate is None:
            self._service_rate = rate if rate > 0 else None
        else:
            self._service_rate = 0.8 * self._service_rate + 0.2 * rate

    def _eject_verdict_locked(self, rep: _Replica, now: float,
                              peers_p99: List[float],
                              n_healthy: int) -> Optional[str]:
        oldest = min((t0 for _, t0 in rep.inflight.values()),
                     default=None)
        if oldest is not None and now - oldest > self.wedge_after \
                and now - rep.last_progress_at > self.wedge_after:
            return "wedged"
        if rep.window_requests >= self.eject_min_requests \
                and rep.error_rate >= self.eject_error_rate:
            return "errors"
        peers = sorted(p for i, p in peers_p99 if i != rep.index)
        if (rep.p99_ms is not None and n_healthy >= 3
                and len(peers) >= 2
                and rep.p99_ms > self.p99_floor_ms
                and rep.p99_ms > self.p99_outlier_factor
                * peers[len(peers) // 2]):
            return "p99_outlier"
        return None

    def _eject_locked(self, rep: _Replica, reason: str):
        if rep.state == _Replica.EJECTED:
            return
        rep.state = _Replica.EJECTED
        rep.reason = reason
        rep.ejected_at = time.monotonic()
        rep.probe = None
        self.recorder.inc("replica/ejected")
        self.recorder.emit_record("replica_event", kind="eject",
                                  replica=rep.index, reason=reason)
        print(f"[serving] replica {rep.index} ejected ({reason})",
              flush=True)

    def _publish_gauges_locked(self):
        rec = self.recorder
        rec.gauge("replica/healthy_count",
                  len(self._routable_locked()))
        for rep in self.replicas:
            rec.gauge(f"replica/healthy.{rep.index}",
                      1 if rep.state == _Replica.HEALTHY else 0)
            rec.gauge(f"replica/queue_rows.{rep.index}", rep.queue_rows)
            rec.gauge(f"replica/error_rate.{rep.index}", rep.error_rate)
            if rep.p99_ms is not None:
                rec.gauge(f"replica/p99_ms.{rep.index}", rep.p99_ms)

    # -- internals: probe-based re-admission ------------------------------- #
    def _probe_input_for(self, rep: _Replica):
        """(name, x) golden probe for ``rep``, from ``set_probe`` or a
        zeros batch off any registered entry's input_shape."""
        with self._lock:
            if self._probe_inputs:
                name = next(iter(self._probe_inputs))
                return name, self._probe_inputs[name]
        for entry in rep.engine.registry.entries():
            if entry.input_shape is not None:
                return entry.name, np.zeros((1,) + tuple(entry.input_shape),
                                            entry.dtype)
        return None, None

    def _probe(self, rep: _Replica, now: float):
        with self._lock:
            if rep.state != _Replica.EJECTED \
                    or rep.reason in TERMINAL_REASONS:
                return
            probe = rep.probe
            if probe is None:
                if now - rep.last_probe_at < self.probe_interval \
                        or rep.inflight:
                    return              # wedge not yet released
                launch = True
            else:
                launch = False
        if launch:
            from ..observability.goodput import ledger_phase
            name, x = self._probe_input_for(rep)
            if name is None:
                return
            self.recorder.inc("replica/probes")
            try:
                with ledger_phase(self.recorder, "probe_readmission"):
                    fut = rep.engine.submit(
                        name, x, deadline_ms=self.probe_deadline_ms)
            except (LoadShedError, EngineClosedError):
                self.recorder.inc("replica/probe_failures")
                with self._lock:
                    rep.last_probe_at = now
                return
            with self._lock:
                rep.probe = fut
                rep.last_probe_at = now
            return
        if not probe.done():
            return
        ok = probe.exception() is None
        if ok:
            try:
                import jax
                ok = all(bool(np.isfinite(np.asarray(leaf)).all())
                         for leaf in
                         jax.tree_util.tree_leaves(probe.result()))
            except Exception:
                ok = False
        with self._lock:
            rep.probe = None
            if rep.state != _Replica.EJECTED \
                    or rep.reason in TERMINAL_REASONS:
                return      # kill()/decommission raced: stay out
            was = rep.reason
            if not ok:
                rep.last_probe_at = now
            else:
                rep.state = _Replica.HEALTHY
                rep.reason = None
                rep.ejected_at = None
                rep.last_progress_at = time.monotonic()
        if ok:
            self.recorder.inc("replica/readmitted")
            self.recorder.emit_record("replica_event", kind="readmit",
                                      replica=rep.index, was=was)
            print(f"[serving] replica {rep.index} re-admitted after a "
                  "healthy probe", flush=True)
        else:
            self.recorder.inc("replica/probe_failures")

    # -- internals: canary staging seam ------------------------------------ #
    def _stage_canary(self, index: int, timeout: float) -> bool:
        """Take replica ``index`` out of rotation for a canary
        validation and wait for its in-flight work to drain.  Returns
        False — with the replica back in rotation — when it is not
        currently routable or fails to drain within ``timeout`` (the
        publisher then picks another): a staged-but-undrained canary
        would serve queued client requests against the UNVALIDATED
        snapshot, the exact exposure the canary exists to prevent."""
        rep = self.replicas[index]
        with self._lock:
            if rep.state != _Replica.HEALTHY:
                return False
            rep.state = _Replica.CANARY
            rep.reason = "canary"
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not rep.inflight and rep.engine.pending_rows() == 0:
                    return True
            # the set's stop event doubles as the interruptible sleep:
            # a shutdown mid-drain ends the wait immediately
            if self._stop.wait(0.01):
                break
        self._unstage_canary(index)     # undrained: NOT a safe canary
        return False

    def _unstage_canary(self, index: int):
        rep = self.replicas[index]
        with self._lock:
            if rep.state == _Replica.CANARY:
                rep.state = _Replica.HEALTHY
                rep.reason = None


class CanaryPublisher:
    """Stages weight rollouts through one quiesced canary replica with
    golden-batch validation, fleet-wide promotion, and automatic
    rollback.  See the module docstring for the protocol; the
    ``serving.publish`` fault site fires inside the (retried) staging
    step, and a rejected publication leaves every replica serving a
    snapshot whose golden outputs are bit-identical to before the
    publish call."""

    def __init__(self, replica_set: ReplicaSet,
                 golden: Dict[str, Any], *,
                 canary: int = 0, drift_rtol: float = 0.5,
                 drift_atol: float = 1e-3,
                 quiesce_timeout: float = 5.0,
                 validate_timeout: float = 30.0,
                 recorder: Optional[Recorder] = None):
        self.rs = replica_set
        self.golden = {k: np.asarray(v) for k, v in golden.items()}
        self.canary = int(canary)
        self.drift_rtol = float(drift_rtol)
        self.drift_atol = float(drift_atol)
        self.quiesce_timeout = float(quiesce_timeout)
        self.validate_timeout = float(validate_timeout)
        self.recorder = recorder if recorder is not None \
            else replica_set.recorder
        self._publish_lock = threading.Lock()
        self._retry = RetryPolicy(max_attempts=3, base=0.01,
                                  max_delay=0.2, name="serving.publish",
                                  recorder_fn=lambda: self.recorder)

    def publish(self, name: str, params=None, state=None,
                version: Optional[str] = None) -> Snapshot:
        """Validate ``params``/``state`` on the canary, then promote
        fleet-wide; raises :class:`CanaryRejectedError` (after rolling
        the canary back) when the golden outputs are non-finite or
        drift past bounds."""
        if name not in self.golden:
            raise ValueError(f"no golden batch registered for {name!r}; "
                             "CanaryPublisher(golden={...}) needs one "
                             "per published model")
        rec = self.recorder
        with self._publish_lock:
            rec.inc("serving/canary_publishes")
            tried: set = set()
            for _ in range(len(self.rs.replicas)):
                idx = self._pick_canary(exclude=tried)
                tried.add(idx)
                rep = self.rs.replicas[idx]
                if not self.rs._stage_canary(idx, self.quiesce_timeout):
                    continue        # raced out of rotation; pick again
                try:
                    return self._publish_on(rep, name, params, state,
                                            version)
                finally:
                    self.rs._unstage_canary(idx)
            raise NoHealthyReplicaError(
                "could not stage any replica as the canary")

    def publish_from_model(self, name: str, model=None,
                           version: Optional[str] = None) -> Snapshot:
        """The ``sync_from_model`` bridge: republish from a module's
        own ``_params``/``_state`` (default: the canary entry's module,
        for in-place ``set_weights``-style updates) through the full
        canary gate."""
        if model is None:
            model = self.rs.replicas[self._pick_canary()] \
                .engine.registry.get(name).model
        return self.publish(name, model._params,
                            dict(model._state or {}), version=version)

    # -- internals --------------------------------------------------------- #
    def _pick_canary(self, exclude=()) -> int:
        with self.rs._lock:
            rep = self.rs.replicas[self.canary]
            if rep.state == _Replica.HEALTHY \
                    and self.canary not in exclude:
                return self.canary
            for r in self.rs.replicas:
                if r.state == _Replica.HEALTHY \
                        and r.index not in exclude:
                    return r.index
        raise NoHealthyReplicaError(
            "no healthy replica available to act as canary")

    def _publish_on(self, rep: _Replica, name: str, params, state,
                    version: Optional[str]) -> Snapshot:
        rec = self.recorder
        registry = rep.engine.registry
        entry = registry.get(name)
        old = entry.snapshot
        x = self.golden[name]
        ref = np.asarray(rep.engine.predict(
            name, x, timeout=self.validate_timeout))

        def stage():
            faultplane.inject("serving.publish", rec)
            return registry.swap_weights(name, params, state,
                                         version=version)
        snap = self._retry.run(stage)   # transient blips retried; a
        # ValueError (aval drift) is fatal and nothing was published
        rec.emit_record("replica_event", kind="canary_stage",
                        replica=rep.index, model=name,
                        version=snap.version)
        reason = detail = None
        try:
            got = np.asarray(rep.engine.predict(
                name, x, timeout=self.validate_timeout))
            if not np.isfinite(got).all():
                reason, detail = "non_finite", \
                    f"{int((~np.isfinite(got)).sum())} non-finite " \
                    "golden outputs"
            elif np.issubdtype(got.dtype, np.integer):
                # integer golden outputs are TOKEN IDS (a decode
                # canary): magnitude drift over ids is meaningless and
                # a legitimate weight update may change every token —
                # the poison gate is the golden decode itself, which
                # FAILS (engine non-finite-logits sentinel -> "error"
                # reason) on a poisoned snapshot.  A changed output
                # shape still rejects.
                if got.shape != ref.shape:
                    reason, detail = "drift", \
                        f"golden decode shape {got.shape} != {ref.shape}"
            else:
                drift = float(np.max(np.abs(got - ref)))
                bound = self.drift_atol + self.drift_rtol \
                    * float(np.max(np.abs(ref)))
                if drift > bound:
                    reason, detail = "drift", \
                        f"golden drift {drift:.4g} > bound {bound:.4g}"
        except Exception as e:
            reason, detail = "error", f"{type(e).__name__}: {e}"
        if reason is not None:
            self._rollback(registry, name, old)
            rec.inc("serving/canary_rejected")
            rec.inc("serving/canary_rollbacks")
            rec.emit_record("replica_event", kind="canary_reject",
                            replica=rep.index, model=name,
                            reason=reason, version=snap.version)
            print(f"[serving] canary REJECTED {name} {snap.version} "
                  f"({reason}: {detail}); old snapshot "
                  f"{old.version} restored", flush=True)
            raise CanaryRejectedError(reason, detail)
        promoted: List[_Replica] = []
        try:
            for other in self.rs.replicas:
                if other is rep:
                    continue
                other.engine.registry.swap_weights(
                    name, params, state, version=snap.version)
                promoted.append(other)
        except Exception:
            for other in promoted:
                self._rollback(other.engine.registry, name, old)
            self._rollback(registry, name, old)
            rec.inc("serving/canary_rollbacks")
            rec.emit_record("replica_event", kind="canary_reject",
                            replica=rep.index, model=name,
                            reason="promotion_failed",
                            version=snap.version)
            raise
        rec.inc("serving/canary_promoted")
        rec.emit_record("replica_event", kind="canary_promote",
                        model=name, version=snap.version,
                        replicas=len(promoted) + 1)
        degrade_name = self.rs.degrade.get(name)
        if degrade_name is not None:
            self._refresh_degrade(name, degrade_name, snap)
        return snap

    def _refresh_degrade(self, name: str, degrade_name: str,
                         snap: Snapshot):
        """Re-quantize every replica's int8 degrade entry from the
        just-promoted weights (same calibration batches), so a brownout
        after a publish serves the NEW model, not a stale one.
        Best-effort per replica: the primary entries are already
        consistent fleet-wide, so a failed refresh is counted + logged
        rather than unwinding the promotion."""
        from ..quantized import quantize_for_serving
        rec = self.recorder
        for rep in self.rs.replicas:
            registry = rep.engine.registry
            try:
                entry8 = registry.get(degrade_name)
            except KeyError:
                continue
            try:
                q = quantize_for_serving(
                    registry.get(name).model,
                    calibration_data=entry8.calibration_data)
                registry.swap_model(degrade_name, q,
                                    version=snap.version)
                rep.engine.warmup(degrade_name)
                rec.inc("serving/degrade_refreshed")
            except Exception as e:
                rec.inc("serving/degrade_refresh_failures")
                print(f"[serving] degrade entry {degrade_name!r} on "
                      f"replica {rep.index} could not be refreshed to "
                      f"{snap.version}: {e!r} — browned-out requests "
                      "there serve the previous weights", flush=True)

    @staticmethod
    def _rollback(registry: ModelRegistry, name: str, old: Snapshot):
        """Republish the OLD snapshot's arrays — outputs after rollback
        are bit-identical to before the publication."""
        registry.swap_weights(name, old.params, old.state,
                              version=old.version)


def build_replica_set(model, n: int, *, name: str = "main",
                      input_shape, dtype=np.float32,
                      int8_degrade: bool = False,
                      calibration_data=None,
                      engine_kw: Optional[Dict[str, Any]] = None,
                      **rs_kw) -> ReplicaSet:
    """Build an N-replica set over ``model``: one registry + engine +
    recorder per replica, all serving ``name``; with
    ``int8_degrade=True`` each registry also gets the quantized
    ``<name>.int8`` entry and the set's brownout ``degrade`` map routes
    to it under sustained saturation."""
    engine_kw = dict(engine_kw or {})
    # per-replica health scoring needs per-replica telemetry: each
    # engine always gets its own Recorder, never a shared one
    engine_kw.pop("recorder", None)
    engines = []
    for _ in range(int(n)):
        reg = ModelRegistry()
        reg.register(name, model, input_shape=input_shape, dtype=dtype)
        if int8_degrade:
            reg.register(f"{name}.int8", model, input_shape=input_shape,
                         dtype=dtype, quantize_int8=True,
                         calibration_data=calibration_data)
        engines.append(ServingEngine(
            reg, recorder=Recorder(annotate=False), **engine_kw))
    if int8_degrade:
        rs_kw.setdefault("degrade", {name: f"{name}.int8"})
    return ReplicaSet(engines, **rs_kw)


__all__ = ["ReplicaSet", "CanaryPublisher", "OverloadController",
           "CanaryRejectedError", "NoHealthyReplicaError",
           "PRIORITY_CLASSES", "build_replica_set"]
