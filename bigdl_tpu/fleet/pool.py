"""Multi-job scheduling on one shared device pool.

Every robustness layer so far protects ONE job that owns the whole
mesh.  Production clusters run *many* jobs on shared capacity — BigDL
2.0's "seamless scaling of AI pipelines" (arXiv:2204.01715) and the
TF system paper's cluster-level design (arXiv:1605.08695) — where the
dominant failure mode is contention, not hardware loss: a job loses
devices to a higher-priority arrival, gets moved, gets them back.
This module is the pool-level control plane over the existing seams:

  :class:`DevicePool`       per-device ownership ledger — which job
                            holds which device, what is free
  :func:`plan_fleet`        fair-share gang planner: disjoint
                            :func:`~bigdl_tpu.elastic.plan.plan_mesh`
                            plans for N jobs, priority tiers, every
                            job's ``min_axes`` floor reserved up front
  :class:`FleetScheduler`   admits jobs, places them, and keeps every
                            one alive through contention

The delivery mechanism is deliberately boring: each job is a normal
:class:`~bigdl_tpu.elastic.ElasticSupervisor` whose ``capacity_fn``
reads its pool assignment.  A re-plan just updates the assignment; the
supervisor notices at its next capacity poll and takes the PR-6
drain → commit → replan → resume path it already knows — a shrink when
it lost devices, a displacement when it was moved, a regrow when
capacity returned.  **A job whose ``min_axes`` floor fits surviving
capacity is never killed by a fleet decision**: admission reserves
every job's floor, so planning can always shrink instead of evict
(an arrival whose own floor does not fit is *rejected*, the running
jobs are untouched).

Bit-exactness taxonomy (same rules as ``docs/checkpointing.md``): a
displacement or same-mesh resume is bit-identical; a shrink/regrow
changes how many partitions reductions run over and drifts at the last
ulp per step — the fleet chaos leg asserts the former, the contention
tests bound the latter.

SIGTERM fans out: the scheduler (main thread) owns the process-level
hook via :class:`~bigdl_tpu.checkpoint.PreemptionHandler`'s shared
dispatcher, so every job supervisor — running on a worker thread that
could never install its own OS handler — still drains and commits on
one real signal, and the scheduler then stops the pool cleanly.

Re-placed jobs warm-start through a **shared persistent compile
cache** (:func:`enable_shared_compile_cache`): a displaced/shrunken
job's rebuild hits the XLA programs its previous placement (or any
other job on the same topology) already compiled, instead of paying a
full compile per displacement.

Faults: ``fleet.place`` fires on every placement computation and
``fleet.preempt`` on every preemption delivery (both retried through
:class:`~bigdl_tpu.utils.retry.RetryPolicy`, name ``fleet``), so chaos
tests can make the control plane itself misbehave.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .. import faults as faultplane
from ..elastic.plan import _axis_candidates, _prod, plan_mesh
from ..observability import tracing as trace_spine
from ..utils.retry import RetryPolicy


class FleetAdmissionError(RuntimeError):
    """The pool cannot reserve the new job's ``min_axes`` floor without
    breaking a running job's — the arrival is rejected; nothing already
    admitted is disturbed."""


def min_plan(template: Dict[str, int],
             min_axes: Optional[Dict[str, int]] = None) -> Dict[str, int]:
    """The smallest legal mesh for a job: per axis, the smallest
    divisor of the template size that meets the ``min_axes`` floor.
    Its device count is what admission must reserve."""
    floors = {str(k): int(v) for k, v in (min_axes or {}).items()}
    axes = {str(k): int(v) for k, v in template.items()}
    return {k: min(c) for k, c in _axis_candidates(axes, floors).items()}


def plan_fleet(n_devices: int,
               jobs: Sequence[Tuple[str, Dict[str, int],
                                    Optional[Dict[str, int]], int]]
               ) -> Dict[str, Dict[str, int]]:
    """Disjoint mesh plans for every job on an ``n_devices`` pool.

    ``jobs`` is the admit-ordered sequence of
    ``(name, template, min_axes, priority)``.  The contract:

      * every job's ``min_axes`` floor is reserved before anything
        grows — raises ``ValueError`` when the floors themselves don't
        fit (the admission gate);
      * higher priority plans first; within a priority tier the
        available devices split evenly (each job still floored), so
        two equal jobs that both fit only at reduced size shrink the
        same way — and each shrink follows ``plan_mesh``'s own
        tie-break (``dp`` first, model-entangled axes last);
      * a final growth pass hands divisor-rounding leftovers to jobs
        in priority order, so the plan wastes as little of the pool as
        the divisor lattice allows.
    """
    specs = [(str(name), {str(k): int(v) for k, v in template.items()},
              dict(min_axes or {}), int(priority))
             for name, template, min_axes, priority in jobs]
    if not specs:
        return {}
    names = [s[0] for s in specs]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate job names in {names}")
    order = sorted(range(len(specs)),
                   key=lambda i: (-specs[i][3], i))
    floors = {name: _prod(min_plan(t, m)) for name, t, m, _ in specs}
    total_floor = sum(floors.values())
    if total_floor > n_devices:
        raise ValueError(
            f"floors need {total_floor} devices, pool has {n_devices}: "
            + ", ".join(f"{n}≥{floors[n]}" for n in names))

    plans: Dict[str, Dict[str, int]] = {}
    remaining = n_devices
    i = 0
    while i < len(order):
        prio = specs[order[i]][3]
        tier = []
        while i < len(order) and specs[order[i]][3] == prio:
            tier.append(order[i])
            i += 1
        later_floor = sum(floors[specs[j][0]] for j in order[i:])
        tier_avail = remaining - later_floor
        avail = tier_avail
        share0 = tier_avail // len(tier)
        for idx, j in enumerate(tier):
            name, t, m, _ = specs[j]
            rest_floor = sum(floors[specs[k][0]] for k in tier[idx + 1:])
            # even split of the tier's budget (FIXED share: a later job
            # must not absorb earlier jobs' divisor-rounding slack —
            # the growth pass hands that out in priority order), never
            # below this job's own floor, never eating a floor
            share = max(floors[name], share0)
            budget = min(share, avail - rest_floor)
            axes = plan_mesh(budget, t, m)
            plans[name] = axes
            avail -= _prod(axes)
        # the tier consumed its WHOLE entitlement, not just what the
        # divisor lattice let it use: rounding slack must reach the
        # growth pass (priority order), never a lower tier's budget —
        # what remains for later tiers is exactly their floor reserve
        remaining -= tier_avail

    # growth pass: divisor plans round down, so devices can be left
    # over even when a higher-priority job could legally use them
    leftover = n_devices - sum(_prod(p) for p in plans.values())
    for j in order:
        if leftover <= 0:
            break
        name, t, m, _ = specs[j]
        bigger = plan_mesh(_prod(plans[name]) + leftover, t, m)
        if _prod(bigger) > _prod(plans[name]):
            leftover -= _prod(bigger) - _prod(plans[name])
            plans[name] = bigger
    return plans


def enable_shared_compile_cache(path: str) -> str:
    """Point jax's persistent compilation cache at ``path`` (created if
    missing) and cache every program, however fast it compiled — the
    fleet's warm-start seam: a re-placed job's rebuild reuses the XLA
    programs its previous placement (or any same-topology job) already
    paid for, so a displacement costs a cache read, not a compile."""
    import os

    import jax
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return path


class PoolExhaustedError(RuntimeError):
    """A claim asked for more devices than the pool can give — the
    loud rejection in an autoscaler/gang-planner race: exactly one
    contender gets the last free device, the loser gets this (and no
    partial gang)."""


class DevicePool:
    """Per-device ownership ledger for one shared pool.

    Bookkeeping only — it never touches jax state.  Two writer
    disciplines share the ledger under one internal lock:

      * the **gang planner** (:class:`FleetScheduler`) swaps whole
        assignments with :meth:`reassign` over the *schedulable*
        devices, so disjointness stays an invariant, not a hope;
      * **incremental claimants** (the autoscale controller) take and
        return devices one claim at a time with :meth:`claim` /
        :meth:`release` / :meth:`transfer`.  Claimed devices leave the
        schedulable set, so a fleet replan can never hand a decode
        replica's device to a training job.

    A race for the last free device has exactly one winner; the loser
    gets :class:`PoolExhaustedError`, never a double-owned device.
    ``release`` is idempotent — a retried drain path is safe."""

    def __init__(self, devices=None):
        if devices is None:
            import jax
            devices = jax.devices()
        self.devices = list(devices)
        self._lock = threading.RLock()
        self._owner: Dict[Any, Optional[str]] = {d: None
                                                 for d in self.devices}
        self._claims: set = set()       # owners registered via claim()
        # device-second ownership accounting: a device claimed by
        # NOBODY is pool idle — a capacity question for the fleet
        # roll-up, never any job's badput (observability.goodput)
        from ..observability.goodput import OwnershipLedger
        self.goodput = OwnershipLedger(len(self.devices))

    def _note_occupancy_locked(self):
        # caller holds self._lock; the ownership ledger has its own
        # lock (pool-lock -> ledger-lock, never the reverse)
        claimed = sum(1 for o in self._owner.values() if o is not None)
        self.goodput.note(claimed, len(self.devices))

    @property
    def size(self) -> int:
        return len(self.devices)

    def owner_of(self, device) -> Optional[str]:
        with self._lock:
            return self._owner.get(device)

    def owned_by(self, name: str) -> list:
        with self._lock:
            return [d for d in self.devices if self._owner[d] == name]

    def free(self) -> list:
        with self._lock:
            return [d for d in self.devices if self._owner[d] is None]

    def schedulable(self) -> list:
        """Devices the gang planner may assign: everything not held by
        an incremental claimant (:meth:`claim`/:meth:`transfer`)."""
        with self._lock:
            return [d for d in self.devices
                    if self._owner[d] is None
                    or self._owner[d] not in self._claims]

    def claim(self, name: str, n: int = 1, trace_ctx=None) -> list:
        """Atomically take ``n`` free devices for ``name`` (pool
        order).  Raises :class:`PoolExhaustedError` — taking nothing —
        when fewer than ``n`` are free: the loser of a last-device
        race is told loudly instead of getting a partial gang.

        ``trace_ctx`` records the ledger move as a ``pool.claim`` span
        under the caller's trace (an autoscale decision, a placement)
        and notes the claimant's actuation context so its supervisor
        can link the resulting transition back to the cause."""
        n = int(n)
        if n <= 0:
            raise ValueError("claim needs n >= 1")
        with self._lock:
            free = [d for d in self.devices if self._owner[d] is None]
            if len(free) < n:
                raise PoolExhaustedError(
                    f"{name!r} asked for {n} device(s), only "
                    f"{len(free)} free in a pool of {self.size}")
            took = free[:n]
            for d in took:
                self._owner[d] = name
            self._claims.add(str(name))
            self._note_occupancy_locked()
        # span + actuation note OUTSIDE the ledger lock: tracing must
        # never extend the pool's critical section
        self._trace_move("pool.claim", trace_ctx, owners=(name,),
                         n=n, devices=took)
        return took

    def _trace_move(self, op: str, ctx, owners: Tuple[str, ...],
                    n: int, devices: Sequence):
        if ctx is None:
            return
        trace_spine.get_tracer().event(
            op, ctx, subsystem="fleet", owners=list(owners), n=int(n),
            devices=[repr(d) for d in devices])
        for owner in owners:
            trace_spine.note_actuation(owner, ctx)

    def transfer(self, src: str, dst: str, n: int = 1,
                 take: str = "tail", trace_ctx=None) -> list:
        """Atomically move ``n`` of ``src``'s devices to ``dst`` — the
        elastic-yield move (a training job shedding capacity to the
        serving tier at a traffic peak, and taking it back at the
        trough).  ``take`` picks which end of ``src``'s holding moves:
        ``"tail"`` (default) sheds spare/highest devices first;
        ``"head"`` forces the victim's in-use prefix out, displacing
        its mesh — the adversarial arrangement a rescale smoke uses to
        prove the drain/relayout path.  Raises
        :class:`PoolExhaustedError` when ``src`` holds fewer than
        ``n`` — floors are the caller's policy, the ledger only
        refuses to invent devices."""
        n = int(n)
        if n <= 0:
            raise ValueError("transfer needs n >= 1")
        with self._lock:
            held = [d for d in self.devices if self._owner[d] == src]
            if len(held) < n:
                raise PoolExhaustedError(
                    f"{src!r} holds {len(held)} device(s), cannot "
                    f"yield {n}")
            moved = held[:n] if take == "head" else held[-n:]
            for d in moved:
                self._owner[d] = dst
            self._claims.add(str(dst))
            if not any(o == src for o in self._owner.values()):
                self._claims.discard(str(src))
            self._note_occupancy_locked()
        self._trace_move("pool.transfer", trace_ctx, owners=(src, dst),
                         n=n, devices=moved)
        return moved

    def reassign(self, assignment: Dict[str, Sequence]) -> None:
        """Replace the gang-planned share of the ownership map with
        ``assignment`` (job → devices).  Rejects devices outside the
        pool and any device assigned to two jobs — the gang-placement
        invariant.  Devices held by incremental claimants are
        preserved as-is and may NOT appear in the assignment (the
        planner must plan over :meth:`schedulable`)."""
        with self._lock:
            kept = {d: o for d, o in self._owner.items()
                    if o in self._claims}
            owner: Dict[Any, Optional[str]] = {d: kept.get(d)
                                               for d in self.devices}
            for name, devs in assignment.items():
                if name in self._claims:
                    raise ValueError(
                        f"{name!r} is an incremental claimant; the "
                        "gang planner may not reassign it")
                for d in devs:
                    if d not in owner:
                        raise ValueError(f"{name!r} assigned a device "
                                         "outside the pool")
                    if owner[d] is not None:
                        raise ValueError(
                            f"device {d} assigned to both "
                            f"{owner[d]!r} and {name!r}")
                    owner[d] = name
            self._owner = owner
            self._note_occupancy_locked()

    def release(self, name: str, devices: Optional[Sequence] = None,
                trace_ctx=None) -> list:
        """Return ``devices`` (default: everything ``name`` holds) to
        the free pool; returns what was actually freed.  Idempotent:
        releasing devices the owner no longer holds — or holding
        nothing at all — is a no-op, so drain paths can retry safely."""
        with self._lock:
            if devices is None:
                victims = [d for d in self.devices
                           if self._owner[d] == name]
            else:
                victims = [d for d in devices
                           if self._owner.get(d) == name]
            for d in victims:
                self._owner[d] = None
            if not any(o == name for o in self._owner.values()):
                self._claims.discard(str(name))
            self._note_occupancy_locked()
        if victims:
            self._trace_move("pool.release", trace_ctx, owners=(name,),
                             n=len(victims), devices=victims)
        return victims


class FleetJob:
    """One admitted job: its spec, its supervisor, and its live pool
    assignment (read through :meth:`capacity` — the supervisor's
    ``capacity_fn`` seam)."""

    def __init__(self, scheduler: "FleetScheduler", name: str,
                 template: Dict[str, int],
                 min_axes: Optional[Dict[str, int]], priority: int,
                 steps: int, batch_fn: Callable, seq: int, recorder):
        self._scheduler = scheduler
        self.name = name
        self.template = {str(k): int(v) for k, v in template.items()}
        self.min_axes = dict(min_axes or {})
        self.priority = int(priority)
        self.steps = int(steps)
        self.batch_fn = batch_fn
        self.seq = int(seq)
        self.recorder = recorder
        self.supervisor = None
        self.thread: Optional[threading.Thread] = None
        self.state = "admitted"
        self.devices: list = []
        self.result = None
        self.error: Optional[BaseException] = None

    def capacity(self) -> list:
        """The job's current device assignment (the supervisor polls
        this every ``replan_every`` steps and at segment boundaries —
        preemption/regrow/displacement delivery is this read)."""
        with self._scheduler._lock:
            return list(self.devices)

    def alive(self) -> bool:
        t = self.thread
        return t is not None and t.is_alive()


class FleetScheduler:
    """Gang-place N :class:`ElasticSupervisor` jobs onto disjoint
    sub-meshes of one :class:`DevicePool` and keep every one alive
    through contention.

    Quickstart::

        fleet = FleetScheduler(jax.devices(), recorder=rec,
                               compile_cache_dir="/tmp/fleet_cache")
        fleet.admit("prod", factory, {"dp": 4}, priority=1,
                    steps=10_000, batch_fn=batches,
                    ckpt_dir="/ckpt/prod")
        fleet.admit("batch", factory, {"dp": 8}, min_axes={"dp": 2},
                    steps=50_000, batch_fn=batches2,
                    ckpt_dir="/ckpt/batch")
        fleet.serve_metrics(9100)          # aggregated /metrics+/healthz
        results = fleet.run()              # start + wait
    """

    def __init__(self, devices=None, *, recorder=None,
                 compile_cache_dir: Optional[str] = None,
                 replan_every: int = 2, handle_sigterm: bool = True):
        self.pool = DevicePool(devices)
        self._recorder = recorder
        self.replan_every = int(replan_every)
        self.handle_sigterm = bool(handle_sigterm)
        self.compile_cache_dir = None
        if compile_cache_dir is not None:
            self.compile_cache_dir = \
                enable_shared_compile_cache(compile_cache_dir)
        # guards _jobs / assignments / job state / lifecycle flags —
        # nothing slow (planning is arithmetic) ever runs under it
        self._lock = threading.Lock()
        self._jobs: Dict[str, FleetJob] = {}
        self._seq = 0
        self._started = False
        self._sigterm_seen = False
        self._preemption = None
        self._http = None
        # the control-plane fault/retry seam: placement computation and
        # preemption delivery both go through the plane and the unified
        # retry policy, so "the scheduler survives a flaky control
        # plane" is assertable like every other transient claim
        self._place_retry = RetryPolicy(max_attempts=4, base=0.01,
                                        max_delay=0.5, name="fleet",
                                        recorder_fn=self._rec)

    # ------------------------------------------------------------------ #
    def _rec(self):
        if self._recorder is not None:
            return self._recorder
        from ..observability import null_recorder
        return null_recorder()

    def _fleet_event(self, kind: str, job: Optional[FleetJob] = None,
                     **fields):
        """One fleet transition.  The ``fleet_event`` RECORD lands on
        the scheduler's recorder only (one stream = one timeline — the
        ``trace_summary fleet`` view merges job streams, so mirroring
        records would double every row); the COUNTER is mirrored onto
        the job's recorder so the aggregated /metrics shows
        per-job-labeled ``fleet/*`` series."""
        if job is not None:
            fields.setdefault("job", job.name)
            if job.recorder is not None:
                job.recorder.inc(f"fleet/{kind}")
        rec = self._rec()
        rec.inc(f"fleet/{kind}")
        rec.emit_record("fleet_event", kind=kind, **fields)

    # -- admission ------------------------------------------------------ #
    def admit(self, name: str, trainer_factory, template: Dict[str, int],
              *, steps: int, batch_fn: Callable, ckpt_dir: str,
              min_axes: Optional[Dict[str, int]] = None,
              priority: int = 0, recorder=None, ckpt_every: int = 50,
              replan_every: Optional[int] = None,
              **supervisor_kwargs) -> FleetJob:
        """Admit a job: reserve its ``min_axes`` floor, build its
        supervisor, re-plan the pool (which may shrink or displace
        lower-priority jobs — never kill them), and start it if the
        scheduler is running.

        Raises :class:`FleetAdmissionError` when the new job's floor
        cannot fit without breaking a running job's — the pool's
        standing jobs always win over an arrival."""
        if recorder is None:
            from ..observability import Recorder
            recorder = Recorder(annotate=False)
        with self._lock:
            if name in self._jobs:
                raise ValueError(f"job {name!r} already admitted")
            job = FleetJob(self, str(name), template, min_axes,
                           priority, steps, batch_fn, self._seq, recorder)
            self._seq += 1
            specs = self._specs_locked() + [
                (job.name, job.template, job.min_axes, job.priority)]
            try:
                plan_fleet(len(self.pool.schedulable()), specs)
            except ValueError as e:
                reject_reason = str(e)
            else:
                reject_reason = None
                self._jobs[name] = job
        if reject_reason is not None:
            # a full fleet_event, not a bare counter: rejections must
            # show up in the trace_summary fleet timeline too
            self._fleet_event("rejected", job, reason=reject_reason)
            raise FleetAdmissionError(
                f"cannot admit {name!r}: {reject_reason}") from None
        from ..elastic import ElasticSupervisor
        job.supervisor = ElasticSupervisor(
            trainer_factory, ckpt_dir, job.template,
            capacity_fn=job.capacity, recorder=recorder,
            ckpt_every=ckpt_every, min_axes=job.min_axes,
            replan_every=self.replan_every if replan_every is None
            else int(replan_every),
            name=job.name, **supervisor_kwargs)
        self._fleet_event("admitted", job, priority=job.priority,
                          template=job.template, min_axes=job.min_axes)
        self._replan("admit")
        if self._http is not None:
            self._register_job_http(job)
        started = False
        with self._lock:
            if self._started:
                started = True
        if started:
            self._start_job(job)
        return job

    def _specs_locked(self) -> List[Tuple]:
        """Planning specs for jobs still holding capacity, admit order.
        (``*_locked``: caller holds ``self._lock``.)"""
        live = [j for j in self._jobs.values()
                if j.state in ("admitted", "running")]
        live.sort(key=lambda j: j.seq)
        return [(j.name, j.template, j.min_axes, j.priority)
                for j in live]

    # -- planning / placement ------------------------------------------- #
    def _replan(self, reason: str):
        """Re-plan the whole pool and apply the new assignment; emits
        preempt/displace/regrow events for every job whose assignment
        changed.  ``fleet.place`` fires (and is retried) here — the
        control-plane placement call."""
        try:
            self._place_retry.run(faultplane.inject, "fleet.place",
                                  self._rec())
        except Exception as e:
            # the plan itself is pure arithmetic and delivery is a pull:
            # a control plane that keeps failing past the retry budget
            # is counted and logged, never a reason to strand the pool
            # on a stale assignment — an admit would otherwise leave a
            # half-admitted zero-device job, and a job_done replan
            # would die in its worker thread and survivors never regrow
            self._rec().inc("fleet/place_giveups")
            print(f"[fleet] placement injection kept failing ({e!r}); "
                  f"applying the plan anyway ({reason})", flush=True)
        with self._lock:
            changes = self._apply_plan_locked()
        for job, kind, detail in changes:
            if kind == "preempted":
                # delivering the shrink to the job's capacity seam is
                # the fleet.preempt site; in-process delivery is a
                # pull (the supervisor polls capacity()), so a
                # persistently failing inject is counted and logged,
                # never a reason to evict the job instead
                try:
                    self._place_retry.run(faultplane.inject,
                                          "fleet.preempt", job.recorder)
                except Exception as e:
                    self._rec().inc("fleet/preempt_giveups")
                    print(f"[fleet] preempt delivery to {job.name!r} "
                          f"kept failing ({e!r}); assignment stands — "
                          "the job reads it at its next capacity poll",
                          flush=True)
            self._fleet_event(kind, job, reason=reason, **detail)
            print(f"[fleet] {kind}: job={job.name} {detail} "
                  f"({reason})", flush=True)

    def _apply_plan_locked(self) -> List[Tuple[FleetJob, str, dict]]:
        """Compute the fair-share plan over live jobs, swap the pool's
        ownership map, update every job's assignment, and return the
        (job, transition, detail) changes for event emission OUTSIDE
        the lock."""
        specs = self._specs_locked()
        if not specs:
            self.pool.reassign({})
            return []
        order = sorted(specs, key=lambda s: (-s[3],
                                             self._jobs[s[0]].seq))
        # plan over the SCHEDULABLE share only: devices an incremental
        # claimant (the autoscale controller) holds are not the gang
        # planner's to hand out, and reassign() enforces that loudly.
        # A claim can land BETWEEN the schedulable() snapshot and the
        # reassign — the planner loses that race gracefully by
        # replanning over the shrunken share (bounded: each retry is
        # caused by a real concurrent claim)
        for attempt in range(8):
            schedulable = self.pool.schedulable()
            plans = plan_fleet(len(schedulable), specs)
            # placement, canonical (priority, admit) order: a job KEEPS
            # its current devices when its size is unchanged and no
            # higher-priority job claimed them this round (no churn on
            # a neighbor's completion); otherwise it takes the first
            # unclaimed devices in pool order — so a high-priority
            # arrival claims the pool prefix and displaces whoever
            # held it
            assignment: Dict[str, list] = {}
            claimed: set = set()
            for name, _t, _m, _p in order:
                n = _prod(plans[name])
                cur = self._jobs[name].devices
                if len(cur) == n and not (set(cur) & claimed) \
                        and all(d in schedulable for d in cur):
                    assignment[name] = list(cur)
                else:
                    free = [d for d in schedulable if d not in claimed]
                    assignment[name] = free[:n]
                claimed.update(assignment[name])
            try:
                self.pool.reassign(assignment)
                break
            except ValueError:
                if attempt == 7:
                    raise
                self._rec().inc("fleet/plan_races")
        changes: List[Tuple[FleetJob, str, dict]] = []
        for name, devs in assignment.items():
            job = self._jobs[name]
            old = job.devices
            job.devices = list(devs)
            detail = {"devices": len(devs), "axes": plans[name]}
            if not old:
                changes.append((job, "placed", detail))
            elif len(devs) < len(old):
                changes.append((job, "preempted",
                                {**detail, "from_devices": len(old)}))
            elif len(devs) > len(old):
                changes.append((job, "regrown",
                                {**detail, "from_devices": len(old)}))
            elif list(devs) != list(old):
                changes.append((job, "displaced", detail))
        return changes

    # -- lifecycle ------------------------------------------------------ #
    def _start_job(self, job: FleetJob):
        with self._lock:
            if job.thread is not None or job.supervisor is None:
                # admit() publishes the job before building its
                # supervisor (construction runs outside the lock); a
                # concurrent start() must not launch a supervisor-less
                # job — the admitting thread starts it itself once the
                # supervisor exists (it re-checks _started after)
                return
            job.state = "running"
            job.thread = threading.Thread(
                target=self._run_job, args=(job,), daemon=True,
                name=f"fleet:{job.name}")
        job.thread.start()

    def _run_job(self, job: FleetJob):
        try:
            result = job.supervisor.run(job.batch_fn, steps=job.steps)
            with self._lock:
                job.result = result
                job.state = "stopped" if job.supervisor._stop \
                    else "completed"
                state = job.state
            self._fleet_event(state, job, steps=len(result or []))
        except BaseException as e:   # noqa: BLE001 — recorded, re-raised to nobody
            with self._lock:
                job.error = e
                job.state = "failed"
            self._fleet_event("failed", job, error=repr(e))
            print(f"[fleet] job {job.name!r} failed: {e!r}", flush=True)
        finally:
            # survivors take over the freed capacity (regrow) — the
            # fair-share re-plan on completion/failure
            self._replan("job_done")

    def start(self) -> "FleetScheduler":
        """Install the process-level SIGTERM hook (main thread — the
        fan-out owner every worker-thread supervisor registers under)
        and start every admitted job."""
        with self._lock:
            if self.handle_sigterm and self._preemption is None:
                from ..checkpoint import PreemptionHandler
                self._preemption = PreemptionHandler().install()
            self._started = True
            pending = [j for j in self._jobs.values()
                       if j.state == "admitted"]
        for job in pending:
            self._start_job(job)
        return self

    def wait(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        """Block until every job finished (or ``timeout`` elapsed);
        returns ``{name: per-step losses}``.  A SIGTERM during the wait
        fans out to every supervisor (each drains + commits a preempt
        checkpoint) and then stops the pool cleanly — the fleet-level
        preemption semantic."""
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            with self._lock:
                running = [j for j in self._jobs.values() if j.alive()]
                preemption = self._preemption
            if not running:
                break
            if preemption is not None and preemption.requested:
                announce = False
                with self._lock:
                    if not self._sigterm_seen:
                        self._sigterm_seen = True
                        announce = True
                if announce:
                    self._fleet_event("sigterm",
                                      jobs=[j.name for j in running])
                    print("[fleet] SIGTERM: every supervisor drains and "
                          "commits; stopping the pool", flush=True)
                    for j in running:
                        j.supervisor.stop()
            for j in running:
                j.thread.join(timeout=0.2)
            if deadline is not None and _time.monotonic() > deadline:
                raise TimeoutError(
                    "fleet wait timed out with jobs still running: "
                    + ", ".join(j.name for j in running))
        with self._lock:
            return {name: j.result for name, j in self._jobs.items()}

    def run(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        self.start()
        return self.wait(timeout)

    def stop(self):
        """Ask every running job to commit a checkpoint and stop at its
        next step boundary."""
        with self._lock:
            jobs = [j for j in self._jobs.values() if j.alive()]
        for j in jobs:
            j.supervisor.stop()

    def job(self, name: str) -> FleetJob:
        with self._lock:
            return self._jobs[name]

    def jobs(self) -> Dict[str, FleetJob]:
        with self._lock:
            return dict(self._jobs)

    def shutdown(self):
        """Stop jobs, join their threads, stop the metrics server,
        release the SIGTERM hook."""
        self.stop()
        with self._lock:
            threads = [j.thread for j in self._jobs.values()
                       if j.thread is not None]
        for t in threads:
            t.join(timeout=30.0)
        http, self._http = self._http, None
        if http is not None:
            http.stop()
        with self._lock:
            preemption, self._preemption = self._preemption, None
        if preemption is not None:
            preemption.uninstall()

    # -- aggregated observability --------------------------------------- #
    def telemetry_sources(self):
        """``[(name, recorder), ...]``: the scheduler's ``fleet/*``
        recorder plus every admitted job's — the one-call aggregator
        attachment hook (``aggregator.add(scheduler, name="fleet")``)."""
        with self._lock:
            jobs = list(self._jobs.values())
        return [("scheduler", self._rec())] + \
            [(job.name, job.recorder) for job in jobs
             if job.recorder is not None]

    def goodput_doc(self) -> Dict[str, Any]:
        """Fleet-level device-second attribution: every job recorder's
        attached :class:`~bigdl_tpu.observability.goodput.GoodputLedger`
        snapshot rolled up with the pool's ownership ledger, so
        unclaimed device-seconds surface as POOL idle, not any job's
        badput.  Served at ``/goodput`` by :meth:`serve_metrics`."""
        from ..observability.goodput import rollup
        with self._lock:
            jobs = list(self._jobs.values())
        snaps = {}
        for job in jobs:
            rec = job.recorder
            led = rec.get_ledger() if rec is not None else None
            if led is not None:
                snaps[job.name] = led.snapshot()
        return rollup(snaps, self.pool.goodput.snapshot())

    def serve_metrics(self, port: int = 0, host: str = "127.0.0.1"):
        """One aggregated introspection server over the whole pool:
        ``/metrics`` renders the scheduler's ``fleet/*`` counters
        unlabeled plus every job's recorder under a ``job=<name>``
        label, ``/healthz`` returns 503 iff ANY job's verdict is
        stalled or diverged (worst-of liveness), and ``/goodput`` the
        fleet attribution roll-up (:meth:`goodput_doc`)."""
        from ..observability.http import IntrospectionServer
        if self._http is not None:
            self._http.stop()
        srv = IntrospectionServer(self._rec(), port=port, host=host,
                                  goodput_source=self.goodput_doc)
        self._http = srv
        with self._lock:
            jobs = list(self._jobs.values())
        for job in jobs:
            self._register_job_http(job)
        srv.start()
        return srv

    def _register_job_http(self, job: FleetJob):
        # late-bound watchdog: the supervisor builds its stall watchdog
        # when (and if) its hang-abort arms — resolve per scrape
        self._http.add_job(
            job.name, job.recorder,
            watchdog=lambda j=job: getattr(j.supervisor, "watchdog",
                                           None))
