"""bigdl_tpu.fleet — many jobs, one device pool.

The fleet layer gang-places N independent
:class:`~bigdl_tpu.elastic.ElasticSupervisor` jobs onto disjoint
sub-meshes of one shared pool and keeps all of them alive through
contention: a higher-priority arrival shrinks or displaces
lower-priority jobs through their existing ``capacity_fn`` seam (the
PR-6 drain → replan → resume path), completions hand capacity back
(regrow), and a job whose ``min_axes`` floor fits surviving capacity
is never killed by a fleet decision.  One aggregated ``/metrics`` +
``/healthz`` covers the pool (per-job labels, worst-of verdict), and a
shared persistent compile cache warm-starts re-placed jobs.

See ``docs/robustness.md`` § Fleet.
"""
from __future__ import annotations

from .pool import (DevicePool, FleetAdmissionError, FleetJob,
                   FleetScheduler, PoolExhaustedError,
                   enable_shared_compile_cache, min_plan, plan_fleet)

__all__ = ["DevicePool", "FleetScheduler", "FleetJob",
           "FleetAdmissionError", "PoolExhaustedError", "plan_fleet",
           "min_plan", "enable_shared_compile_cache"]
