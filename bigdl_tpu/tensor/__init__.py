"""bigdl_tpu.tensor — tensor utilities (≙ com.intel.analytics.bigdl.tensor).

The reference implements DenseTensor/SparseTensor/QuantizedTensor with MKL
BLAS (tensor/DenseTensor.scala, SparseTensor.scala, QuantizedTensor.scala).
On TPU the dense tensor IS ``jax.numpy.ndarray`` — XLA owns layout and
kernels — so this package provides:

- torch-style view helpers (narrow/select/index_select) used by layers and
  the t7/caffe importers;
- :class:`SparseTensor` — a COO (indices, values, shape) pytree.  XLA has no
  native sparse representation; ops on it lower to gathers +
  ``segment_sum`` which map well onto TPU (vectorized, static shapes given
  a fixed nnz);
- int8 quantization helpers backing ``bigdl_tpu.quantized``.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp


# --------------------------------------------------------------------- #
# torch-style helpers (tensor/DenseTensor.scala narrow/select/index)    #
# --------------------------------------------------------------------- #
def narrow(x, dim: int, index: int, size: int):
    """1-based narrow: slice `size` elements starting at `index` along dim."""
    return jax.lax.slice_in_dim(x, index - 1, index - 1 + size, axis=dim - 1)


def select(x, dim: int, index: int):
    """1-based select: index along dim, dropping that dim."""
    return jnp.take(x, index - 1, axis=dim - 1)


def index_select(x, dim: int, indices):
    """1-based index_select along dim."""
    idx = jnp.asarray(indices, jnp.int32) - 1
    return jnp.take(x, idx, axis=dim - 1)


def index_add(x, dim: int, indices, source):
    """1-based index_add: x[..., indices[i], ...] += source[..., i, ...]
    (tensor/DenseTensor.scala indexAdd). Duplicate indices accumulate."""
    idx = jnp.asarray(indices, jnp.int32) - 1
    sl = [slice(None)] * x.ndim
    sl[dim - 1] = idx
    return x.at[tuple(sl)].add(source)


def index_copy(x, dim: int, indices, source):
    """1-based index_copy: x[..., indices[i], ...] = source[..., i, ...]."""
    idx = jnp.asarray(indices, jnp.int32) - 1
    sl = [slice(None)] * x.ndim
    sl[dim - 1] = idx
    return x.at[tuple(sl)].set(source)


def index_fill(x, dim: int, indices, value):
    """1-based index_fill along dim with a scalar."""
    idx = jnp.asarray(indices, jnp.int32) - 1
    sl = [slice(None)] * x.ndim
    sl[dim - 1] = idx
    return x.at[tuple(sl)].set(value)


def _dim_index(index, dim_axis, ndim):
    """Build advanced-index grids that address x[i0,..,index[i0,..],..]."""
    grids = jnp.meshgrid(*[jnp.arange(s) for s in index.shape],
                         indexing="ij")
    return tuple(index if a == dim_axis else grids[a] for a in range(ndim))


def gather(x, dim: int, index):
    """torch-style 1-based gather: out[i][j] = x[index[i][j]][j] for dim=1
    (tensor/DenseTensor.scala gather)."""
    idx = jnp.asarray(index, jnp.int32) - 1
    return x[_dim_index(idx, dim - 1, x.ndim)]


def scatter(x, dim: int, index, src):
    """torch-style 1-based scatter: out[index[i][j]][j] = src[i][j] for
    dim=1 (tensor/DenseTensor.scala scatter)."""
    idx = jnp.asarray(index, jnp.int32) - 1
    return x.at[_dim_index(idx, dim - 1, x.ndim)].set(jnp.asarray(src))


def scatter_add(x, dim: int, index, src):
    """torch-style 1-based scatter-add (duplicates accumulate)."""
    idx = jnp.asarray(index, jnp.int32) - 1
    return x.at[_dim_index(idx, dim - 1, x.ndim)].add(jnp.asarray(src))


def masked_fill(x, mask, value):
    """x where mask is 0, value where mask is nonzero."""
    return jnp.where(jnp.asarray(mask).astype(bool), value, x)


def masked_select(x, mask):
    """Host-side masked select (data-dependent size ⇒ not jittable)."""
    xh, mh = np.asarray(x), np.asarray(mask).astype(bool)
    return jnp.asarray(xh[mh])


# --------------------------------------------------------------------- #
# sparse (tensor/SparseTensor.scala)                                    #
# --------------------------------------------------------------------- #
@jax.tree_util.register_pytree_node_class
class SparseTensor:
    """COO sparse tensor: ``indices`` (ndim, nnz) int32, ``values`` (nnz,),
    dense ``shape``.  Registered as a pytree so it can flow through jit."""

    def __init__(self, indices, values, shape: Tuple[int, ...]):
        self.indices = jnp.asarray(indices, jnp.int32)
        self.values = jnp.asarray(values)
        self.shape = tuple(int(s) for s in shape)

    # pytree protocol
    def tree_flatten(self):
        return (self.indices, self.values), self.shape

    @classmethod
    def tree_unflatten(cls, shape, children):
        obj = cls.__new__(cls)
        obj.indices, obj.values = children
        obj.shape = shape
        return obj

    @property
    def nnz(self):
        return self.values.shape[0]

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def dtype(self):
        return self.values.dtype

    @classmethod
    def from_dense(cls, dense):
        """Host-side conversion (data-dependent nnz ⇒ not jittable)."""
        dense = np.asarray(dense)
        idx = np.nonzero(dense)
        return cls(np.stack(idx).astype(np.int32), dense[idx], dense.shape)

    def to_dense(self):
        out = jnp.zeros(self.shape, self.values.dtype)
        return out.at[tuple(self.indices)].add(self.values)

    def row_ids(self):
        """Flattened leading-dims index per nnz (segment ids for combiners)."""
        if self.ndim == 1:
            return jnp.zeros((self.nnz,), jnp.int32)
        strides = np.concatenate(
            [np.cumprod(self.shape[1:-1][::-1])[::-1], [1]]).astype(np.int32)
        lead = self.indices[:-1]
        return jnp.sum(lead * jnp.asarray(strides)[:, None], axis=0)

    # -- the reference SparseTensor's implemented surface ---------------- #
    # (tensor/SparseTensor.scala: most Tensor methods throw Unsupported-
    #  Operation there too; the ones below are the ones it actually has)
    def astype(self, dtype) -> "SparseTensor":
        return SparseTensor(self.indices, self.values.astype(dtype),
                            self.shape)

    def apply1(self, fn) -> "SparseTensor":
        """Elementwise map over STORED values (zeros stay zero), jit-safe
        (tensor/SparseTensor.scala apply1)."""
        return SparseTensor(self.indices, fn(self.values), self.shape)

    def __mul__(self, scalar):
        return SparseTensor(self.indices, self.values * scalar, self.shape)

    __rmul__ = __mul__

    def __truediv__(self, scalar):
        return SparseTensor(self.indices, self.values / scalar, self.shape)

    def __neg__(self):
        return SparseTensor(self.indices, -self.values, self.shape)

    def abs(self) -> "SparseTensor":
        return self.apply1(jnp.abs)

    def sum(self):
        return jnp.sum(self.values)

    def num_nonzero_by_row(self):
        """nnz count per leading-dim row
        (tensor/SparseTensor.scala numNonZeroByRow)."""
        return jax.ops.segment_sum(
            jnp.ones((self.nnz,), jnp.int32), self.row_ids(),
            num_segments=int(np.prod(self.shape[:-1])) if self.ndim > 1
            else 1)

    def transpose(self) -> "SparseTensor":
        """2-D transpose: swap index rows (host/jit-safe; result indices
        are no longer row-major sorted)."""
        if self.ndim != 2:
            raise ValueError("transpose needs a 2-D SparseTensor")
        return SparseTensor(self.indices[::-1], self.values,
                            self.shape[::-1])

    t = transpose

    def narrow(self, dim: int, index: int, size: int) -> "SparseTensor":
        """1-based narrow along the LEADING dim — the one the reference
        supports for mini-batch slicing (SparseTensor.scala:306).
        Host-side (data-dependent nnz)."""
        if dim != 1:
            raise ValueError("SparseTensor.narrow supports dim=1 only "
                             "(like the reference)")
        lo = index - 1
        idx = np.asarray(self.indices)
        vals = np.asarray(self.values)
        keep = (idx[0] >= lo) & (idx[0] < lo + size)
        new_idx = idx[:, keep].copy()
        new_idx[0] -= lo
        return SparseTensor(new_idx, vals[keep],
                            (size,) + self.shape[1:])

    def select(self, dim: int, index: int) -> "SparseTensor":
        """1-based row select dropping the leading dim (host-side)."""
        if dim != 1 or self.ndim < 2:
            raise ValueError("SparseTensor.select supports dim=1 on >=2-D")
        sub = self.narrow(1, index, 1)
        return SparseTensor(np.asarray(sub.indices)[1:], sub.values,
                            self.shape[1:])

    def __repr__(self):
        return (f"SparseTensor(shape={self.shape}, nnz={int(self.nnz)}, "
                f"dtype={self.values.dtype})")


def sparse_dense_matmul(sp: SparseTensor, dense):
    """(N, D)-sparse @ (D, K)-dense via gather + segment_sum (MXU-free but
    bandwidth-optimal for high sparsity; SparseLinear's core)."""
    if sp.ndim != 2:
        raise ValueError("sparse_dense_matmul needs a 2-D SparseTensor")
    rows, cols = sp.indices
    contrib = sp.values[:, None] * jnp.take(dense, cols, axis=0)
    return jax.ops.segment_sum(contrib, rows, num_segments=sp.shape[0])


def embedding_bag(weight, ids_sp: SparseTensor, per_id_weights=None,
                  combiner="sum", max_norm=-1.0):
    """Combine embedding rows per sparse-row bag: one gather + one
    segment_sum (nn/LookupTableSparse.scala's per-row loop, TPU shape).

    ``ids_sp.values`` are 1-based embedding ids; combiner ∈ sum|mean|sqrtn;
    ``max_norm > 0`` l2-clips each embedding before combining.

    Out-of-range ids (< 1 or > weight rows) are a caller bug, not a
    clamping opportunity: concrete ids raise ``IndexError`` eagerly;
    under a trace (where python control flow can't fire) the offending
    embeddings are NaN-poisoned so the error surfaces in the output
    instead of silently reading row 0 or row V-1.
    """
    if combiner not in ("sum", "mean", "sqrtn"):
        raise ValueError(f"combiner must be sum|mean|sqrtn: {combiner}")
    n_rows = ids_sp.shape[0]
    rows = ids_sp.row_ids()
    ids = ids_sp.values.astype(jnp.int32) - 1
    oob = (ids < 0) | (ids >= weight.shape[0])
    try:
        if bool(oob.any()):
            bad = np.asarray(ids)[np.asarray(oob)][:4] + 1
            raise IndexError(
                f"embedding_bag: ids out of range for {weight.shape[0]}-row "
                f"table (1-based, first offenders: {bad.tolist()})")
    except jax.errors.TracerBoolConversionError:
        pass    # traced ids: the NaN poison below carries the error
    emb = jnp.take(weight, jnp.clip(ids, 0, weight.shape[0] - 1), axis=0)
    emb = jnp.where(oob[:, None], jnp.nan, emb)
    if max_norm > 0:
        norms = jnp.linalg.norm(emb, axis=-1, keepdims=True)
        emb = emb * jnp.minimum(1.0, max_norm / jnp.maximum(norms, 1e-7))
    wts = per_id_weights if per_id_weights is not None \
        else jnp.ones_like(emb[..., 0])
    summed = jax.ops.segment_sum(emb * wts[:, None], rows,
                                 num_segments=n_rows)
    if combiner == "sum":
        return summed
    if combiner == "mean":
        denom = jax.ops.segment_sum(wts, rows, num_segments=n_rows)
        return summed / jnp.maximum(denom, 1e-7)[:, None]
    denom2 = jax.ops.segment_sum(wts * wts, rows, num_segments=n_rows)
    return summed / jnp.sqrt(jnp.maximum(denom2, 1e-7))[:, None]


def sparse_concat(tensors, dim: int = 2):
    """Concatenate 2-D SparseTensors along rows (1-based dim=1) or
    columns (dim=2) (tensor/SparseTensor.scala concat, both arities)."""
    if dim == 2:
        n_rows = tensors[0].shape[0]
        col_off = 0
        idx_parts, val_parts = [], []
        for sp in tensors:
            if sp.shape[0] != n_rows:
                raise ValueError("row counts must match")
            idx_parts.append(sp.indices.at[1].add(col_off))
            val_parts.append(sp.values)
            col_off += sp.shape[1]
        return SparseTensor(jnp.concatenate(idx_parts, axis=1),
                            jnp.concatenate(val_parts), (n_rows, col_off))
    if dim == 1:
        n_cols = tensors[0].shape[1]
        row_off = 0
        idx_parts, val_parts = [], []
        for sp in tensors:
            if sp.shape[1] != n_cols:
                raise ValueError("column counts must match")
            idx_parts.append(sp.indices.at[0].add(row_off))
            val_parts.append(sp.values)
            row_off += sp.shape[0]
        return SparseTensor(jnp.concatenate(idx_parts, axis=1),
                            jnp.concatenate(val_parts), (row_off, n_cols))
    raise ValueError("sparse_concat supports dim=1 (rows) or 2 (columns)")


def sparse_dense_add(sp: SparseTensor, dense):
    """dense + sparse -> dense (tensor/DenseTensorMath sparse add path)."""
    return jnp.asarray(dense).at[tuple(sp.indices)].add(sp.values)


# --------------------------------------------------------------------- #
# int8 quantization (tensor/QuantizedTensor.scala)                      #
# --------------------------------------------------------------------- #
def quantize_symmetric(x, axis=None):
    """Symmetric per-tensor (axis=None) or per-axis int8 quantization.
    Returns (q_int8, scale) with x ≈ q * scale."""
    amax = jnp.max(jnp.abs(x)) if axis is None else jnp.max(
        jnp.abs(x), axis=tuple(i for i in range(x.ndim) if i != axis),
        keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize(q, scale):
    return q.astype(jnp.float32) * scale


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """int8 values + fp32 scale, x ≈ q * scale (tensor/QuantizedTensor.scala).
    A pytree, so it flows through jit; ``axis`` records the per-axis
    quantization dim (None = per-tensor)."""

    def __init__(self, q, scale, axis=None):
        self.q = jnp.asarray(q, jnp.int8)
        self.scale = jnp.asarray(scale, jnp.float32)
        self.axis = axis

    @classmethod
    def quantize(cls, x, axis=None):
        q, scale = quantize_symmetric(x, axis=axis)
        return cls(q, scale, axis=axis)

    def dequantize(self):
        return dequantize(self.q, self.scale)

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype

    def tree_flatten(self):
        return (self.q, self.scale), self.axis

    @classmethod
    def tree_unflatten(cls, axis, children):
        obj = cls.__new__(cls)
        obj.q, obj.scale = children
        obj.axis = axis
        return obj

    def __repr__(self):
        return f"QuantizedTensor(shape={self.q.shape}, axis={self.axis})"
