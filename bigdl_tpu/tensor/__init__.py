"""bigdl_tpu.tensor — tensor utilities (≙ com.intel.analytics.bigdl.tensor).

The reference implements DenseTensor/SparseTensor/QuantizedTensor with MKL
BLAS (tensor/DenseTensor.scala, SparseTensor.scala, QuantizedTensor.scala).
On TPU the dense tensor IS ``jax.numpy.ndarray`` — XLA owns layout and
kernels — so this package provides:

- torch-style view helpers (narrow/select/index_select) used by layers and
  the t7/caffe importers;
- :class:`SparseTensor` — a COO (indices, values, shape) pytree.  XLA has no
  native sparse representation; ops on it lower to gathers +
  ``segment_sum`` which map well onto TPU (vectorized, static shapes given
  a fixed nnz);
- int8 quantization helpers backing ``bigdl_tpu.quantized``.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp


# --------------------------------------------------------------------- #
# torch-style helpers (tensor/DenseTensor.scala narrow/select/index)    #
# --------------------------------------------------------------------- #
def narrow(x, dim: int, index: int, size: int):
    """1-based narrow: slice `size` elements starting at `index` along dim."""
    return jax.lax.slice_in_dim(x, index - 1, index - 1 + size, axis=dim - 1)


def select(x, dim: int, index: int):
    """1-based select: index along dim, dropping that dim."""
    return jnp.take(x, index - 1, axis=dim - 1)


def index_select(x, dim: int, indices):
    """1-based index_select along dim."""
    idx = jnp.asarray(indices, jnp.int32) - 1
    return jnp.take(x, idx, axis=dim - 1)


# --------------------------------------------------------------------- #
# sparse (tensor/SparseTensor.scala)                                    #
# --------------------------------------------------------------------- #
@jax.tree_util.register_pytree_node_class
class SparseTensor:
    """COO sparse tensor: ``indices`` (ndim, nnz) int32, ``values`` (nnz,),
    dense ``shape``.  Registered as a pytree so it can flow through jit."""

    def __init__(self, indices, values, shape: Tuple[int, ...]):
        self.indices = jnp.asarray(indices, jnp.int32)
        self.values = jnp.asarray(values)
        self.shape = tuple(int(s) for s in shape)

    # pytree protocol
    def tree_flatten(self):
        return (self.indices, self.values), self.shape

    @classmethod
    def tree_unflatten(cls, shape, children):
        obj = cls.__new__(cls)
        obj.indices, obj.values = children
        obj.shape = shape
        return obj

    @property
    def nnz(self):
        return self.values.shape[0]

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def dtype(self):
        return self.values.dtype

    @classmethod
    def from_dense(cls, dense):
        """Host-side conversion (data-dependent nnz ⇒ not jittable)."""
        dense = np.asarray(dense)
        idx = np.nonzero(dense)
        return cls(np.stack(idx).astype(np.int32), dense[idx], dense.shape)

    def to_dense(self):
        out = jnp.zeros(self.shape, self.values.dtype)
        return out.at[tuple(self.indices)].add(self.values)

    def row_ids(self):
        """Flattened leading-dims index per nnz (segment ids for combiners)."""
        if self.ndim == 1:
            return jnp.zeros((self.nnz,), jnp.int32)
        strides = np.concatenate(
            [np.cumprod(self.shape[1:-1][::-1])[::-1], [1]]).astype(np.int32)
        lead = self.indices[:-1]
        return jnp.sum(lead * jnp.asarray(strides)[:, None], axis=0)

    def __repr__(self):
        return (f"SparseTensor(shape={self.shape}, nnz={int(self.nnz)}, "
                f"dtype={self.values.dtype})")


def sparse_dense_matmul(sp: SparseTensor, dense):
    """(N, D)-sparse @ (D, K)-dense via gather + segment_sum (MXU-free but
    bandwidth-optimal for high sparsity; SparseLinear's core)."""
    if sp.ndim != 2:
        raise ValueError("sparse_dense_matmul needs a 2-D SparseTensor")
    rows, cols = sp.indices
    contrib = sp.values[:, None] * jnp.take(dense, cols, axis=0)
    return jax.ops.segment_sum(contrib, rows, num_segments=sp.shape[0])


# --------------------------------------------------------------------- #
# int8 quantization (tensor/QuantizedTensor.scala)                      #
# --------------------------------------------------------------------- #
def quantize_symmetric(x, axis=None):
    """Symmetric per-tensor (axis=None) or per-axis int8 quantization.
    Returns (q_int8, scale) with x ≈ q * scale."""
    amax = jnp.max(jnp.abs(x)) if axis is None else jnp.max(
        jnp.abs(x), axis=tuple(i for i in range(x.ndim) if i != axis),
        keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize(q, scale):
    return q.astype(jnp.float32) * scale
