"""Declarative SLOs with error-budget burn-rate alerting.

The measurement half of ROADMAP's SLO-driven autoscaling: an
:class:`SLObjective` states *what good looks like* ("decode TTFT p99
<= 200ms for 99% of observations over 5 minutes"); the
:class:`SLOEngine` evaluates every objective over a
:class:`~bigdl_tpu.observability.timeseries.SeriesStore` (a
``Recorder(keep_series=)`` store or a
:class:`~bigdl_tpu.observability.aggregate.MetricsAggregator`'s
scrape-fed one) into:

  compliance        good / total observations inside the window
  budget_remaining  ``1 - burn_slow`` — the fraction of the window's
                    error budget still unspent (negative = overspent)
  burn rate         ``(1 - compliance) / (1 - target)`` — 1.0 means
                    "spending the budget exactly as fast as allowed";
                    evaluated over a **fast** window (default
                    ``window / 12``) and the full **slow** window, and
                    a breach fires only when BOTH exceed
                    ``burn_alert`` — the classic dual-window guard
                    against paging on a single bad scrape (fast-only)
                    or alerting an hour late (slow-only)

Verdicts are emitted through the existing :class:`Recorder` — per-
objective ``slo/*`` gauges on every evaluation, ``slo/breaches`` /
``slo/recoveries`` counters and an ``slo_event`` record on every state
transition — so the flight recorder, ``/records`` and
``trace_summary slo`` all see breaches with zero extra plumbing.

Two objective modes:

  threshold  ``series=`` patterns + ``threshold=``: each point in the
             window is good iff ``value <= threshold`` (or ``>=`` with
             ``good_below=False``).  For latency-quantile series.
  ratio      ``bad_series=`` / ``total_series=`` counter patterns:
             compliance is ``1 - Δbad / Δtotal`` over the window.  For
             shed rate and checkpoint write failures.

All time comes from an injected clock (the store's), so burn-rate
fixtures reproduce bit-for-bit in tests.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .recorder import Recorder


class SLObjective:
    """One service-level objective over series-store metrics."""

    def __init__(self, name: str, target: float, window: float,
                 series=None, threshold: Optional[float] = None,
                 good_below: bool = True, bad_series=None,
                 total_series=None, fast_window: Optional[float] = None,
                 burn_alert: float = 2.0, description: str = ""):
        if (series is None) == (bad_series is None):
            raise ValueError("exactly one of series= (threshold mode) "
                             "or bad_series=/total_series= (ratio mode)"
                             " is required")
        if series is not None and threshold is None:
            raise ValueError("threshold mode needs threshold=")
        if bad_series is not None and total_series is None:
            raise ValueError("ratio mode needs total_series=")
        self.name = str(name)
        self.target = float(target)
        if not 0.0 < self.target < 1.0:
            raise ValueError("target must be in (0, 1)")
        self.window = float(window)
        self.fast_window = (float(fast_window) if fast_window is not None
                            else self.window / 12.0)
        self.series = series
        self.threshold = (float(threshold) if threshold is not None
                          else None)
        self.good_below = bool(good_below)
        self.bad_series = bad_series
        self.total_series = total_series
        self.burn_alert = float(burn_alert)
        self.description = description

    @property
    def mode(self) -> str:
        return "threshold" if self.series is not None else "ratio"

    # -- window math -------------------------------------------------------- #
    def compliance(self, store, window: float, now: float
                   ) -> Tuple[float, float, Optional[float]]:
        """``(good, total, compliance)`` over the trailing ``window``;
        compliance is ``None`` when there is no data to judge."""
        if self.series is not None:
            good = total = 0
            for key in store.match(self.series):
                for _, v in store.points(key, window, now):
                    total += 1
                    if (v <= self.threshold if self.good_below
                            else v >= self.threshold):
                        good += 1
            return (float(good), float(total),
                    good / total if total else None)
        bad = self._delta_sum(store, self.bad_series, window, now)
        tot = self._delta_sum(store, self.total_series, window, now)
        if tot is None or tot <= 0:
            return (bad or 0.0, tot or 0.0, None)
        bad = bad or 0.0
        return (bad, tot, max(0.0, 1.0 - bad / tot))

    @staticmethod
    def _delta_sum(store, patterns, window: float, now: float
                   ) -> Optional[float]:
        """Summed counter increase over the window across every
        matching series (None when no series has two points yet)."""
        total = None
        for key in store.match(patterns):
            d = store.get(key).delta(window, now)
            if d is not None:
                total = (total or 0.0) + max(d, 0.0)
        return total

    def evaluate(self, store, now: Optional[float] = None
                 ) -> Dict[str, Any]:
        """One verdict: compliance + budget over the full window, burn
        rates over (fast, slow) windows, breach = both above
        ``burn_alert``.  A window with no data never breaches — "no
        traffic" is not "all traffic failed"."""
        if now is None:
            now = store.now()
        allowed = 1.0 - self.target
        good, total, comp_slow = self.compliance(store, self.window, now)
        _, _, comp_fast = self.compliance(store, self.fast_window, now)
        burn_slow = (None if comp_slow is None
                     else (1.0 - comp_slow) / allowed)
        burn_fast = (None if comp_fast is None
                     else (1.0 - comp_fast) / allowed)
        breach = (burn_slow is not None and burn_fast is not None
                  and burn_slow >= self.burn_alert
                  and burn_fast >= self.burn_alert)
        return {
            "objective": self.name,
            "mode": self.mode,
            "target": self.target,
            "threshold": self.threshold,
            "window": self.window,
            "fast_window": self.fast_window,
            "burn_alert": self.burn_alert,
            "good": good,
            "total": total,
            "compliance": comp_slow,
            "budget_remaining": (None if burn_slow is None
                                 else 1.0 - burn_slow),
            "burn_slow": burn_slow,
            "burn_fast": burn_fast,
            "no_data": comp_slow is None,
            "breach": breach,
        }


def default_objectives(window: float = 300.0, target: float = 0.99,
                       ttft_p99_ms: float = 200.0,
                       intertoken_p99_ms: float = 50.0,
                       shed_target: float = 0.99,
                       ckpt_target: float = 0.999,
                       burn_alert: float = 2.0) -> List[SLObjective]:
    """The serving + training objectives this codebase already exports
    metrics for.  Patterns match BOTH naming planes: a raw recorder
    store (``decode/ttft_ms/p99``) and an aggregator store
    (``replica0/bigdl_decode_ttft_ms/p99``)."""
    return [
        SLObjective("decode_ttft_p99", target=target, window=window,
                    series=("*decode*ttft_ms/p99",),
                    threshold=ttft_p99_ms, burn_alert=burn_alert,
                    description="time-to-first-token p99"),
        SLObjective("decode_intertoken_p99", target=target,
                    window=window,
                    series=("*decode*intertoken_ms/p99",),
                    threshold=intertoken_p99_ms, burn_alert=burn_alert,
                    description="inter-token latency p99"),
        SLObjective("shed_rate", target=shed_target, window=window,
                    bad_series=("*decode*shed_*", "*serving*shed_*"),
                    total_series=("*decode*requests*",
                                  "*serving*requests*"),
                    burn_alert=burn_alert,
                    description="admitted fraction of offered requests"),
        SLObjective("checkpoint_writes", target=ckpt_target,
                    window=window,
                    bad_series=("*checkpoint*failed*",),
                    total_series=("*checkpoint*committed*",
                                  "*checkpoint*failed*"),
                    burn_alert=burn_alert,
                    description="checkpoint write success"),
    ]


class SLOEngine:
    """Evaluate objectives over a series store; emit ``slo/*`` gauges
    and ``slo_event`` records through a Recorder."""

    def __init__(self, store, objectives: Sequence[SLObjective] = (),
                 recorder: Optional[Recorder] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.store = store
        self.objectives: List[SLObjective] = list(objectives)
        self.recorder = recorder if recorder is not None \
            else Recorder(annotate=False)
        self.clock = clock if clock is not None \
            else getattr(store, "now", time.time)
        self._breached: Dict[str, bool] = {}
        #: the most recent :meth:`evaluate` results, by objective name —
        #: consumers that must not re-run the window math (the
        #: autoscale policy reading burn rates between its own ticks)
        #: read this instead of calling evaluate() again
        self.last_results: Dict[str, Dict[str, Any]] = {}
        self.last_eval_at: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def add(self, objective: SLObjective) -> "SLOEngine":
        self.objectives.append(objective)
        return self

    def evaluate(self, now: Optional[float] = None
                 ) -> Dict[str, Dict[str, Any]]:
        """One pass over every objective.  Gauges are refreshed each
        call; ``slo_event`` records fire only on breach/recovery
        transitions, so the record stream stays quiet in steady state."""
        if now is None:
            now = float(self.clock())
        rec = self.recorder
        results: Dict[str, Dict[str, Any]] = {}
        for obj in self.objectives:
            r = obj.evaluate(self.store, now)
            results[obj.name] = r
            g = f"slo/{obj.name}"
            rec.gauge(f"{g}/breach", 1.0 if r["breach"] else 0.0)
            rec.gauge(f"{g}/no_data", 1.0 if r["no_data"] else 0.0)
            if not r["no_data"]:
                rec.gauge(f"{g}/compliance", r["compliance"])
                rec.gauge(f"{g}/budget_remaining",
                          r["budget_remaining"])
                rec.gauge(f"{g}/burn_slow", r["burn_slow"])
                if r["burn_fast"] is not None:
                    rec.gauge(f"{g}/burn_fast", r["burn_fast"])
            prev = self._breached.get(obj.name, False)
            if r["breach"] and not prev:
                rec.inc("slo/breaches")
                rec.emit_record("slo_event", kind="breach",
                                eval_time=now, **r)
            elif prev and not r["breach"] and not r["no_data"]:
                rec.inc("slo/recoveries")
                rec.emit_record("slo_event", kind="recovered",
                                eval_time=now, **r)
            if not r["no_data"]:
                self._breached[obj.name] = r["breach"]
        self.last_results = results
        self.last_eval_at = now
        return results

    def breached(self) -> List[str]:
        """Objectives currently in breach, sorted."""
        return sorted(n for n, b in self._breached.items() if b)

    def summary_record(self, results: Optional[Dict[str, Any]] = None,
                       now: Optional[float] = None) -> Dict[str, Any]:
        """Emit one ``slo_summary`` record carrying the full objective
        table — the shutdown/post-run snapshot ``trace_summary slo``
        renders its table from."""
        if now is None:
            now = float(self.clock())
        if results is None:
            results = self.evaluate(now)
        return self.recorder.emit_record(
            "slo_summary", eval_time=now,
            objectives=[results[o.name] for o in self.objectives
                        if o.name in results])

    # -- background evaluation ---------------------------------------------- #
    def start(self, interval: float = 5.0) -> "SLOEngine":
        if self._thread is not None:
            return self

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.evaluate()
                except Exception:
                    pass        # SLO math must never kill the host

        self._stop.clear()
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="slo-engine")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
