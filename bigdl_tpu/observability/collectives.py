"""Collective-volume accounting: bytes on the interconnect per step.

Two complementary estimators:

  * **Static** (`tree_bytes`, `ring_allreduce_bytes`, ...): computed
    host-side from gradient/parameter shapes — what
    :mod:`~bigdl_tpu.parallel.allreduce` reports at trace time, with
    pre/post-compression byte counts (≙ FP16CompressedTensor's halved
    wire volume in the reference's parameter server).
  * **Measured** (`hlo_collective_ops`): parsed out of the partitioned
    HLO of a compiled step, counting the collectives XLA actually
    inserted (the GSPMD path in :mod:`~bigdl_tpu.parallel.spmd`, where
    the compiler, not our code, chooses the ops).

Ring costs per chip for S bytes over a ring of n:
  all-reduce       2*S*(n-1)/n     (reduce-scatter + all-gather)
  all-gather         S*(n-1)/n     (S = full gathered size)
  reduce-scatter     S*(n-1)/n     (S = full pre-scatter size)
  collective-permute S
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "f64": 8, "s32": 4,
                "u32": 4, "s8": 1, "u8": 1, "pred": 1, "s64": 8, "u64": 8}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


# -- static accounting ----------------------------------------------------- #
def leaf_bytes(leaf, wire_itemsize: Optional[int] = None) -> int:
    """Bytes of one array leaf; ``wire_itemsize`` overrides the dtype
    width (compressed-on-the-wire accounting)."""
    shape = getattr(leaf, "shape", ())
    n = int(np.prod(shape)) if shape else 1
    if wire_itemsize is not None:
        return n * wire_itemsize
    dt = getattr(leaf, "dtype", None)
    return n * (np.dtype(dt).itemsize if dt is not None else 4)


def tree_bytes(tree, wire_itemsize: Optional[int] = None,
               mask=None) -> int:
    """Total bytes of every (float) array leaf in a pytree.  ``mask``
    (same-structure bool tree) restricts the sum to True leaves."""
    import jax
    leaves = jax.tree_util.tree_leaves(tree)
    if mask is None:
        sel = leaves
    else:
        flags = jax.tree_util.tree_leaves(mask)
        sel = [l for l, m in zip(leaves, flags) if m]
    return sum(leaf_bytes(l, wire_itemsize) for l in sel)


def ring_allreduce_bytes(total_bytes: int, n: int) -> float:
    return 2.0 * total_bytes * (n - 1) / n if n > 1 else 0.0


def ring_gather_bytes(total_bytes: int, n: int) -> float:
    """all-gather OR reduce-scatter of a full-size tensor over a ring."""
    return float(total_bytes) * (n - 1) / n if n > 1 else 0.0


def compressed_itemsize(compress: Optional[str]) -> Optional[int]:
    """Wire bytes/element for an allreduce ``compress=`` mode."""
    if compress in ("fp16", "float16", "bf16", "bfloat16"):
        return 2
    return None


# -- measured accounting (partitioned HLO) --------------------------------- #
def _element_bytes(shape_str: str) -> List[int]:
    """Bytes of each typed element in an HLO result type — one entry for
    a plain type like f32[64,3,7,7], several for a tuple."""
    out = []
    for dt, dims in _SHAPE_RE.findall(shape_str):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append(n * _DTYPE_BYTES[dt])
    return out


def _bytes_of(shape_str: str) -> int:
    """Total bytes of an HLO result type like f32[64,3,7,7] or a tuple."""
    return sum(_element_bytes(shape_str))


def _group_size(line: str, default: int) -> int:
    """Ring size of a collective = its replica-group size, parsed from
    the HLO attrs.  Forms: ``replica_groups={{0,1},{2,3}}`` (explicit)
    and ``replica_groups=[G,S]<=[...]`` (iota: G groups of S)."""
    m = re.search(r"replica_groups=\[(\d+),(\d+)\]", line)
    if m:
        return int(m.group(2))
    m = re.search(r"replica_groups=\{\{([\d,]+)\}", line)
    if m:
        return len(m.group(1).split(","))
    return default


def _parse_collective_line(s: str, n_shards: int):
    """One stripped HLO line -> (op, result_bytes, wire_bytes, line) or
    None for non-collective lines.  Shared by :func:`hlo_collective_ops`
    and :func:`hlo_group_breakdown` so the two views never disagree on
    what counts as a collective or what it weighs."""
    # result type may be a long tuple containing /*index=N*/ comments
    m = re.match(r"%?[\w.-]+ = (.*?) (all-reduce|all-gather|"
                 r"reduce-scatter|collective-permute|all-to-all)"
                 r"(-start)?\(", s)
    if not m:
        return None
    shape_str, op, is_start = m.group(1), m.group(2), bool(m.group(3))
    elems = _element_bytes(shape_str)
    if is_start and len(elems) > 1:
        # async form: the result tuple carries (operand, result[,
        # context]) — only one element is the payload, the rest would
        # double-count it (and ignore the matching -done).  The wire
        # formulas below expect the RESULT size: the full tensor for
        # all-gather (largest element), the 1/n SHARD for
        # reduce-scatter (smallest — taking the operand here would
        # overcount by a factor of n after the ×n below)
        size = min(elems) if op == "reduce-scatter" else max(elems)
    else:
        size = sum(elems)
    n = _group_size(s, n_shards)
    f = (n - 1) / n if n > 1 else 0.0
    if op == "all-reduce":
        wire = 2 * size * f
    elif op == "all-gather":
        wire = size * f               # result is the full size
    elif op == "reduce-scatter":
        wire = size * f * n           # result is the 1/n shard
    else:
        wire = size                   # permute / all-to-all: ships ~S
    return op, size, wire, s


def hlo_collective_ops(hlo_text: str,
                       n_shards: int) -> List[Tuple[str, int, float]]:
    """[(op, result_bytes, wire_bytes_per_chip)] for every collective in
    a partitioned-HLO dump (``compiled.as_text()``)."""
    per_op = []
    for line in hlo_text.splitlines():
        parsed = _parse_collective_line(line.strip(), n_shards)
        if parsed is not None:
            per_op.append(parsed[:3])
    return per_op


# -- per-axis-group attribution (partitioned HLO) -------------------------- #
def _replica_id_groups(line: str) -> Optional[List[Tuple[int, ...]]]:
    """Concrete device-id groups of one collective line, from either the
    explicit ``replica_groups={{0,1},{2,3}}`` form or the iota form
    ``replica_groups=[G,S]<=[dims](T(perm))``.  None when the line has
    no parseable group list (the collective spans everything)."""
    m = re.search(r"replica_groups=\{(\{[\d,]+\}(?:,\s*\{[\d,]+\})*)\}",
                  line)
    if m:
        return [tuple(int(x) for x in grp.split(","))
                for grp in re.findall(r"\{([\d,]+)\}", m.group(1))]
    m = re.search(r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\]"
                  r"(?:T\(([\d,]+)\))?", line)
    if m:
        g, s = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        ids = np.arange(int(np.prod(dims, dtype=np.int64))).reshape(dims)
        if m.group(4):
            ids = ids.transpose([int(p) for p in m.group(4).split(",")])
        return [tuple(int(x) for x in row) for row in ids.reshape(g, s)]
    return None


def _axes_list(mesh) -> List[Tuple[str, int]]:
    """Ordered [(axis, size), ...] from a jax Mesh, a dict, or an
    already-ordered pair list."""
    if hasattr(mesh, "axis_names"):        # jax.sharding.Mesh
        return [(str(a), int(mesh.shape[a])) for a in mesh.axis_names]
    if isinstance(mesh, dict):
        return [(str(k), int(v)) for k, v in mesh.items()]
    return [(str(a), int(s)) for a, s in mesh]


def replica_group_label(groups: Optional[List[Tuple[int, ...]]],
                        mesh) -> str:
    """Name the mesh-axis subset a replica-group set varies over.

    The mesh's device ids are laid out row-major over its axes (how
    ``create_mesh`` builds them), so a device id maps to axis
    coordinates; the axes whose coordinate varies *within* a group are
    the axes the collective communicates over.  Returns e.g. ``"dp"``,
    ``"tp"``, ``"dp×pp"``, ``"all"`` (every axis >1 varies), or
    ``"unattributed"`` when the ids don't fit the mesh."""
    axes = _axes_list(mesh)
    names = [n for n, _ in axes]
    sizes = [s for _, s in axes]
    total = int(np.prod(sizes, dtype=np.int64))
    if groups is None:
        return "all"
    coords = np.stack(np.unravel_index(np.arange(total), sizes), axis=1)
    varying = set()
    for grp in groups:
        if any(d < 0 or d >= total for d in grp):
            return "unattributed"
        cs = coords[list(grp)]
        for i in range(len(names)):
            if len(np.unique(cs[:, i])) > 1:
                varying.add(i)
    if not varying:
        return "unattributed"       # singleton groups: no communication
    if varying == {i for i, s in enumerate(sizes) if s > 1}:
        return "all" if len(varying) > 1 else names[next(iter(varying))]
    return "×".join(names[i] for i in sorted(varying))


def hlo_group_breakdown(hlo_text: str, mesh) -> Dict[str, Dict[str, float]]:
    """Per-axis-group wire volume of a partitioned HLO:
    ``{group_label: {op: wire_bytes_per_chip, "wire_bytes": total}}``.

    This is the measured counterpart of the trace-time
    ``comm/group.<axis>.*`` gauges — on the GSPMD/partial-auto paths the
    compiler owns the op choice, so the only honest per-group
    attribution is to parse the replica groups it actually emitted and
    map them back onto mesh axes."""
    axes = _axes_list(mesh)
    n_shards = int(np.prod([s for _, s in axes], dtype=np.int64))
    out: Dict[str, Dict[str, float]] = {}
    for line in hlo_text.splitlines():
        s = line.strip()
        parsed = _parse_collective_line(s, n_shards)
        if parsed is None:
            continue
        op, _, wire, _ = parsed
        label = replica_group_label(_replica_id_groups(s), axes)
        d = out.setdefault(label, {"wire_bytes": 0.0})
        d[op] = d.get(op, 0.0) + wire
        d["wire_bytes"] += wire
    return out


def hlo_collective_bytes(hlo_text: str, n_shards: int) -> float:
    """Total wire bytes per chip per step from a partitioned HLO."""
    return sum(w for _, _, w in hlo_collective_ops(hlo_text, n_shards))


# -- trace-time reporting --------------------------------------------------- #
def account_collective(op: str, raw_bytes: int, wire_bytes: float,
                       recorder=None, group: Optional[str] = None):
    """Report one collective's static volume to the (active) recorder.

    Called at *trace time* from inside jitted step functions — shapes
    are static there, so the numbers are exact per executed step; the
    host loop turns the per-step gauges into cumulative counters.
    Gauges set (per step):
      ``collective/{op}_bytes``       raw (uncompressed) volume
      ``collective/{op}_wire_bytes``  on-the-wire (post-compression) volume
      ``collective/bytes_per_step``   running total of raw volume
      ``collective/wire_bytes_per_step``  running total of wire volume

    ``group`` names the parallelism group the exchange runs over (the
    mesh axis or axis set, e.g. ``"dp"`` / ``"ep"`` / ``"dp×pp"``) and
    additionally lands the volume in the per-group family — ACCUMULATED
    across calls in one trace (a composed step issues several exchanges
    per group; per-op gauges keep last-write semantics, the group view
    must not):
      ``comm/group.{group}.{op}_bytes`` / ``..._wire_bytes``
      ``comm/group.{group}.wire_bytes_per_step``
    Callers reset the ``comm/group.`` prefix alongside ``collective/``
    when rebuilding a step (re-traces re-report).
    """
    if recorder is None:
        from .recorder import get_recorder
        recorder = get_recorder()
    if not recorder.enabled:
        return
    recorder.gauge(f"collective/{op}_bytes", float(raw_bytes))
    recorder.gauge(f"collective/{op}_wire_bytes", float(wire_bytes))
    recorder.gauge("collective/bytes_per_step",
                   recorder.gauge_value("collective/bytes_per_step")
                   + float(raw_bytes))
    recorder.gauge("collective/wire_bytes_per_step",
                   recorder.gauge_value("collective/wire_bytes_per_step")
                   + float(wire_bytes))
    if group is not None:
        pre = f"comm/group.{group}."
        for suffix, val in ((f"{op}_bytes", float(raw_bytes)),
                            (f"{op}_wire_bytes", float(wire_bytes)),
                            ("wire_bytes_per_step", float(wire_bytes))):
            recorder.gauge(pre + suffix,
                           recorder.gauge_value(pre + suffix) + val)
