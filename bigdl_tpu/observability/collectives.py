"""Collective-volume accounting: bytes on the interconnect per step.

Two complementary estimators:

  * **Static** (`tree_bytes`, `ring_allreduce_bytes`, ...): computed
    host-side from gradient/parameter shapes — what
    :mod:`~bigdl_tpu.parallel.allreduce` reports at trace time, with
    pre/post-compression byte counts (≙ FP16CompressedTensor's halved
    wire volume in the reference's parameter server).
  * **Measured** (`hlo_collective_ops`): parsed out of the partitioned
    HLO of a compiled step, counting the collectives XLA actually
    inserted (the GSPMD path in :mod:`~bigdl_tpu.parallel.spmd`, where
    the compiler, not our code, chooses the ops).

Ring costs per chip for S bytes over a ring of n:
  all-reduce       2*S*(n-1)/n     (reduce-scatter + all-gather)
  all-gather         S*(n-1)/n     (S = full gathered size)
  reduce-scatter     S*(n-1)/n     (S = full pre-scatter size)
  collective-permute S
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "f64": 8, "s32": 4,
                "u32": 4, "s8": 1, "u8": 1, "pred": 1, "s64": 8, "u64": 8}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


# -- static accounting ----------------------------------------------------- #
def leaf_bytes(leaf, wire_itemsize: Optional[int] = None) -> int:
    """Bytes of one array leaf; ``wire_itemsize`` overrides the dtype
    width (compressed-on-the-wire accounting)."""
    shape = getattr(leaf, "shape", ())
    n = int(np.prod(shape)) if shape else 1
    if wire_itemsize is not None:
        return n * wire_itemsize
    dt = getattr(leaf, "dtype", None)
    return n * (np.dtype(dt).itemsize if dt is not None else 4)


def tree_bytes(tree, wire_itemsize: Optional[int] = None,
               mask=None) -> int:
    """Total bytes of every (float) array leaf in a pytree.  ``mask``
    (same-structure bool tree) restricts the sum to True leaves."""
    import jax
    leaves = jax.tree_util.tree_leaves(tree)
    if mask is None:
        sel = leaves
    else:
        flags = jax.tree_util.tree_leaves(mask)
        sel = [l for l, m in zip(leaves, flags) if m]
    return sum(leaf_bytes(l, wire_itemsize) for l in sel)


def ring_allreduce_bytes(total_bytes: int, n: int) -> float:
    return 2.0 * total_bytes * (n - 1) / n if n > 1 else 0.0


def ring_gather_bytes(total_bytes: int, n: int) -> float:
    """all-gather OR reduce-scatter of a full-size tensor over a ring."""
    return float(total_bytes) * (n - 1) / n if n > 1 else 0.0


def compressed_itemsize(compress: Optional[str]) -> Optional[int]:
    """Wire bytes/element for an allreduce ``compress=`` mode."""
    if compress in ("fp16", "float16", "bf16", "bfloat16"):
        return 2
    return None


# -- measured accounting (partitioned HLO) --------------------------------- #
def _element_bytes(shape_str: str) -> List[int]:
    """Bytes of each typed element in an HLO result type — one entry for
    a plain type like f32[64,3,7,7], several for a tuple."""
    out = []
    for dt, dims in _SHAPE_RE.findall(shape_str):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append(n * _DTYPE_BYTES[dt])
    return out


def _bytes_of(shape_str: str) -> int:
    """Total bytes of an HLO result type like f32[64,3,7,7] or a tuple."""
    return sum(_element_bytes(shape_str))


def _group_size(line: str, default: int) -> int:
    """Ring size of a collective = its replica-group size, parsed from
    the HLO attrs.  Forms: ``replica_groups={{0,1},{2,3}}`` (explicit)
    and ``replica_groups=[G,S]<=[...]`` (iota: G groups of S)."""
    m = re.search(r"replica_groups=\[(\d+),(\d+)\]", line)
    if m:
        return int(m.group(2))
    m = re.search(r"replica_groups=\{\{([\d,]+)\}", line)
    if m:
        return len(m.group(1).split(","))
    return default


def hlo_collective_ops(hlo_text: str,
                       n_shards: int) -> List[Tuple[str, int, float]]:
    """[(op, result_bytes, wire_bytes_per_chip)] for every collective in
    a partitioned-HLO dump (``compiled.as_text()``)."""
    per_op = []
    for line in hlo_text.splitlines():
        s = line.strip()
        # result type may be a long tuple containing /*index=N*/ comments
        m = re.match(r"%?[\w.-]+ = (.*?) (all-reduce|all-gather|"
                     r"reduce-scatter|collective-permute|all-to-all)"
                     r"(-start)?\(", s)
        if not m:
            continue
        shape_str, op, is_start = m.group(1), m.group(2), bool(m.group(3))
        elems = _element_bytes(shape_str)
        if is_start and len(elems) > 1:
            # async form: the result tuple carries (operand, result[,
            # context]) — only the largest element is the payload, the
            # rest would double-count it (and ignore the matching -done)
            size = max(elems)
        else:
            size = sum(elems)
        n = _group_size(s, n_shards)
        f = (n - 1) / n if n > 1 else 0.0
        if op == "all-reduce":
            wire = 2 * size * f
        elif op == "all-gather":
            wire = size * f               # result is the full size
        elif op == "reduce-scatter":
            wire = size * f * n           # result is the 1/n shard
        else:
            wire = size
        per_op.append((op, size, wire))
    return per_op


def hlo_collective_bytes(hlo_text: str, n_shards: int) -> float:
    """Total wire bytes per chip per step from a partitioned HLO."""
    return sum(w for _, _, w in hlo_collective_ops(hlo_text, n_shards))


# -- trace-time reporting --------------------------------------------------- #
def account_collective(op: str, raw_bytes: int, wire_bytes: float,
                       recorder=None):
    """Report one collective's static volume to the (active) recorder.

    Called at *trace time* from inside jitted step functions — shapes
    are static there, so the numbers are exact per executed step; the
    host loop turns the per-step gauges into cumulative counters.
    Gauges set (per step):
      ``collective/{op}_bytes``       raw (uncompressed) volume
      ``collective/{op}_wire_bytes``  on-the-wire (post-compression) volume
      ``collective/bytes_per_step``   running total of raw volume
      ``collective/wire_bytes_per_step``  running total of wire volume
    """
    if recorder is None:
        from .recorder import get_recorder
        recorder = get_recorder()
    if not recorder.enabled:
        return
    recorder.gauge(f"collective/{op}_bytes", float(raw_bytes))
    recorder.gauge(f"collective/{op}_wire_bytes", float(wire_bytes))
    recorder.gauge("collective/bytes_per_step",
                   recorder.gauge_value("collective/bytes_per_step")
                   + float(raw_bytes))
    recorder.gauge("collective/wire_bytes_per_step",
                   recorder.gauge_value("collective/wire_bytes_per_step")
                   + float(wire_bytes))
