"""Proxy-regression sentinel over the goodput/bench trajectory.

The ROADMAP's standing constraint — the hardware bench backend has been
unreachable since BENCH_r02 — makes the CPU proxies (smoke scripts,
and now the goodput ledger) the ONLY performance signal this repo has.
A proxy trajectory nobody checks rots silently; this module is the
check, run by ``scripts/goodput_smoke.py`` in CI.

Discipline mirrors the graftlint baseline: a proxy metric may only
regress past its committed bound when the baseline entry carries a
**justification** string — an undocumented regression fails, a
justified one is reported as *waived*, and a stale bound (the metric
is now far better than the baseline demands) is surfaced so the bound
gets ratcheted.

Two input shapes, one trajectory schema (``{"source", "metrics"}``
rows):

  * normalized BENCH rounds (``scripts/bench_trend.py --json`` /
    ``normalize_rounds``) — heterogeneous r01–r10 docs flattened to
    dotted metric keys;
  * goodput-ledger snapshots (:meth:`~.goodput.GoodputLedger.snapshot`)
    — per-bucket device-seconds, folded to **fractions of owned time**
    so the bounds are load-independent.

Baseline JSON (committed at ``artifacts/goodput_baseline.json``)::

    {
      "metrics": {
        "ledger:train/goodput_fraction": {"min": 0.45},
        "ledger:train/buckets.checkpoint_blocking": {"max": 0.30},
        "bench:r09/decode_throughput.speedup": {
            "min": 1.2, "justification": null}
      },
      "buckets": {"input_stall": {"max_fraction": 0.5}}
    }

``metrics`` bounds name one trajectory point; ``buckets`` bounds apply
to EVERY ledger row (a goodput bucket growing past its recorded
baseline fails CI — the acceptance bar).  Change-point check: a metric
with ≥ 3 points in its series is also flagged when the newest point
jumps more than ``change_factor`` × the prior spread away from the
prior mean — the cheap CUSUM-ish tripwire for drifts no bound was
written for.  Counters land under ``regress/*`` (registered in
docs/observability.md).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from .goodput import BUCKETS


def ledger_row(name: str, snapshot: Dict[str, Any]) -> Dict[str, Any]:
    """One trajectory row from a ledger snapshot: buckets as fractions
    of owned time (load-independent), plus the goodput fraction and
    the conservation error itself — a wiring bug that breaks the
    conservation law should trip the sentinel too."""
    owned = float(snapshot.get("owned_s", 0.0)) or 1.0
    metrics = {"goodput_fraction":
               float(snapshot.get("goodput_fraction", 0.0)),
               "conservation_error":
               float(snapshot.get("conservation_error", 0.0)),
               "owned_s": float(snapshot.get("owned_s", 0.0))}
    for b in BUCKETS:
        metrics[f"buckets.{b}"] = \
            float(snapshot.get("buckets", {}).get(b, 0.0)) / owned
    return {"source": f"ledger:{name}", "metrics": metrics}


def bench_rows(normalized: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Trajectory rows from ``bench_trend.normalize_rounds`` output.
    FAILED rounds keep an empty row — the gap is part of the record."""
    return [{"source": f"bench:r{row['round']:02d}",
             "mode": row.get("mode"),
             "metrics": dict(row.get("metrics") or {})}
            for row in normalized]


def _series(rows: List[Dict[str, Any]], key: str) -> List[float]:
    """Chronological values of one dotted metric across every row whose
    source family matches the key's prefix (``bench:*/x`` collects x
    from every bench row; an exact source only from that row)."""
    fam, _, metric = key.partition("/")
    out = []
    for row in rows:
        src = row.get("source", "")
        if src == fam or (fam.endswith("*") and
                          src.startswith(fam[:-1])):
            v = row.get("metrics", {}).get(metric)
            if isinstance(v, (int, float)):
                out.append(float(v))
    return out


class Finding:
    """One sentinel verdict.  ``severity`` is ``fail`` (undocumented
    regression — CI red), ``waived`` (regressed, but the baseline
    entry carries a justification), or ``info`` (stale bound /
    change-point advisory)."""

    def __init__(self, severity: str, key: str, message: str,
                 value: Optional[float] = None,
                 bound: Optional[float] = None):
        self.severity = severity
        self.key = key
        self.message = message
        self.value = value
        self.bound = bound

    def as_dict(self) -> Dict[str, Any]:
        return {"severity": self.severity, "key": self.key,
                "message": self.message, "value": self.value,
                "bound": self.bound}

    def render(self) -> str:
        return f"[{self.severity}] {self.key}: {self.message}"


def check(rows: List[Dict[str, Any]], baseline: Dict[str, Any],
          change_factor: float = 4.0,
          stale_margin: float = 0.5) -> List[Finding]:
    """Apply the committed baseline to a trajectory.  Returns every
    finding; CI fails iff any has severity ``fail`` (see
    :func:`gate`)."""
    findings: List[Finding] = []
    by_source = {row.get("source"): row for row in rows}

    # -- explicit per-metric bounds ------------------------------------ #
    for key, spec in (baseline.get("metrics") or {}).items():
        fam, _, metric = key.partition("/")
        row = by_source.get(fam)
        if row is None:
            findings.append(Finding(
                "info", key, "no trajectory row for this source — "
                "bound not evaluated"))
            continue
        v = row.get("metrics", {}).get(metric)
        if not isinstance(v, (int, float)):
            findings.append(Finding(
                "info", key, f"metric absent from {fam} — bound not "
                "evaluated (schema drift?)"))
            continue
        just = spec.get("justification")
        lo, hi = spec.get("min"), spec.get("max")
        if lo is not None and v < float(lo):
            findings.append(Finding(
                "waived" if just else "fail", key,
                f"regressed below committed floor ({v:g} < {lo:g})"
                + (f"; justified: {just}" if just else
                   " with no committed justification"),
                value=float(v), bound=float(lo)))
        elif hi is not None and v > float(hi):
            findings.append(Finding(
                "waived" if just else "fail", key,
                f"grew past committed ceiling ({v:g} > {hi:g})"
                + (f"; justified: {just}" if just else
                   " with no committed justification"),
                value=float(v), bound=float(hi)))
        else:
            # stale-bound ratchet: the graftlint discipline in the
            # other direction — a bound the reality has left far
            # behind stops meaning anything
            if lo is not None and float(lo) > 0 \
                    and v > float(lo) * (1.0 + stale_margin):
                findings.append(Finding(
                    "info", key,
                    f"bound is stale: {v:g} beats floor {lo:g} by "
                    f">{stale_margin:.0%}; ratchet it",
                    value=float(v), bound=float(lo)))
            if hi is not None and float(hi) > 0 \
                    and v < float(hi) * (1.0 - stale_margin):
                findings.append(Finding(
                    "info", key,
                    f"bound is stale: {v:g} is under ceiling {hi:g} "
                    f"by >{stale_margin:.0%}; ratchet it",
                    value=float(v), bound=float(hi)))

    # -- bucket ceilings over every ledger row ------------------------- #
    for bucket, spec in (baseline.get("buckets") or {}).items():
        cap = spec.get("max_fraction")
        if cap is None:
            continue
        just = spec.get("justification")
        for row in rows:
            src = row.get("source", "")
            if not src.startswith("ledger:"):
                continue
            v = row.get("metrics", {}).get(f"buckets.{bucket}")
            if isinstance(v, (int, float)) and v > float(cap):
                findings.append(Finding(
                    "waived" if just else "fail",
                    f"{src}/buckets.{bucket}",
                    f"badput bucket grew past its recorded baseline "
                    f"({v:.3f} > {cap:g} of owned time)"
                    + (f"; justified: {just}" if just else ""),
                    value=float(v), bound=float(cap)))

    # -- change-point advisory over multi-point series ------------------ #
    for key in (baseline.get("watch") or []):
        pts = _series(rows, key)
        if len(pts) < 3:
            continue
        prior, latest = pts[:-1], pts[-1]
        mean = sum(prior) / len(prior)
        spread = max(prior) - min(prior)
        if spread <= 0:
            spread = abs(mean) * 0.01 or 1e-9
        if abs(latest - mean) > change_factor * spread:
            findings.append(Finding(
                "info", key,
                f"change-point: latest {latest:g} departs the prior "
                f"mean {mean:g} by >{change_factor:g}x the prior "
                f"spread {spread:g}",
                value=latest, bound=mean))
    return findings


def gate(findings: List[Finding]) -> bool:
    """True when the trajectory passes (no undocumented regression)."""
    return not any(f.severity == "fail" for f in findings)


def load_baseline(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)
