"""Pluggable sinks for Recorder step records.

A sink is anything with ``emit(record: dict)`` (and optionally
``close()``).  Three are provided:

  :class:`JsonlSink`        one JSON object per line — the machine-
                            readable export ``scripts/trace_summary.py
                            steps`` renders, and the cheapest thing to
                            ship off-host
  :class:`InMemorySink`     keeps records in a list — for tests and
                            notebook inspection
  :class:`TensorBoardSink`  forwards span durations and scalars through
                            the existing tfevents
                            :class:`~bigdl_tpu.visualization.event_writer.EventWriter`
                            so telemetry lands next to the Loss curves
"""
from __future__ import annotations

import json
import math
import os
import re
import threading
from typing import Any, Dict, List, Optional


class Sink:
    """Interface marker; subclasses implement emit/close."""

    def emit(self, record: Dict[str, Any]):
        raise NotImplementedError

    def close(self):
        pass


class InMemorySink(Sink):
    """Append records to ``self.records`` (thread-safe)."""

    def __init__(self):
        self.records: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    def emit(self, record):
        with self._lock:
            self.records.append(record)

    def steps(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r for r in self.records if r.get("type") == "step"]


class JsonlSink(Sink):
    """One JSON object per line, flushed every ``flush_every`` records
    (and on close) so a crashed run keeps its telemetry tail."""

    def __init__(self, path: str, flush_every: int = 20):
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self.path = path
        self._f = open(path, "a")
        self._lock = threading.Lock()
        self._since_flush = 0
        self.flush_every = max(int(flush_every), 1)

    def emit(self, record):
        line = json.dumps(record, default=_json_default)
        with self._lock:
            self._f.write(line + "\n")
            self._since_flush += 1
            if self._since_flush >= self.flush_every:
                self._f.flush()
                self._since_flush = 0

    def flush(self):
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._since_flush = 0

    def close(self):
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                self._f.close()


class TensorBoardSink(Sink):
    """Write span durations (milliseconds, under ``telemetry/span_ms/``)
    and step scalars (under ``telemetry/``) as tfevents scalars.

    Accepts a log dir (an :class:`EventWriter` is created) or any object
    with ``add_scalar(tag, value, step)`` — e.g. an existing
    :class:`~bigdl_tpu.visualization.TrainSummary`.
    """

    def __init__(self, writer_or_dir, prefix: str = "telemetry"):
        if isinstance(writer_or_dir, str):
            from ..visualization.event_writer import EventWriter
            writer_or_dir = EventWriter(writer_or_dir)
            self._owned = True
        else:
            self._owned = False
        self.writer = writer_or_dir
        self.prefix = prefix.rstrip("/")

    def emit(self, record):
        step = record.get("step")
        if record.get("type") != "step" or step is None:
            return
        add = self.writer.add_scalar
        for name, secs in record.get("spans", {}).items():
            add(f"{self.prefix}/span_ms/{name}", secs * 1e3, step)
        for name, v in record.get("scalars", {}).items():
            if isinstance(v, (int, float)):
                add(f"{self.prefix}/{name}", float(v), step)

    def flush(self):
        fl = getattr(self.writer, "flush", None)
        if fl is not None:
            fl()

    def close(self):
        if self._owned:
            self.writer.close()


def _json_default(v):
    """Last-resort leaf encoder: device scalars and numpy types float()
    cleanly; anything else degrades to repr instead of killing the run."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return repr(v)


# -- Prometheus exposition rendering -------------------------------------- #
# Not a Sink: Prometheus *pulls*, so the /metrics endpoint
# (observability.http) renders the Recorder's current snapshot per
# scrape instead of pushing records anywhere.

_PROM_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def prometheus_name(name: str, namespace: str = "bigdl") -> str:
    """Sanitize a recorder metric name into a legal Prometheus metric
    name ``[a-zA-Z_:][a-zA-Z0-9_:]*`` under ``namespace``."""
    out = _PROM_NAME_BAD.sub("_", str(name))
    if out and out[0].isdigit():
        out = "_" + out
    return f"{namespace}_{out}" if namespace else out


def prometheus_escape_help(text: str) -> str:
    r"""Escape a HELP line: ``\`` -> ``\\`` and newline -> ``\n``."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def prometheus_escape_label(value: str) -> str:
    r"""Escape a label value: ``\``, ``"`` and newline."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_value(v) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def _prom_labels(labels: Optional[Dict[str, Any]]) -> str:
    """``{k="v",...}`` sample-label block; empty string for no labels."""
    if not labels:
        return ""
    inner = ",".join(f'{k}="{prometheus_escape_label(v)}"'
                     for k, v in labels.items())
    return "{" + inner + "}"


def _prom_group(groups: Dict[str, Dict[str, Any]], metric: str,
                help_text: str, type_text: str) -> List[str]:
    """The sample-line list for ``metric``, creating its HELP/TYPE group
    on first sight — exposition format wants ONE header per metric even
    when several labeled sources (fleet jobs) contribute samples."""
    g = groups.get(metric)
    if g is None:
        g = groups[metric] = {"help": help_text, "type": type_text,
                              "lines": []}
    return g["lines"]


def _collect_prometheus(recorder, namespace: str,
                        labels: Optional[Dict[str, Any]],
                        groups: Dict[str, Dict[str, Any]]) -> None:
    """Fold one recorder's snapshot into ``groups`` (ordered metric →
    header + sample lines), tagging every sample with ``labels``."""
    snap = recorder.snapshot()
    lab = dict(labels or {})

    for name in sorted(snap["counters"]):
        metric = prometheus_name(name, namespace)
        if not metric.endswith("_total"):
            metric += "_total"
        _prom_group(groups, metric,
                    prometheus_escape_help("counter " + name),
                    "counter").append(
            f"{metric}{_prom_labels(lab)} "
            f"{_prom_value(snap['counters'][name])}")

    queue_depths = {}
    for name in sorted(snap["gauges"]):
        if name.startswith("serving.queue_depth."):
            queue_depths[name[len("serving.queue_depth."):]] = \
                snap["gauges"][name]
            continue
        metric = prometheus_name(name, namespace)
        _prom_group(groups, metric,
                    prometheus_escape_help("gauge " + name),
                    "gauge").append(
            f"{metric}{_prom_labels(lab)} "
            f"{_prom_value(snap['gauges'][name])}")
    if queue_depths:
        metric = prometheus_name("serving.queue_depth", namespace)
        lines = _prom_group(groups, metric, "rows queued per model",
                            "gauge")
        for model in sorted(queue_depths):
            lines.append(
                f"{metric}{_prom_labels({**lab, 'model': model})} "
                f"{_prom_value(queue_depths[model])}")

    hist_buckets = getattr(recorder, "hist_buckets", None)
    for name in sorted(recorder.hist_names()):
        summ = recorder.hist_summary(name)
        if not summ:
            continue
        metric = prometheus_name(name, namespace)
        buckets = hist_buckets(name) if hist_buckets is not None else None
        if buckets is not None and buckets[0] is not None:
            # opted-in bucket spec: native TYPE histogram with
            # cumulative le-labeled buckets counted at observe() time,
            # so +Inf == _count exactly and external Prometheus can
            # compute its own quantiles
            bounds, bins = buckets
            lines = _prom_group(groups, metric,
                                prometheus_escape_help("histogram "
                                                       + name),
                                "histogram")
            cum = 0
            for le, n in zip(bounds, bins):
                cum += n
                lines.append(
                    f"{metric}_bucket"
                    f"{_prom_labels({**lab, 'le': _prom_value(le)})} "
                    f"{cum}")
            lines.append(
                f"{metric}_bucket{_prom_labels({**lab, 'le': '+Inf'})} "
                f"{cum + bins[-1]}")
        else:
            lines = _prom_group(groups, metric,
                                prometheus_escape_help("histogram "
                                                       + name),
                                "summary")
            for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                if key in summ:
                    lines.append(
                        f"{metric}{_prom_labels({**lab, 'quantile': q})} "
                        f"{_prom_value(summ[key])}")
        lines.append(f"{metric}_sum{_prom_labels(lab)} "
                     f"{_prom_value(summ['mean'] * summ['count'])}")
        lines.append(f"{metric}_count{_prom_labels(lab)} "
                     f"{int(summ['count'])}")


def _emit_prometheus(groups: Dict[str, Dict[str, Any]]) -> str:
    lines: List[str] = []
    for metric, g in groups.items():
        lines.append(f"# HELP {metric} {g['help']}")
        lines.append(f"# TYPE {metric} {g['type']}")
        lines.extend(g["lines"])
    return "\n".join(lines) + "\n" if lines else ""


def render_prometheus(recorder, namespace: str = "bigdl",
                      labels: Optional[Dict[str, Any]] = None) -> str:
    """Render ``recorder``'s counters, gauges and pending histograms as
    Prometheus text exposition format (version 0.0.4).

    Counters keep their monotonic semantics (``_total`` suffix added
    when missing), gauges map 1:1, and each histogram renders as a
    ``summary``: ``{quantile="..."}`` samples over the bounded recent
    window plus exact ``_sum``/``_count``.  Per-model
    ``serving.queue_depth.<model>`` gauges fold into ONE metric with a
    ``model`` label so a fleet of models can't explode the metric
    namespace.  ``labels`` tags every sample (e.g. ``{"job": name}``)."""
    groups: Dict[str, Dict[str, Any]] = {}
    _collect_prometheus(recorder, namespace, labels, groups)
    return _emit_prometheus(groups)


def render_prometheus_multi(sources, namespace: str = "bigdl") -> str:
    """One exposition over several recorders — the fleet's aggregated
    ``/metrics``.  ``sources`` is an iterable of ``(labels, recorder)``
    pairs (``labels`` None for the unlabeled base source); a metric
    emitted by several sources renders under ONE ``HELP``/``TYPE``
    header with one labeled sample per source, so per-job ``fleet/*``
    and ``elastic/*`` counters stay distinct series instead of
    colliding."""
    groups: Dict[str, Dict[str, Any]] = {}
    for labels, recorder in sources:
        _collect_prometheus(recorder, namespace, labels, groups)
    return _emit_prometheus(groups)


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Parse a JsonlSink file back into records (bad lines skipped)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out
