"""bigdl_tpu.observability — unified training telemetry.

The reference ships a driver-side ``optim/Metrics.scala`` that times
data-fetch / compute / aggregate phases every iteration and surfaces
them in the Spark UI.  This package is that idea grown into framework
surface for the TPU rebuild:

  * :class:`Recorder` — thread-safe counters, gauges, span timers and
    per-step histograms, folded into one *step record* per training
    iteration.  Disabled recorders are near-zero-cost no-ops, so the
    instrumentation can stay compiled into every hot path.
  * Pluggable sinks (:mod:`~bigdl_tpu.observability.sinks`): JSONL
    file, in-memory (tests), and TensorBoard via the existing
    :class:`~bigdl_tpu.visualization.event_writer.EventWriter`.
  * Collective-volume accounting
    (:mod:`~bigdl_tpu.observability.collectives`): bytes-on-wire per
    step, pre/post compression, from static shapes or partitioned HLO.
  * Live introspection (:mod:`~bigdl_tpu.observability.http`): a
    stdlib HTTP daemon serving ``/metrics`` (Prometheus), ``/healthz``
    and ``/records`` — ``serve_metrics(port)`` on the trainers and the
    serving engine.
  * Training health (:mod:`~bigdl_tpu.observability.health`): NaN/Inf
    and loss-spike sentinels with warn/record/raise/rollback policies,
    a stall-and-straggler watchdog, and a crash flight recorder that
    dumps the recent-record ring on unhandled exception / SIGTERM.
  * Fleet telemetry plane: bounded time series with windowed reducers
    (:mod:`~bigdl_tpu.observability.timeseries`, opt-in via
    ``Recorder(keep_series=N)``, served at ``/series``), a multi-
    endpoint scrape aggregator re-exposing one fleet ``/metrics`` with
    ``source``/``stale`` labels
    (:mod:`~bigdl_tpu.observability.aggregate`), and declarative SLOs
    with dual-window error-budget burn-rate alerts
    (:mod:`~bigdl_tpu.observability.slo`).
  * Cost/memory attribution (:mod:`~bigdl_tpu.observability.profile`):
    XLA compile-time FLOPs/HBM capture feeding per-step ``perf/mfu``,
    ``perf/hbm_bw_util`` and ``mem/peak_hbm_bytes`` scalars, a device
    peak-spec table, live device-memory gauges, and per-request trace
    IDs with Chrome-trace/Perfetto export via ``/trace``.

  * Causal trace spine (:mod:`~bigdl_tpu.observability.tracing` +
    :mod:`~bigdl_tpu.observability.context`): one W3C-shaped
    ``TraceContext`` flowing admission → failover → decode on the
    serve side and step → checkpoint writer → elastic transitions on
    the train side, autoscale decisions causally linked to the SLO
    samples that triggered them and the pool moves they caused, a
    merged multi-subsystem Perfetto export on ONE clock domain
    (``context.trace_now``), and per-trace critical-path latency
    attribution (``scripts/trace_summary.py critical-path``).

Every span is also emitted as a ``jax.profiler.TraceAnnotation`` so the
host-side phase structure lines up with device events in a TensorBoard /
Perfetto trace, and ``Recorder.trace_every(n)`` captures an on-demand
XLA profile without touching training code.

Quick start::

    from bigdl_tpu.observability import Recorder, JsonlSink

    rec = Recorder(sinks=[JsonlSink("/tmp/telemetry.jsonl")])
    opt.set_telemetry(rec)          # LocalOptimizer / DistriOptimizer
    ...
    # python scripts/trace_summary.py steps /tmp/telemetry.jsonl
"""
from __future__ import annotations

from .context import TraceContext, trace_now
from .tracing import (SpanStore, Tracer, critical_path, get_tracer,
                      merge_perfetto, note_actuation, set_tracer,
                      spans_from_chrome, take_actuation)
from .recorder import Recorder, get_recorder, set_recorder, null_recorder
from .sinks import (InMemorySink, JsonlSink, Sink, TensorBoardSink,
                    render_prometheus, render_prometheus_multi)
from .http import IntrospectionServer
from .health import (DivergenceError, FlightRecorder, HealthMonitor,
                     StallWatchdog)
from .timeseries import MetricSeries, SeriesStore
from .aggregate import MetricsAggregator, parse_prometheus
from .goodput import (BUCKETS, GoodputLedger, OwnershipLedger,
                      ledger_phase, rollup)
from .slo import SLObjective, SLOEngine, default_objectives
from . import collectives
from . import health
from . import profile

__all__ = [
    "TraceContext", "trace_now", "Tracer", "SpanStore",
    "get_tracer", "set_tracer", "note_actuation", "take_actuation",
    "merge_perfetto", "critical_path", "spans_from_chrome",
    "Recorder", "get_recorder", "set_recorder", "null_recorder",
    "Sink", "InMemorySink", "JsonlSink", "TensorBoardSink",
    "render_prometheus", "render_prometheus_multi", "IntrospectionServer",
    "DivergenceError", "FlightRecorder", "HealthMonitor", "StallWatchdog",
    "BUCKETS", "GoodputLedger", "OwnershipLedger", "ledger_phase",
    "rollup",
    "MetricSeries", "SeriesStore", "MetricsAggregator",
    "parse_prometheus", "SLObjective", "SLOEngine", "default_objectives",
    "collectives", "health", "profile",
]
