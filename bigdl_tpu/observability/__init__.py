"""bigdl_tpu.observability — unified training telemetry.

The reference ships a driver-side ``optim/Metrics.scala`` that times
data-fetch / compute / aggregate phases every iteration and surfaces
them in the Spark UI.  This package is that idea grown into framework
surface for the TPU rebuild:

  * :class:`Recorder` — thread-safe counters, gauges, span timers and
    per-step histograms, folded into one *step record* per training
    iteration.  Disabled recorders are near-zero-cost no-ops, so the
    instrumentation can stay compiled into every hot path.
  * Pluggable sinks (:mod:`~bigdl_tpu.observability.sinks`): JSONL
    file, in-memory (tests), and TensorBoard via the existing
    :class:`~bigdl_tpu.visualization.event_writer.EventWriter`.
  * Collective-volume accounting
    (:mod:`~bigdl_tpu.observability.collectives`): bytes-on-wire per
    step, pre/post compression, from static shapes or partitioned HLO.

Every span is also emitted as a ``jax.profiler.TraceAnnotation`` so the
host-side phase structure lines up with device events in a TensorBoard /
Perfetto trace, and ``Recorder.trace_every(n)`` captures an on-demand
XLA profile without touching training code.

Quick start::

    from bigdl_tpu.observability import Recorder, JsonlSink

    rec = Recorder(sinks=[JsonlSink("/tmp/telemetry.jsonl")])
    opt.set_telemetry(rec)          # LocalOptimizer / DistriOptimizer
    ...
    # python scripts/trace_summary.py steps /tmp/telemetry.jsonl
"""
from __future__ import annotations

from .recorder import Recorder, get_recorder, set_recorder, null_recorder
from .sinks import (InMemorySink, JsonlSink, Sink, TensorBoardSink)
from . import collectives

__all__ = [
    "Recorder", "get_recorder", "set_recorder", "null_recorder",
    "Sink", "InMemorySink", "JsonlSink", "TensorBoardSink",
    "collectives",
]
