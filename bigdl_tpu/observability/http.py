"""Live introspection server: /metrics, /healthz, /records.

The reference BigDL surfaces training state through Spark's live UI;
the Recorder (PR 1) only *writes*.  :class:`IntrospectionServer` is the
read side — a stdlib ``http.server`` daemon thread (no new
dependencies) rendering the Recorder a scraper can poll while the job
runs:

  ``/metrics``   Prometheus text exposition
                 (:func:`~bigdl_tpu.observability.sinks.render_prometheus`):
                 counters, gauges, histogram summaries with quantiles
  ``/healthz``   JSON liveness — last-step index and age, the stall
                 watchdog's verdict and budget, writer-queue depths
                 (dataloader / checkpoint in-flight / serving queues),
                 serving shed rate, sentinel event counts.  HTTP 200
                 when healthy, 503 when stalled or diverged, so a
                 k8s-style probe needs no JSON parsing
  ``/records``   the last-N records from the Recorder's ring
                 (``?n=20&type=step``) — the live tail JSONL sinks only
                 give you after the fact
  ``/trace``     Chrome-trace/Perfetto JSON of recent per-request span
                 timelines (serving engines attach their trace ring via
                 ``trace_source``; curl it to a file and load in
                 ui.perfetto.dev)
  ``/series``    windowed time-series points
                 (``?name=decode/ttft_ms/p99&window=300``) from a
                 Recorder's ``keep_series=`` store or an aggregator's —
                 no name lists the available series
  ``/goodput``   the device-second attribution document — a job
                 recorder's attached
                 :class:`~bigdl_tpu.observability.goodput.GoodputLedger`
                 snapshot, or (on an aggregator's server) the fleet
                 roll-up with per-bucket badput and pool idle

Attach with ``serve_metrics(port)`` on ``Optimizer`` / ``SpmdTrainer``
/ ``ServingEngine``, or standalone::

    from bigdl_tpu.observability.http import IntrospectionServer
    srv = IntrospectionServer(rec, port=9100).start()   # port=0: ephemeral
    # curl localhost:9100/metrics
    srv.stop()

Handlers only ever read snapshots under the Recorder's lock, so a
scrape can't block or corrupt the step loop; ``ThreadingHTTPServer``
keeps one slow scraper from starving the next probe.
"""
from __future__ import annotations

import errno
import json
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, urlparse

from .sinks import (_json_default, render_prometheus,
                    render_prometheus_multi)
from ..utils.retry import RetryPolicy


def _finite_json(obj):
    """Strict-JSON encode: non-finite floats become the strings "NaN" /
    "Inf" / "-Inf".  json.dumps would emit the bare token ``NaN``
    (invalid RFC 8259) — and a NaN loss in the ring is EXACTLY the
    record a health client wants to read, so it must stay parseable."""
    def walk(v):
        if isinstance(v, float) and not math.isfinite(v):
            if math.isnan(v):
                return "NaN"
            return "Inf" if v > 0 else "-Inf"
        if isinstance(v, dict):
            return {k: walk(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [walk(x) for x in v]
        return v
    return json.dumps(walk(obj), default=_json_default)


def _filter_trace(body, trace_id: str):
    """Restrict a Chrome-trace document to one trace id: keep the
    ``"M"`` metadata rows (process/thread names) and every B/E event
    whose ``args.trace_id`` matches.  A body that isn't Chrome-trace
    JSON passes through untouched — the filter must never 500 the
    endpoint over an exotic trace_source."""
    doc = body
    if isinstance(doc, (str, bytes)):
        try:
            doc = json.loads(doc)
        except ValueError:
            return body
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return body
    events = [ev for ev in doc["traceEvents"]
              if ev.get("ph") == "M"
              or (ev.get("args") or {}).get("trace_id") == trace_id
              or (ev.get("ph") == "E" and "args" not in ev)]
    # an E event carries no args; keep it only when its B survived —
    # pair per (pid, tid) stack to drop ends of filtered-out spans
    kept, depth = [], {}
    for ev in events:
        key = (ev.get("pid"), ev.get("tid"))
        if ev.get("ph") == "B":
            depth[key] = depth.get(key, 0) + 1
            kept.append(ev)
        elif ev.get("ph") == "E" and "args" not in ev:
            if depth.get(key, 0) > 0:
                depth[key] -= 1
                kept.append(ev)
        else:
            kept.append(ev)
    out = dict(doc)
    out["traceEvents"] = kept
    return out


class IntrospectionServer:
    """One Recorder's live read surface; start()/stop() lifecycle."""

    def __init__(self, recorder, port: int = 0, host: str = "127.0.0.1",
                 watchdog=None, monitor=None, namespace: str = "bigdl",
                 records_default: int = 50, trace_source=None,
                 bind_retries: int = 4, metrics_source=None,
                 healthz_source=None, series_source=None,
                 goodput_source=None):
        self.recorder = recorder
        self.host = host
        self.port = int(port)           # 0 -> ephemeral, bound in start()
        self.watchdog = watchdog
        self.monitor = monitor
        self.namespace = namespace
        self.records_default = int(records_default)
        # zero-arg callable returning a Chrome-trace JSON string (e.g.
        # ServingEngine.dump_chrome_trace); None -> /trace is 404
        self.trace_source = trace_source
        self.bind_retries = int(bind_retries)
        # overrides for a non-Recorder-backed surface (the fleet
        # MetricsAggregator): zero-arg callables replacing the /metrics
        # body and the /healthz payload
        self.metrics_source = metrics_source
        self.healthz_source = healthz_source
        # a SeriesStore served at /series; defaults to the recorder's
        # own (Recorder(keep_series=N)), resolved per request so a
        # late-attached store is picked up
        self.series_source = series_source
        # zero-arg callable returning the goodput attribution document
        # (MetricsAggregator.goodput_doc, or a ledger's snapshot);
        # defaults to the recorder's own attached ledger
        self.goodput_source = goodput_source
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # fleet mode: named (recorder, watchdog, monitor) jobs this
        # server aggregates next to its own recorder.  Plain dict with
        # whole-value assignment only (GIL-atomic); scrapes iterate a
        # dict() copy, so registration needs no lock of its own
        self._jobs: Dict[str, Dict[str, Any]] = {}

    # -- lifecycle --------------------------------------------------------- #
    def start(self) -> "IntrospectionServer":
        if self._server is not None:
            return self
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):       # no per-scrape stderr spam
                pass

            def do_GET(self):
                try:
                    outer._route(self)
                except (BrokenPipeError, ConnectionResetError):
                    pass                # scraper went away mid-response
                except Exception as e:  # introspection must never crash
                    try:
                        self.send_error(500, repr(e))
                    except Exception:
                        pass

        def bind():
            from .. import faults as faultplane
            faultplane.inject("http.bind", self.recorder)
            return ThreadingHTTPServer((self.host, self.port), Handler)

        # a fixed port just vacated by a predecessor (serve_metrics
        # reconfiguration, a supervisor restart) can sit in TIME_WAIT
        # for a beat: EADDRINUSE is the one transient bind error worth
        # retrying — anything else (bad host, privileged port) is fatal
        srv = RetryPolicy(
            max_attempts=self.bind_retries, base=0.1, max_delay=1.0,
            classify=lambda e: (isinstance(e, OSError)
                                and e.errno == errno.EADDRINUSE),
            recorder_fn=lambda: self.recorder, name="http.bind",
        ).run(bind)
        srv.daemon_threads = True
        self._server = srv
        self.port = srv.server_address[1]
        self._thread = threading.Thread(target=srv.serve_forever,
                                        daemon=True,
                                        name=f"introspection:{self.port}")
        self._thread.start()
        return self

    def stop(self):
        srv, self._server = self._server, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    # -- fleet job registration -------------------------------------------- #
    def add_job(self, name: str, recorder, watchdog=None,
                monitor=None) -> "IntrospectionServer":
        """Aggregate ``recorder`` into this server under a
        ``job="<name>"`` label on every /metrics sample and a per-job
        verdict in /healthz (the aggregated ``ok`` is the worst-of).
        ``watchdog``/``monitor`` may be the object itself or a zero-arg
        callable resolved per scrape — a supervisor builds its watchdog
        lazily, after the job is registered."""
        self._jobs[str(name)] = {"recorder": recorder,
                                 "watchdog": watchdog,
                                 "monitor": monitor}
        return self

    def remove_job(self, name: str):
        self._jobs.pop(str(name), None)

    # -- routing ----------------------------------------------------------- #
    def _route(self, h: BaseHTTPRequestHandler):
        parsed = urlparse(h.path)
        if parsed.path == "/metrics":
            if self.metrics_source is not None:
                body = self.metrics_source()
            else:
                jobs = dict(self._jobs)
                if jobs:
                    sources = [(None, self.recorder)]
                    sources += [({"job": name}, j["recorder"])
                                for name, j in jobs.items()]
                    body = render_prometheus_multi(sources,
                                                   self.namespace)
                else:
                    body = render_prometheus(self.recorder,
                                             self.namespace)
            self._reply(h, 200, body,
                        "text/plain; version=0.0.4; charset=utf-8")
        elif parsed.path == "/healthz":
            payload = (self.healthz_source() if self.healthz_source
                       is not None else self.healthz())
            self._reply(h, 200 if payload["ok"] else 503,
                        _finite_json(payload), "application/json")
        elif parsed.path == "/series":
            store = self.series_source
            if store is None:
                store = getattr(self.recorder, "series", None)
            if store is None:
                h.send_error(404, "no series store attached "
                                  "(Recorder(keep_series=N) or an "
                                  "aggregator expose one)")
                return
            q = parse_qs(parsed.query)
            name = q["name"][0] if q.get("name") else None
            window = float(q["window"][0]) if q.get("window") else None
            if name is None:
                payload = {"names": store.names()}
            else:
                payload = {"name": name, "window": window,
                           "points": [[t, v] for t, v in
                                      store.points(name, window)],
                           "summary": store.summary(name, window)}
            self._reply(h, 200, _finite_json(payload),
                        "application/json")
        elif parsed.path == "/goodput":
            if self.goodput_source is not None:
                payload = self.goodput_source()
            else:
                get_led = getattr(self.recorder, "get_ledger", None)
                led = get_led() if get_led is not None else None
                if led is None:
                    h.send_error(404, "no goodput ledger attached "
                                      "(rec.set_ledger(GoodputLedger) "
                                      "or an aggregator expose one)")
                    return
                payload = led.snapshot()
            self._reply(h, 200, _finite_json(payload),
                        "application/json")
        elif parsed.path == "/records":
            q = parse_qs(parsed.query)
            n = int(q["n"][0]) if q.get("n") else self.records_default
            rec_type = q["type"][0] if q.get("type") else None
            recs = self.recorder.recent_records(n, rec_type=rec_type)
            self._reply(h, 200, _finite_json(recs), "application/json")
        elif parsed.path == "/trace":
            if self.trace_source is None:
                h.send_error(404, "no per-request trace source attached "
                                  "(serving engines expose one)")
            else:
                body = self.trace_source()
                q = parse_qs(parsed.query)
                want = q["trace_id"][0] if q.get("trace_id") else None
                if want is not None:
                    body = _filter_trace(body, want)
                if not isinstance(body, str):
                    body = json.dumps(body, default=_json_default)
                self._reply(h, 200, body, "application/json")
        else:
            h.send_error(404, "try /metrics, /healthz, /records, "
                              "/series, /goodput or /trace")

    @staticmethod
    def _reply(h: BaseHTTPRequestHandler, code: int, body: str,
               content_type: str):
        data = body.encode("utf-8")
        h.send_response(code)
        h.send_header("Content-Type", content_type)
        h.send_header("Content-Length", str(len(data)))
        h.end_headers()
        h.wfile.write(data)

    # -- health verdict ----------------------------------------------------- #
    @staticmethod
    def _resolve(obj):
        """A watchdog/monitor registered as a zero-arg provider (fleet
        jobs build theirs lazily) resolves at scrape time."""
        return obj() if callable(obj) else obj

    def _verdict(self, rec, watchdog, monitor) -> Dict[str, Any]:
        """One source's healthz payload: liveness + queue depths +
        sentinel state.  ``ok`` is False when the watchdog says stalled
        or the monitor has tripped a fatal condition."""
        snap = rec.snapshot()
        gauges, counters = snap["gauges"], snap["counters"]
        stalled = bool(gauges.get("health/stalled", 0))
        budget = None
        watchdog = self._resolve(watchdog)
        monitor = self._resolve(monitor)
        if watchdog is not None:
            stalled = watchdog.check_once()
            budget = watchdog.budget()
        diverged = (monitor is not None and not monitor.healthy)
        out: Dict[str, Any] = {
            "ok": not (stalled or diverged),
            "stalled": stalled,
            "diverged": diverged,
            "last_step": rec.last_step(),
            "step_age_s": rec.step_age(),
            "stall_budget_s": budget,
            "health_events": counters.get("health/events", 0),
            "writer_queue_depth": {
                k: v for k, v in gauges.items()
                if k in ("dataloader/queue_depth", "checkpoint/in_flight")
                or k.startswith("serving.queue_depth.")},
        }
        requests = counters.get("serving.requests", 0)
        if requests:
            shed = (counters.get("serving.shed_queue_full", 0)
                    + counters.get("serving.shed_deadline", 0))
            out["shed_rate"] = shed / requests
        # replica-set health (serving resilience): rotation state per
        # replica, the healthy count, and the brownout flag — published
        # as gauges by ReplicaSet.check_health, folded in here so one
        # /healthz answers "how degraded is the serving fleet"
        replicas = {k: v for k, v in gauges.items()
                    if k.startswith("replica/")
                    or k in ("serving/brownout", "serving/saturation")}
        if replicas:
            out["replicas"] = replicas
        return out

    def healthz(self) -> Dict[str, Any]:
        """The /healthz JSON.  With registered fleet jobs the payload
        grows a per-job verdict map and the top-level ``ok`` becomes the
        WORST-OF: 503 iff the base source or any job is stalled or
        diverged — one probe covers the whole pool."""
        out = self._verdict(self.recorder, self.watchdog, self.monitor)
        jobs = dict(self._jobs)
        if not jobs:
            return out
        out["jobs"] = {}
        stalled, diverged = out["stalled"], out["diverged"]
        for name, j in jobs.items():
            v = self._verdict(j["recorder"], j["watchdog"], j["monitor"])
            out["jobs"][name] = v
            stalled = stalled or v["stalled"]
            diverged = diverged or v["diverged"]
        out["stalled"] = stalled
        out["diverged"] = diverged
        out["ok"] = not (stalled or diverged)
        return out
