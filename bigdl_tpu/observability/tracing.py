"""Causal trace spine: spans, causal links, merged export, attribution.

The Recorder (PR 1) aggregates; the serving TraceRing (PR 5) keeps
per-request timelines for ONE engine.  Neither can answer "why was
this request slow" when the answer crosses a subsystem boundary — a
failover hop, a checkpoint write on another thread, an autoscale
decision.  This module is the cross-subsystem half:

  :class:`Span`        one named interval stamped on the
                       :func:`~.context.trace_now` clock, carrying a
                       :class:`~.context.TraceContext` (so every span
                       knows its trace and its parent) plus optional
                       causal ``links`` to spans in OTHER traces —
                       the Dapper-style "this shrink was caused by
                       that decision" edge.
  :class:`SpanStore`   thread-safe bounded ring of finished spans
                       (O(capacity) memory, same contract as the
                       TraceRing), queryable by trace id.
  :class:`Tracer`      the recording surface: ``span()`` context
                       manager, ``begin()``/``OpenSpan.end()`` for
                       intervals whose two ends live on different
                       threads (pass the handle through the same
                       queue that orders the work — the handoff IS
                       the synchronization, exactly the PR-5 trace
                       discipline), and ``event()`` for points.
  :func:`merge_perfetto`
                       merge N sources — Tracers/SpanStores and the
                       serving TraceRings — into ONE Chrome-trace/
                       Perfetto document: one clock domain (everything
                       is trace_now seconds, rebased once), one
                       process row per source.
  :func:`critical_path`
                       per-trace latency attribution: every instant of
                       the trace's end-to-end window is charged to the
                       innermost span covering it (uncovered gaps
                       charge to ``(untraced)``), so "which hop/phase
                       actually bounded TTFT" is one table, and the
                       named-coverage fraction is a testable number.

A process-global default tracer (:func:`get_tracer` /
:func:`set_tracer`, mirroring the Recorder's accessors) lets deep
call sites — the checkpoint writer thread, the device-pool ledger —
record spans without threading a tracer through every signature;
components that take an explicit ``tracer=`` still win over it.

Counters: a full store increments ``trace/spans_dropped`` semantics on
the store itself (``SpanStore.dropped``); the ``trace/*`` recorder
family is documented in docs/observability.md.
"""
from __future__ import annotations

import json
import threading
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from . import context as _ctx
from .context import TraceContext


class Span:
    """One finished interval.  ``links`` is a tuple of
    ``(trace_id, span_id, kind)`` causal edges to spans in other
    traces (same-trace parentage rides on the context itself)."""

    __slots__ = ("name", "subsystem", "context", "t0", "t1", "args",
                 "links")

    def __init__(self, name: str, ctx: TraceContext, t0: float,
                 t1: float, subsystem: str = "",
                 args: Optional[Dict[str, Any]] = None,
                 links: Sequence[Tuple[str, str, str]] = ()):
        self.name = str(name)
        self.subsystem = str(subsystem)
        self.context = ctx
        self.t0 = float(t0)
        self.t1 = max(float(t1), self.t0)
        self.args = dict(args) if args else None
        self.links = tuple(links)

    @property
    def trace_id(self) -> str:
        return self.context.trace_id

    def duration(self) -> float:
        return self.t1 - self.t0

    def as_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "subsystem": self.subsystem,
                "t0": self.t0, "t1": self.t1,
                "links": [list(l) for l in self.links],
                "args": self.args, **self.context.as_dict()}

    def __repr__(self):
        return (f"Span({self.name!r}, {self.duration() * 1e3:.3f}ms, "
                f"trace={self.trace_id[:8]}…)")


class SpanStore:
    """Bounded, thread-safe ring of finished spans."""

    def __init__(self, capacity: int = 2048):
        self._ring: deque = deque(maxlen=max(int(capacity), 1))
        self._lock = threading.Lock()
        self.dropped = 0        # finished spans evicted by the bound

    def add(self, span: Span):
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(span)

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._ring)

    def by_trace(self, trace_id: str) -> List[Span]:
        with self._lock:
            return [s for s in self._ring if s.trace_id == trace_id]

    def trace_ids(self) -> List[str]:
        seen, out = set(), []
        for s in self.spans():
            if s.trace_id not in seen:
                seen.add(s.trace_id)
                out.append(s.trace_id)
        return out

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()


class OpenSpan:
    """A span begun on one thread and ended on another.  NOT internally
    locked: the contract is the PR-5 handoff discipline — the handle
    travels through the same queue/condition that orders the work, so
    exactly one thread touches it at a time."""

    __slots__ = ("tracer", "name", "context", "subsystem", "t0",
                 "_links", "_done")

    def __init__(self, tracer: "Tracer", name: str, ctx: TraceContext,
                 subsystem: str, t0: float):
        self.tracer = tracer
        self.name = name
        self.context = ctx
        self.subsystem = subsystem
        self.t0 = t0
        self._links: List[Tuple[str, str, str]] = []
        self._done = False

    def link(self, other: Optional[TraceContext], kind: str = "causes"):
        if other is not None:
            self._links.append((other.trace_id, other.span_id, kind))

    def end(self, t1: Optional[float] = None, **args) -> Span:
        """Finish and record the span; idempotent (a double end on a
        failure path records once)."""
        if self._done:
            return None
        self._done = True
        span = Span(self.name, self.context,
                    self.t0, _ctx.trace_now() if t1 is None else t1,
                    subsystem=self.subsystem, args=args or None,
                    links=self._links)
        self.tracer.store.add(span)
        return span


class _SpanCtx:
    """``with tracer.span(...)`` sugar over :class:`OpenSpan`."""

    __slots__ = ("open",)

    def __init__(self, open_span: OpenSpan):
        self.open = open_span

    @property
    def context(self) -> TraceContext:
        return self.open.context

    def link(self, other: Optional[TraceContext], kind: str = "causes"):
        self.open.link(other, kind)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.open.end(error=repr(exc)) if exc is not None \
            else self.open.end()
        return False


class Tracer:
    """Recording surface over one :class:`SpanStore`."""

    def __init__(self, capacity: int = 2048, subsystem: str = ""):
        self.store = SpanStore(capacity)
        self.subsystem = str(subsystem)

    # -- recording ------------------------------------------------------ #
    def begin(self, name: str, ctx: Optional[TraceContext] = None, *,
              subsystem: Optional[str] = None,
              child: bool = True) -> OpenSpan:
        """Open a span now.  ``ctx=None`` mints a new root trace;
        ``child=True`` (default) derives a child context so the span
        has its own span_id parented on ``ctx``; ``child=False``
        records under ``ctx`` itself (the caller already minted it)."""
        if ctx is None:
            ctx = TraceContext.new_root()
        elif child:
            ctx = ctx.child()
        return OpenSpan(self, name, ctx,
                        self.subsystem if subsystem is None
                        else subsystem, _ctx.trace_now())

    def span(self, name: str, ctx: Optional[TraceContext] = None, *,
             subsystem: Optional[str] = None,
             child: bool = True) -> _SpanCtx:
        return _SpanCtx(self.begin(name, ctx, subsystem=subsystem,
                                   child=child))

    def event(self, name: str, ctx: Optional[TraceContext] = None, *,
              subsystem: Optional[str] = None,
              links: Sequence[Tuple[str, str, str]] = (),
              t: Optional[float] = None, **args) -> Span:
        """A zero-length span (a state transition, a decision)."""
        if ctx is None:
            ctx = TraceContext.new_root()
        else:
            ctx = ctx.child()
        t = _ctx.trace_now() if t is None else t
        span = Span(name, ctx, t, t,
                    subsystem=self.subsystem if subsystem is None
                    else subsystem, args=args or None, links=links)
        self.store.add(span)
        return span

    def record(self, span: Span):
        self.store.add(span)


# -- process-global default tracer (mirrors recorder.get_recorder) ------ #
_default_tracer = Tracer()
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    return _default_tracer


def set_tracer(tracer: Tracer) -> Tracer:
    """Install ``tracer`` as the process default; returns the previous
    one so tests can restore it."""
    global _default_tracer
    with _tracer_lock:
        prev, _default_tracer = _default_tracer, tracer
    return prev


# -- cross-subsystem actuation stitching -------------------------------- #
# The autoscaler moves devices in the POOL's name space; the elastic
# supervisor observes only "my capacity_fn shrank".  This tiny registry
# carries the causal context across that gap: the pool notes the
# context that moved an owner's devices, the supervisor's next replan
# takes it and links its span back to the decision that caused it.
_actuations: Dict[str, TraceContext] = {}
_actuation_lock = threading.Lock()


def note_actuation(owner: str, ctx: Optional[TraceContext]):
    if ctx is None:
        return
    with _actuation_lock:
        _actuations[str(owner)] = ctx


def take_actuation(owner: str) -> Optional[TraceContext]:
    with _actuation_lock:
        return _actuations.pop(str(owner), None)


# -- merged Perfetto export --------------------------------------------- #
def _source_spans(src) -> Tuple[List[Span], List[Any]]:
    """Normalize one source into (tracing spans, serving RequestTraces)."""
    if isinstance(src, Tracer):
        return src.store.spans(), []
    if isinstance(src, SpanStore):
        return src.spans(), []
    if hasattr(src, "traces"):              # TraceRing
        return [], list(src.traces())
    if isinstance(src, (list, tuple)):
        spans = [s for s in src if isinstance(s, Span)]
        reqs = [t for t in src if hasattr(t, "spans")
                and not isinstance(t, Span)]
        return spans, reqs
    raise TypeError(f"cannot merge trace source {type(src).__name__}")


def merge_perfetto(sources: Iterable[Tuple[str, Any]],
                   extra_meta: Optional[Dict[str, Any]] = None) -> str:
    """Merge ``[(label, source), ...]`` into one Chrome-trace JSON.

    Every source gets its own process row (``pid`` + process_name
    metadata = the label — per-host/per-subsystem rows in the Perfetto
    UI); within a source, one ``tid`` track per trace id.  All
    timestamps are :func:`~.context.trace_now` seconds rebased to the
    earliest event across ALL sources — one clock domain, no skew."""
    resolved = []
    t_origin = None
    for label, src in sources:
        spans, reqs = _source_spans(src)
        resolved.append((str(label), spans, reqs))
        for s in spans:
            t_origin = s.t0 if t_origin is None else min(t_origin, s.t0)
        for tr in reqs:
            for _, t0, _, _ in tr.spans:
                t_origin = t0 if t_origin is None else min(t_origin, t0)
    t_origin = t_origin or 0.0

    def us(t):
        return round((t - t_origin) * 1e6, 3)

    events: List[Dict[str, Any]] = []
    for pid, (label, spans, reqs) in enumerate(resolved, start=1):
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": label}})
        tids: Dict[str, int] = {}

        def tid_for(trace_id, title):
            if trace_id not in tids:
                tids[trace_id] = len(tids) + 1
                events.append({"ph": "M", "name": "thread_name",
                               "pid": pid, "tid": tids[trace_id],
                               "args": {"name": title}})
            return tids[trace_id]

        for s in sorted(spans, key=lambda s: s.t0):
            tid = tid_for(s.trace_id, f"trace {s.trace_id[:12]}")
            args = {"trace_id": s.trace_id,
                    "span_id": s.context.span_id}
            if s.context.parent_span_id:
                args["parent_span_id"] = s.context.parent_span_id
            if s.subsystem:
                args["subsystem"] = s.subsystem
            if s.links:
                args["links"] = [{"trace_id": t, "span_id": sp,
                                  "kind": k} for t, sp, k in s.links]
            if s.args:
                args.update(s.args)
            events.append({"ph": "B", "name": s.name,
                           "cat": s.subsystem or "trace", "pid": pid,
                           "tid": tid, "ts": us(s.t0), "args": args})
            events.append({"ph": "E", "name": s.name,
                           "cat": s.subsystem or "trace", "pid": pid,
                           "tid": tid, "ts": us(s.t1)})
        for tr in reqs:
            tid = tid_for(tr.trace_id,
                          f"req {tr.trace_id[:12]} ({tr.model})")
            for name, t0, t1, args in sorted(tr.spans,
                                             key=lambda s: s[1]):
                span_args = {"trace_id": tr.trace_id,
                             "model": tr.model}
                span_args.update(tr.meta)
                if args:
                    span_args.update(args)
                events.append({"ph": "B", "name": name,
                               "cat": "serving", "pid": pid,
                               "tid": tid, "ts": us(t0),
                               "args": span_args})
                events.append({"ph": "E", "name": name,
                               "cat": "serving", "pid": pid,
                               "tid": tid, "ts": us(t1)})
    doc: Dict[str, Any] = {"traceEvents": events,
                           "displayTimeUnit": "ms"}
    if extra_meta:
        doc["otherData"] = dict(extra_meta)
    return json.dumps(doc)


# -- critical-path attribution ------------------------------------------ #
def critical_path(intervals: Sequence[Tuple[str, float, float]]
                  ) -> Dict[str, Any]:
    """Attribute one trace's end-to-end window to its spans.

    ``intervals`` is ``[(name, t0, t1), ...]`` for ONE trace.  Every
    elementary interval between consecutive span boundaries is charged
    to the innermost covering span — the one that started latest
    (ties: the one ending soonest), which for properly nested spans is
    the deepest frame, i.e. what was *actually happening*.  Instants
    no span covers charge to ``(untraced)``.

    Returns ``{"total": seconds, "attribution": {name: seconds},
    "coverage": named_fraction}`` where coverage is the share of the
    window attributed to named spans (the ≥95% acceptance number)."""
    spans = [(str(n), float(t0), float(t1))
             for n, t0, t1 in intervals if t1 >= t0]
    if not spans:
        return {"total": 0.0, "attribution": {}, "coverage": 1.0}
    lo = min(t0 for _, t0, _ in spans)
    hi = max(t1 for _, _, t1 in spans)
    bounds = sorted({t for _, t0, t1 in spans for t in (t0, t1)})
    attribution: Dict[str, float] = {}
    for a, b in zip(bounds, bounds[1:]):
        if b <= a:
            continue
        covering = [(t0, t1, n) for n, t0, t1 in spans
                    if t0 <= a and t1 >= b]
        if covering:
            # innermost: latest start, then earliest end
            _, _, name = max(covering, key=lambda c: (c[0], -c[1]))
        else:
            name = "(untraced)"
        attribution[name] = attribution.get(name, 0.0) + (b - a)
    total = hi - lo
    named = sum(v for k, v in attribution.items() if k != "(untraced)")
    return {"total": total, "attribution": attribution,
            "coverage": (named / total) if total > 0 else 1.0}


def spans_from_chrome(doc) -> Dict[str, List[Tuple[str, float, float]]]:
    """Reconstruct per-trace ``(name, t0, t1)`` interval lists from a
    Chrome-trace document (dict or JSON string) produced by
    :func:`merge_perfetto` / the serving exporter.  ``B``/``E`` events
    are paired per ``(pid, tid)`` LIFO; timestamps come back in
    SECONDS (the µs rebase divided out) so the result feeds
    :func:`critical_path` directly."""
    if isinstance(doc, (str, bytes)):
        doc = json.loads(doc)
    events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    open_stack: Dict[Tuple[int, int], List[Tuple[str, float, dict]]] = {}
    by_trace: Dict[str, List[Tuple[str, float, float]]] = {}
    for ev in events:
        ph = ev.get("ph")
        key = (ev.get("pid", 0), ev.get("tid", 0))
        if ph == "B":
            open_stack.setdefault(key, []).append(
                (ev.get("name", "?"), float(ev.get("ts", 0.0)),
                 ev.get("args") or {}))
        elif ph == "E":
            stack = open_stack.get(key)
            if not stack:
                continue
            name, t0, args = stack.pop()
            trace_id = args.get("trace_id")
            if trace_id is None:
                continue
            t1 = float(ev.get("ts", t0))
            by_trace.setdefault(str(trace_id), []).append(
                (name, t0 / 1e6, t1 / 1e6))
    return by_trace
