"""Fleet-wide /metrics aggregation: scrape N endpoints, serve one.

A ReplicaSet + a fleet of trainers is N independent /metrics surfaces;
ROADMAP's SLO-driven autoscaling wants ONE.  :class:`MetricsAggregator`
polls each source — an in-process :class:`Recorder`, a live
``http://host:port`` base URL, or any zero-arg callable returning
exposition text — parses the Prometheus text back into typed samples
(:func:`parse_prometheus`), and re-exposes:

  * one merged ``/metrics`` where every sample carries a
    ``source="<name>"`` label (and ``stale="1"`` when that source's
    last successful scrape is older than ``stale_after``) — a dead
    member's last samples are RETAINED and flagged, never silently
    dropped, so dashboards see the gap instead of a shrunken fleet;
  * one worst-of ``/healthz`` (503 iff any source is unhealthy or
    stale — same semantics as ``IntrospectionServer.add_job``);
  * a :class:`~bigdl_tpu.observability.timeseries.SeriesStore` fed on
    every scrape (series key ``<source>/<metric>``, summary quantiles
    flattened to ``/p50``/``/p95``/``/p99`` suffixes), which the
    :class:`~bigdl_tpu.observability.slo.SLOEngine` evaluates and
    ``/series`` serves.

The aggregator's own telemetry (``agg/*``) rides the same exposition.
``clock`` is injectable, so staleness and window math are fully
deterministic under test.

One-call attachment: anything with ``telemetry_sources()`` (ReplicaSet,
DecodeEngine, ServingEngine, FleetScheduler, Optimizer) registers all
its recorders at once::

    agg = MetricsAggregator()
    agg.add(replica_set, name="serve")
    agg.add(trainer, name="train")
    agg.scrape()                       # or agg.start(interval=5.0)
    srv = agg.serve(port=9200)         # fleet /metrics + /healthz + /series
"""
from __future__ import annotations

import json
import re
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

from .http import IntrospectionServer
from .recorder import Recorder
from .sinks import (_collect_prometheus, _emit_prometheus, _prom_group,
                    _prom_labels, _prom_value, render_prometheus)
from .timeseries import SeriesStore

Sample = Tuple[str, Dict[str, str], float]

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)\s*(\{.*\})?\s+(\S+)(?:\s+-?\d+)?$")


def _parse_label_block(block: str) -> Dict[str, str]:
    """Parse ``{k="v",...}`` honouring ``\\\\``, ``\\"`` and ``\\n``
    escapes inside values."""
    out: Dict[str, str] = {}
    i, n = 1, len(block)                 # skip leading '{'
    while i < n:
        while i < n and block[i] in ", ":
            i += 1
        if i >= n or block[i] == "}":
            break
        j = block.index("=", i)
        key = block[i:j].strip()
        i = j + 1
        if i >= n or block[i] != '"':
            raise ValueError(f"unquoted label value near {block[i:]!r}")
        i += 1
        buf = []
        while i < n:
            c = block[i]
            if c == "\\" and i + 1 < n:
                nxt = block[i + 1]
                buf.append({"n": "\n", '"': '"', "\\": "\\"}
                           .get(nxt, "\\" + nxt))
                i += 2
                continue
            if c == '"':
                i += 1
                break
            buf.append(c)
            i += 1
        out[key] = "".join(buf)
    return out


def parse_prometheus(text: str) -> Dict[str, Any]:
    """Parse exposition text (version 0.0.4) back into typed samples:
    ``{"samples": [(name, labels, value), ...], "types": {metric:
    type}, "help": {metric: help}}``.  Malformed lines are skipped —
    one bad sample from one replica must not poison the fleet scrape."""
    samples: List[Sample] = []
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                types[parts[2]] = parts[3] if len(parts) > 3 else "untyped"
            elif len(parts) >= 3 and parts[1] == "HELP":
                helps[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, block, value = m.group(1), m.group(2), m.group(3)
        try:
            labels = _parse_label_block(block) if block else {}
            samples.append((name, labels, float(value)))
        except (ValueError, IndexError):
            continue
    return {"samples": samples, "types": types, "help": helps}


def _base_metric(name: str, types: Dict[str, str]) -> str:
    """``_sum``/``_count``/``_bucket`` samples belong to their declared
    summary/histogram metric's HELP/TYPE group."""
    for suffix in ("_sum", "_count", "_bucket"):
        if name.endswith(suffix):
            base = name[:-len(suffix)]
            if types.get(base) in ("summary", "histogram"):
                return base
    return name


def series_key(source: str, name: str, labels: Dict[str, str]) -> str:
    """The SeriesStore key for one scraped sample:
    ``<source>/<metric>`` plus sorted non-synthetic labels; a summary's
    ``quantile="0.99"`` flattens to a ``/p99`` suffix so one objective
    pattern (``*decode*ttft_ms/p99``) matches both raw recorder series
    and aggregated ones."""
    labels = {k: v for k, v in labels.items()
              if k not in ("source", "stale")}
    q = labels.pop("quantile", None)
    key = f"{source}/{name}"
    if labels:
        inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
        key += "{" + inner + "}"
    if q is not None:
        try:
            key += f"/p{float(q) * 100:g}"
        except ValueError:
            key += f"/q{q}"
    return key


class MetricsAggregator:
    """Scrape many /metrics sources into one surface + series store."""

    def __init__(self, namespace: str = "bigdl", stale_after: float = 10.0,
                 clock: Optional[Callable[[], float]] = None,
                 series_capacity: int = 512, timeout: float = 2.0,
                 series_filter: Optional[Callable[[str], bool]] = None,
                 recorder: Optional[Recorder] = None):
        self.namespace = namespace
        self.stale_after = float(stale_after)
        self.clock = clock if clock is not None else time.time
        self.timeout = float(timeout)
        # keep-or-drop predicate on series keys; None keeps everything
        # (bounded by series_capacity points per key)
        self.series_filter = series_filter
        self.recorder = recorder if recorder is not None \
            else Recorder(annotate=False)
        self.store = SeriesStore(capacity=series_capacity,
                                 clock=self.clock)
        self._lock = threading.Lock()
        self._sources: Dict[str, Dict[str, Any]] = {}
        # label -> trace source (Tracer/SpanStore/TraceRing/engine with
        # a .trace_ring) for the merged /trace document
        self._trace_sources: Dict[str, Any] = {}
        # job name -> GoodputLedger (or any object with snapshot());
        # rolled up into the fleet /goodput document plus pool-level
        # goodput/* gauges on the aggregator's own recorder
        self._goodput_sources: Dict[str, Any] = {}
        # the DevicePool's OwnershipLedger: unclaimed device-seconds
        # are POOL idle, attributed separately from any job's badput
        self._pool_ledger: Optional[Any] = None
        self._server: Optional[IntrospectionServer] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- source registration ---------------------------------------------- #
    def add_source(self, name: str, fetch: Callable[[], str],
                   healthz: Optional[Callable[[], Dict[str, Any]]] = None
                   ) -> "MetricsAggregator":
        """Register a raw source: ``fetch()`` returns exposition text,
        ``healthz()`` (optional) a PR-11-shaped verdict dict."""
        with self._lock:
            self._sources[str(name)] = {
                "fetch": fetch, "healthz": healthz,
                "samples": [], "types": {},
                "last_ok": None, "last_err": None, "stale": False,
                "scrapes": 0, "errors": 0, "health": None,
            }
        return self

    def add_recorder(self, name: str, recorder) -> "MetricsAggregator":
        """In-process source: rendered and re-parsed through the same
        pipeline as remote ones, so there is exactly one merge path."""
        probe = IntrospectionServer(recorder, namespace=self.namespace)
        return self.add_source(
            name, lambda: render_prometheus(recorder, self.namespace),
            healthz=probe.healthz)

    def add_endpoint(self, name: str, base_url: str
                     ) -> "MetricsAggregator":
        """Remote source scraped over HTTP: ``<base_url>/metrics`` for
        samples, ``<base_url>/healthz`` for the verdict (a 503 still
        carries the JSON body — that IS the verdict, not an error)."""
        base = base_url.rstrip("/")

        def fetch() -> str:
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=self.timeout) as r:
                return r.read().decode("utf-8")

        def healthz() -> Optional[Dict[str, Any]]:
            try:
                try:
                    with urllib.request.urlopen(
                            base + "/healthz", timeout=self.timeout) as r:
                        body = r.read()
                except urllib.error.HTTPError as e:
                    body = e.read()
                return json.loads(body.decode("utf-8"))
            except Exception:
                return None

        return self.add_source(name, fetch, healthz=healthz)

    def add(self, obj, name: Optional[str] = None) -> "MetricsAggregator":
        """One-call attachment.  ``obj`` may be anything with
        ``telemetry_sources() -> [(sub_name, recorder), ...]``
        (ReplicaSet, DecodeEngine, FleetScheduler, Optimizer, ...), a
        Recorder, an ``http://...`` base URL, or a zero-arg callable
        returning exposition text.  ``name`` prefixes (or names) the
        registered source(s)."""
        hook = getattr(obj, "telemetry_sources", None)
        if hook is not None:
            for sub, rec in hook():
                self.add_recorder(f"{name}.{sub}" if name else str(sub),
                                  rec)
            return self
        if isinstance(obj, str):
            return self.add_endpoint(name or obj, obj)
        if hasattr(obj, "snapshot") and hasattr(obj, "hist_names"):
            return self.add_recorder(name or "recorder", obj)
        if callable(obj):
            return self.add_source(name or getattr(obj, "__name__",
                                                   "source"), obj)
        raise TypeError(f"don't know how to scrape {type(obj).__name__}")

    def remove_source(self, name: str):
        with self._lock:
            self._sources.pop(str(name), None)

    # -- merged tracing ---------------------------------------------------- #
    def add_trace_source(self, name: str, source) -> "MetricsAggregator":
        """Register a span source for the merged ``/trace`` document: a
        :class:`~.tracing.Tracer` / :class:`~.tracing.SpanStore`, a
        serving :class:`~.profile.trace.TraceRing`, or an engine/
        replica-set exposing ``trace_ring``.  Each source renders as
        its own process row in Perfetto, all on the one
        :func:`~.context.trace_now` clock domain."""
        with self._lock:
            self._trace_sources[str(name)] = source
        return self

    def remove_trace_source(self, name: str) -> bool:
        with self._lock:
            return self._trace_sources.pop(str(name), None) is not None

    # -- goodput roll-up ---------------------------------------------------- #
    def add_goodput(self, name: str, ledger) -> "MetricsAggregator":
        """Register a job's :class:`~.goodput.GoodputLedger` (anything
        with ``snapshot() -> dict``) for the fleet roll-up: the
        ``/goodput`` document and pool-level ``goodput/*`` gauges."""
        with self._lock:
            self._goodput_sources[str(name)] = ledger
        return self

    def remove_goodput(self, name: str) -> bool:
        with self._lock:
            return self._goodput_sources.pop(str(name), None) is not None

    def set_pool_ledger(self, ledger) -> "MetricsAggregator":
        """Attach the DevicePool's :class:`~.goodput.OwnershipLedger`
        so unclaimed device-seconds are attributed as POOL idle in the
        roll-up, never as any job's badput."""
        with self._lock:
            self._pool_ledger = ledger
        return self

    def goodput_doc(self) -> Dict[str, Any]:
        """The fleet goodput attribution — per-job ledger snapshots
        rolled into summed buckets + pool idle + one goodput fraction
        (:func:`~.goodput.rollup`).  Served at ``/goodput``; also
        mirrors pool-level gauges onto the aggregator's recorder."""
        from .goodput import rollup
        with self._lock:
            sources = list(self._goodput_sources.items())
            pool = self._pool_ledger
        jobs = {}
        for name, led in sources:
            try:
                jobs[name] = led.snapshot()
            except Exception:
                continue    # one broken ledger must not kill the doc
        doc = rollup(jobs, pool.snapshot() if pool is not None else None)
        rec = self.recorder
        rec.gauge("goodput/pool_fraction", doc["goodput_fraction"])
        rec.gauge("goodput/pool_owned_s", doc["owned_s"])
        rec.gauge("goodput/pool_idle_s", doc["pool_idle_s"])
        for b, v in doc["buckets"].items():
            rec.gauge(f"goodput/pool_{b}_s", v)
        return doc

    def trace_doc(self) -> str:
        """One Chrome-trace/Perfetto JSON merging every registered
        trace source — what ``/trace`` serves and ``trace_summary
        critical-path`` consumes."""
        from .tracing import merge_perfetto
        with self._lock:
            items = list(self._trace_sources.items())
        resolved = []
        for name, src in items:
            rings = getattr(src, "trace_ring", None)
            resolved.append((name, rings if rings is not None else src))
        return merge_perfetto(resolved)

    def remove_member(self, name: str, purge_series: bool = True) -> bool:
        """Deliberate deregistration — the scale-DOWN path, as opposed
        to a crash.  The source leaves the merged exposition AND (by
        default) its retained samples leave the series store, so
        ``stale="1"`` keeps meaning "crashed, dashboards should see
        the gap" while a scaled-away member simply stops existing:
        ``/healthz`` must not 503 forever over a replica the
        autoscaler retired on purpose.  Members that die WITHOUT
        deregistering keep the crash-retention behavior (samples
        retained, flagged stale).  Idempotent: unknown names return
        False.  Counted as ``agg/deregistered``."""
        name = str(name)
        with self._lock:
            src = self._sources.pop(name, None)
            self._goodput_sources.pop(name, None)
        if src is None:
            return False
        rec = self.recorder
        rec.inc("agg/deregistered")
        # drop the per-source gauges so the merged /metrics carries no
        # ghost staleness verdict for a member that no longer exists
        rec.reset_gauges(f"agg/stale.{name}")
        rec.reset_gauges(f"agg/scrape_age_s.{name}")
        if purge_series:
            self.store.drop(f"{name}/*")
        return True

    def source_names(self) -> List[str]:
        with self._lock:
            return list(self._sources)

    def stale_sources(self) -> List[str]:
        with self._lock:
            return [n for n, s in self._sources.items() if s["stale"]]

    # -- scraping ----------------------------------------------------------- #
    def scrape(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One scrape round over every source.  A source that raises
        mid-scrape keeps its previous samples (flagged stale once its
        last-success age exceeds ``stale_after``) — the fleet surface
        never shrinks because one member died."""
        now = float(now) if now is not None else float(self.clock())
        with self._lock:
            sources = list(self._sources.items())
        rec = self.recorder
        ok = errs = 0
        for name, src in sources:
            rec.inc("agg/scrapes")
            try:
                parsed = parse_prometheus(src["fetch"]())
                health = src["healthz"]() if src["healthz"] else None
            except Exception as e:
                src["errors"] += 1
                src["last_err"] = repr(e)
                rec.inc("agg/scrape_errors")
                errs += 1
            else:
                src["samples"] = parsed["samples"]
                src["types"] = parsed["types"]
                src["health"] = health
                src["last_ok"] = now
                src["last_err"] = None
                src["scrapes"] += 1
                ok += 1
                self._feed_series(name, parsed["samples"], now)
            age = (now - src["last_ok"]) if src["last_ok"] is not None \
                else None
            src["stale"] = age is None or age > self.stale_after
            rec.gauge(f"agg/stale.{name}", 1.0 if src["stale"] else 0.0)
            if age is not None:
                rec.gauge(f"agg/scrape_age_s.{name}", age)
        stale = [n for n, s in sources if s["stale"]]
        rec.gauge("agg/sources", len(sources))
        rec.gauge("agg/stale_sources", len(stale))
        with self._lock:
            any_goodput = bool(self._goodput_sources
                               or self._pool_ledger is not None)
        if any_goodput:
            try:
                self.goodput_doc()    # refresh pool goodput/* gauges
            except Exception:
                pass    # attribution must never kill a scrape
        return {"time": now, "sources": len(sources), "ok": ok,
                "errors": errs, "stale": stale}

    def _feed_series(self, source: str, samples: List[Sample],
                     now: float):
        keep = self.series_filter
        for mname, labels, value in samples:
            key = series_key(source, mname, labels)
            if keep is None or keep(key):
                self.store.observe(key, value, now)

    # -- re-exposure -------------------------------------------------------- #
    def render(self) -> str:
        """The merged exposition: the aggregator's own ``agg/*``
        telemetry first, then every source's retained samples tagged
        ``source="<name>"`` (plus ``stale="1"`` on sources past the
        staleness budget)."""
        groups: Dict[str, Dict[str, Any]] = {}
        _collect_prometheus(self.recorder, self.namespace, None, groups)
        with self._lock:
            sources = list(self._sources.items())
        for name, src in sources:
            extra = {"source": name}
            if src["stale"]:
                extra["stale"] = "1"
            types = src["types"]
            for mname, labels, value in src["samples"]:
                base = _base_metric(mname, types)
                lines = _prom_group(groups, base,
                                    f"aggregated {base}",
                                    types.get(base, "untyped"))
                lines.append(f"{mname}{_prom_labels({**labels, **extra})}"
                             f" {_prom_value(value)}")
        return _emit_prometheus(groups)

    def healthz(self) -> Dict[str, Any]:
        """Worst-of verdict across sources (PR-11 semantics): ``ok`` is
        False iff any source's own /healthz said so OR the source went
        stale.  Per-source verdicts ride along for diagnosis."""
        with self._lock:
            sources = list(self._sources.items())
        out: Dict[str, Any] = {"ok": True, "stalled": False,
                               "diverged": False, "sources": {},
                               "stale_sources": []}
        for name, src in sources:
            v = dict(src["health"]) if src["health"] else {}
            v.setdefault("ok", src["last_err"] is None)
            v["stale"] = src["stale"]
            if src["last_err"] is not None:
                v["last_error"] = src["last_err"]
            if src["stale"]:
                v["ok"] = False
                out["stale_sources"].append(name)
            out["sources"][name] = v
            out["ok"] = out["ok"] and bool(v["ok"])
            out["stalled"] = out["stalled"] or bool(v.get("stalled"))
            out["diverged"] = out["diverged"] or bool(v.get("diverged"))
        return out

    # -- lifecycle ----------------------------------------------------------- #
    def serve(self, port: int = 0, host: str = "127.0.0.1"
              ) -> IntrospectionServer:
        """Start the fleet-level HTTP surface: ``/metrics`` renders the
        merged exposition, ``/healthz`` the worst-of verdict,
        ``/series`` the scrape-fed store, and ``/trace`` the merged
        multi-subsystem Perfetto document (``?trace_id=`` filters to
        one request/decision trace)."""
        if self._server is None:
            self._server = IntrospectionServer(
                self.recorder, port=port, host=host,
                namespace=self.namespace, metrics_source=self.render,
                healthz_source=self.healthz,
                series_source=self.store,
                goodput_source=self.goodput_doc,
                trace_source=self.trace_doc).start()
        return self._server

    def start(self, interval: float = 5.0) -> "MetricsAggregator":
        """Background scrape loop every ``interval`` seconds (wall
        time; tests drive ``scrape(now=...)`` directly instead)."""
        if self._thread is not None:
            return self

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.scrape()
                except Exception:
                    pass        # the scraper must outlive any source

        self._stop.clear()
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="metrics-aggregator")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def close(self):
        self.stop()
        srv, self._server = self._server, None
        if srv is not None:
            srv.stop()
