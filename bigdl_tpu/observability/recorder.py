"""Thread-safe telemetry recorder (≙ optim/Metrics.scala grown up).

One :class:`Recorder` instance aggregates four primitive kinds:

  counters    monotonically increasing totals (bytes reduced, stall
              seconds, records seen) — ``inc``
  gauges      last-written values (queue depth, bytes-per-step) —
              ``gauge``
  spans       wall-clock timed regions (``with rec.span("data_fetch")``),
              accumulated per step and mirrored as
              ``jax.profiler.TraceAnnotation`` so they line up with
              device events on an XLA trace
  histograms  per-step value distributions kept as count/min/max/
              sum/sumsq plus a bounded recent-sample window for
              p50/p95/p99 quantiles — ``observe``; read back via
              ``hist_quantiles``/``hist_summary``

``start_step``/``end_step`` bracket one training iteration; ``end_step``
folds everything recorded since ``start_step`` into a *step record*
(a plain dict) and hands it to every sink.  A disabled recorder's
methods return immediately and ``span`` hands back a shared no-op
context manager, so instrumentation can stay in the hot path
unconditionally.

``trace_every(n, log_dir)`` captures a full XLA profiler trace of every
n-th step — the on-demand deep view to the step records' always-on
shallow view.
"""
from __future__ import annotations

import json
import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Any, Dict, List, Optional

from . import context as _trace_clock


class _NullSpan:
    """Shared no-op context manager for disabled recorders."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_rec", "_name", "_t0", "_ann")

    def __init__(self, rec: "Recorder", name: str):
        self._rec = rec
        self._name = name
        self._ann = None

    def __enter__(self):
        if self._rec.annotate:
            import jax
            self._ann = jax.profiler.TraceAnnotation(self._name)
            self._ann.__enter__()
        # one trace clock across the repo (context.trace_now =
        # time.monotonic); perf_counter here used to skew merged
        # Perfetto timelines against the serving TraceRing's stamps
        self._t0 = _trace_clock.trace_now()
        return self

    def __exit__(self, *exc):
        dt = _trace_clock.trace_now() - self._t0
        if self._ann is not None:
            self._ann.__exit__(*exc)
        self._rec._add_span(self._name, dt)
        return False


class Recorder:
    """Aggregates telemetry and emits one record per training step.

    ``sinks`` is any iterable of objects with ``emit(record: dict)``
    (see :mod:`~bigdl_tpu.observability.sinks`).  ``annotate`` mirrors
    spans onto the jax profiler timeline (cheap; only meaningful while
    a trace is being captured).
    """

    def __init__(self, sinks=(), enabled: bool = True,
                 annotate: bool = True, hist_sample_cap: int = 2048,
                 keep_records: int = 256, keep_series: int = 0,
                 series_clock=None):
        self._lock = threading.Lock()
        self.sinks = list(sinks)
        self._enabled = bool(enabled)
        self.annotate = bool(annotate)
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        # pending per-step state, reset by end_step
        self._spans: Dict[str, float] = {}
        self._span_counts: Dict[str, int] = {}
        self._scalars: Dict[str, float] = {}
        self._hists: Dict[str, List[float]] = {}
        # bounded raw-sample window per histogram so percentiles
        # (p50/p95/p99 — the serving-latency SLO numbers) are available;
        # the moment/extremum fields above stay exact over ALL samples,
        # the quantiles cover the most recent `hist_sample_cap`
        self.hist_sample_cap = int(hist_sample_cap)
        self._hist_samples: Dict[str, deque] = {}
        self._step: Optional[int] = None
        self._step_t0: Optional[float] = None
        self._n_records = 0
        self._trace_cfg = None        # (every_n, log_dir)
        self._tracing = False
        # flight-recorder ring: the last `keep_records` emitted records
        # (step + out-of-band), kept regardless of sinks so a crash dump
        # and the /records endpoint work even for a sink-less recorder
        self.keep_records = int(keep_records)
        self._ring: deque = deque(maxlen=max(self.keep_records, 1))
        # liveness: wall time the current step opened / the last step
        # closed — what /healthz and the stall watchdog read
        self._step_started_wall: Optional[float] = None
        self._last_step_end: Optional[float] = None
        self._last_step_index: Optional[int] = None
        # cost attribution (observability.profile): a StepCostModel
        # whose scalars(dur) fold perf/mfu, perf/hbm_bw_util and
        # mem/peak_hbm_bytes into every step record
        self._cost_model = None
        # goodput attribution (observability.goodput): a GoodputLedger
        # end_step folds span totals into (device-second buckets) and
        # mirrors as goodput/* gauges; same no-new-host-syncs
        # discipline as the cost model
        self._ledger = None
        # gauge pollers: callables(recorder) refreshed before each
        # snapshot()/end_step() — live device-memory stats and friends
        self._gauge_pollers: List = []
        # opt-in time series: keep_series > 0 attaches a SeriesStore
        # (that many points per metric) fed by end_step and
        # series_tick(); series_clock injects virtual time for
        # deterministic windowed math in tests
        self.series = None
        if keep_series:
            from .timeseries import SeriesStore
            self.series = SeriesStore(capacity=int(keep_series),
                                      clock=series_clock)
        # opt-in Prometheus histogram buckets: name (or "prefix/*"
        # family) -> sorted upper bounds; per-bin counts live beside
        # _hists and share its per-step lifecycle
        self._hist_bucket_spec: Dict[str, tuple] = {}
        self._hist_bucket_bounds: Dict[str, Optional[tuple]] = {}
        self._hist_bucket_counts: Dict[str, List[int]] = {}

    # -- enable/disable -------------------------------------------------- #
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, on: bool = True):
        self._enabled = bool(on)
        return self

    def add_sink(self, sink):
        self.sinks.append(sink)
        return self

    def set_cost_model(self, model):
        """Attach a cost model (anything with ``scalars(dur) -> dict``,
        e.g. :class:`~bigdl_tpu.observability.profile.StepCostModel`);
        ``end_step`` folds its derived efficiency scalars into every
        step record.  ``None`` detaches."""
        self._cost_model = model
        return self

    def set_ledger(self, ledger):
        """Attach a :class:`~bigdl_tpu.observability.goodput
        .GoodputLedger`; ``end_step`` folds each step's span totals
        into its buckets and stamps ``goodput/*`` gauges.  ``None``
        detaches."""
        self._ledger = ledger
        return self

    def get_ledger(self):
        """The attached goodput ledger, or None."""
        return self._ledger

    def add_gauge_poller(self, fn):
        """Register ``fn(recorder)`` to refresh live gauges right before
        each ``snapshot()`` / ``end_step()`` — i.e. on every /metrics
        scrape and every step record.  Poller exceptions are swallowed:
        a broken poller must never take down a scrape or the step
        loop."""
        self._gauge_pollers.append(fn)
        return self

    def _run_gauge_pollers(self):
        # OUTSIDE the lock: pollers call self.gauge(), which locks
        for fn in list(self._gauge_pollers):
            try:
                fn(self)
            except Exception:
                pass

    # -- primitives ------------------------------------------------------ #
    def inc(self, name: str, value: float = 1.0) -> float:
        """Add to a monotonic counter; returns the new total."""
        if not self._enabled:
            return 0.0
        with self._lock:
            total = self._counters.get(name, 0.0) + value
            self._counters[name] = total
            return total

    def gauge(self, name: str, value: float):
        """Set a last-value gauge."""
        if not self._enabled:
            return
        with self._lock:
            self._gauges[name] = float(value)

    def gauge_value(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def counter_value(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._counters.get(name, default)

    def span_value(self, name: str, default: float = 0.0) -> float:
        """Accumulated seconds of ``name`` in the *pending* step."""
        with self._lock:
            return self._spans.get(name, default)

    def reset_gauges(self, prefix: str = ""):
        """Drop gauges whose name starts with ``prefix`` (used before a
        step-function rebuild so trace-time collective accounting does
        not double-count across recompiles)."""
        with self._lock:
            for k in list(self._gauges):
                if k.startswith(prefix):
                    del self._gauges[k]

    def scalar(self, name: str, value):
        """Record a per-step scalar (loss, grad-norm, lr, ...).  Device
        scalars are accepted and converted at ``end_step``."""
        if not self._enabled:
            return
        with self._lock:
            self._scalars[name] = value

    def observe(self, name: str, value: float):
        """Add one observation to the step's histogram for ``name``."""
        if not self._enabled:
            return
        v = float(value)
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                self._hists[name] = [1, v, v, v, v * v]
            else:
                h[0] += 1
                h[1] = min(h[1], v)
                h[2] = max(h[2], v)
                h[3] += v
                h[4] += v * v
            s = self._hist_samples.get(name)
            if s is None:
                s = self._hist_samples[name] = deque(
                    maxlen=self.hist_sample_cap)
            s.append(v)
            if self._hist_bucket_spec:
                bounds = self._resolve_buckets(name)
                if bounds is not None:
                    c = self._hist_bucket_counts.get(name)
                    if c is None:
                        c = self._hist_bucket_counts[name] = \
                            [0] * (len(bounds) + 1)
                    c[bisect_left(bounds, v)] += 1

    # -- Prometheus histogram buckets (opt-in) --------------------------- #
    def set_hist_buckets(self, spec: Dict[str, Any]):
        """Opt histograms into cumulative ``_bucket`` exposition.
        ``spec`` maps an exact histogram name — or a ``"prefix/*"``
        family — to its ``le`` upper bounds (sorted ascending; ``+Inf``
        is implicit).  Exact names beat families; within families the
        longest prefix wins.  Buckets are counted at ``observe`` time,
        so ``_bucket`` lines stay exactly consistent with ``_count``
        instead of being re-derived from the bounded sample window."""
        with self._lock:
            self._hist_bucket_spec = {
                str(k): tuple(sorted(float(b) for b in v))
                for k, v in spec.items()}
            self._hist_bucket_bounds.clear()
            self._hist_bucket_counts.clear()
        return self

    def _resolve_buckets(self, name: str) -> Optional[tuple]:
        # caller holds the lock
        if name in self._hist_bucket_bounds:
            return self._hist_bucket_bounds[name]
        bounds = self._hist_bucket_spec.get(name)
        if bounds is None:
            best = -1
            for pat, b in self._hist_bucket_spec.items():
                if pat.endswith("/*") and len(pat) > best \
                        and name.startswith(pat[:-1]):
                    bounds, best = b, len(pat)
        self._hist_bucket_bounds[name] = bounds
        return bounds

    def hist_buckets(self, name: str):
        """``(bounds, per_bin_counts)`` for an opted-in histogram with
        observations this step, else ``None``.  ``per_bin_counts`` has
        ``len(bounds) + 1`` entries (the last is the overflow bin);
        renderers cumulate them into ``le``-labeled samples."""
        with self._lock:
            c = self._hist_bucket_counts.get(name)
            if c is None:
                return None
            return (self._hist_bucket_bounds.get(name), list(c))

    def hist_quantiles(self, name: str, qs=(50.0, 95.0, 99.0)
                       ) -> Optional[Dict[str, float]]:
        """``{"p50": ..., "p95": ..., "p99": ...}`` over the pending
        histogram's sample window, or None if nothing was observed.
        Long-running consumers (the serving engine, the /metrics
        endpoint) read this without a step loop; ``end_step`` folds the
        same numbers into the step record.  Unknown or empty names
        return ``None`` — never raise — so health endpoints can probe
        histograms that may not have been observed yet."""
        try:
            with self._lock:
                s = self._hist_samples.get(name)
                samples = sorted(s) if s else None
        except TypeError:        # unhashable name: nothing recorded under it
            return None
        if not samples:
            return None
        return {f"p{q:g}": _quantile(samples, q) for q in qs}

    def hist_summary(self, name: str) -> Optional[Dict[str, float]]:
        """count/min/max/mean plus p50/p95/p99 of the pending histogram;
        ``None`` (never an exception) for unknown/empty names."""
        try:
            with self._lock:
                h = self._hists.get(name)
                if h is None or not h[0]:
                    return None
                s = self._hist_samples.get(name)
                samples = sorted(s) if s else []
        except TypeError:        # unhashable name
            return None
        out = {"count": int(h[0]), "min": h[1], "max": h[2],
               "mean": h[3] / max(h[0], 1), "sumsq": h[4]}
        if samples:
            out.update({f"p{q:g}": _quantile(samples, q)
                        for q in (50.0, 95.0, 99.0)})
        return out

    def hist_names(self) -> List[str]:
        """Names with at least one observation in the pending step."""
        with self._lock:
            return list(self._hists)

    def span(self, name: str):
        """Context manager timing a region into the current step."""
        if not self._enabled:
            return _NULL_SPAN
        return _Span(self, name)

    def _add_span(self, name: str, dt: float):
        with self._lock:
            self._spans[name] = self._spans.get(name, 0.0) + dt
            self._span_counts[name] = self._span_counts.get(name, 0) + 1

    def add_span(self, name: str, seconds: float):
        """Record an externally-timed duration as a span."""
        if not self._enabled:
            return
        self._add_span(name, seconds)

    # -- step lifecycle -------------------------------------------------- #
    def start_step(self, step: Optional[int] = None):
        if not self._enabled:
            return
        with self._lock:
            self._step = step
            self._step_t0 = _trace_clock.trace_now()
            self._step_started_wall = time.time()
        if self._ledger is not None:
            try:
                # close out the inter-step gap (background phase) so
                # fold_step attributes only this step's own interval;
                # outside our lock — recorder/ledger locks never nest
                self._ledger.note_step_begin()
            except Exception:
                pass        # attribution must never kill the step loop
        self._maybe_start_trace(step)

    def end_step(self, step: Optional[int] = None,
                 **scalars) -> Optional[Dict[str, Any]]:
        """Close the current step: fold pending spans/scalars/histograms
        plus counter and gauge snapshots into one record, emit it to
        every sink, and reset the per-step state."""
        if not self._enabled:
            return None
        self._maybe_stop_trace()
        self._run_gauge_pollers()
        with self._lock:
            if step is None:
                step = self._step
            dur = (_trace_clock.trace_now() - self._step_t0
                   if self._step_t0 is not None else None)
            pend = dict(self._scalars)
            pend.update(scalars)
            if self._cost_model is not None:
                try:
                    # pure arithmetic over the compiled cost capture —
                    # safe under the lock; explicit scalars win ties
                    for k, v in self._cost_model.scalars(dur).items():
                        pend.setdefault(k, v)
                except Exception:
                    pass        # attribution must never kill a record
            rec: Dict[str, Any] = {
                "type": "step",
                "step": step,
                "time": time.time(),
                "dur": dur,
                "spans": dict(self._spans),
                "span_counts": dict(self._span_counts),
                "scalars": {k: _to_float(v) for k, v in pend.items()},
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
            }
            recs = rec["scalars"].get("records")
            if dur and isinstance(recs, (int, float)) and recs > 0:
                rec["scalars"]["records_per_sec"] = recs / dur
            if self._hists:
                rec["hist"] = {}
                for k, h in self._hists.items():
                    entry = {"count": int(h[0]), "min": h[1], "max": h[2],
                             "mean": h[3] / max(h[0], 1),
                             "sumsq": h[4]}
                    s = self._hist_samples.get(k)
                    if s:
                        samples = sorted(s)
                        entry.update(
                            {f"p{q:g}": _quantile(samples, q)
                             for q in (50.0, 95.0, 99.0)})
                    rec["hist"][k] = entry
            self._spans.clear()
            self._span_counts.clear()
            self._scalars.clear()
            self._hists.clear()
            self._hist_samples.clear()
            self._hist_bucket_counts.clear()
            self._step = None
            self._step_t0 = None
            self._step_started_wall = None
            self._last_step_end = rec["time"]
            self._last_step_index = step
            self._n_records += 1
            self._ring.append(rec)
            sinks = list(self.sinks)
        if self._ledger is not None:
            try:
                # the fold and the gauge mirror both run OUTSIDE the
                # recorder lock (publish takes the ledger lock, then
                # rec.gauge takes ours — strictly sequential, so the
                # two locks never nest in either order)
                self._ledger.fold_step(rec.get("dur"),
                                       rec.get("spans") or {})
                rec["goodput"] = self._ledger.publish(self)
            except Exception:
                pass        # attribution must never kill a record
        if self.series is not None:
            self._feed_series(rec)
        for s in sinks:
            s.emit(rec)
        return rec

    def _feed_series(self, rec: Dict[str, Any]):
        """Append one point per numeric scalar/counter/gauge (and per
        histogram p50/p95/p99, as ``<name>/pXX``) to the attached
        series store at its clock's current time."""
        store = self.series
        t = store.now()
        for k, v in rec.get("scalars", {}).items():
            if isinstance(v, (int, float)):
                store.observe(k, v, t)
        for k, v in rec.get("counters", {}).items():
            store.observe(k, v, t)
        for k, v in rec.get("gauges", {}).items():
            store.observe(k, v, t)
        for k, entry in rec.get("hist", {}).items():
            for q in ("p50", "p95", "p99"):
                if q in entry:
                    store.observe(f"{k}/{q}", entry[q], t)

    def series_tick(self):
        """Snapshot counters, gauges and pending-histogram quantiles
        into the attached series store WITHOUT cutting a step record —
        how sources with no step loop (serving engines) or a periodic
        poller grow a time dimension.  No-op without ``keep_series``."""
        if self.series is None or not self._enabled:
            return None
        snap = self.snapshot()
        rec = {"counters": snap["counters"], "gauges": snap["gauges"],
               "hist": {}}
        for name in self.hist_names():
            qs = self.hist_quantiles(name)
            if qs:
                rec["hist"][name] = qs
        self._feed_series(rec)
        return rec

    def emit_record(self, rec_type: str, **fields):
        """Emit an out-of-band (non-step) record to every sink — e.g.
        the post-drain ``checkpoint_summary`` whose writer-thread
        counters finished after the last step record was cut."""
        if not self._enabled:
            return None
        rec = {"type": rec_type, "time": time.time(), **fields}
        with self._lock:
            self._ring.append(rec)
            sinks = list(self.sinks)
        for s in sinks:
            s.emit(rec)
        return rec

    def abort_step(self):
        """Discard the pending step (e.g. the data iterator ran dry after
        ``start_step``); pending spans/scalars are dropped."""
        if not self._enabled:
            return
        self._maybe_stop_trace()
        with self._lock:
            self._spans.clear()
            self._span_counts.clear()
            self._scalars.clear()
            self._hists.clear()
            self._hist_samples.clear()
            self._hist_bucket_counts.clear()
            self._step = None
            self._step_t0 = None
            self._step_started_wall = None

    # -- on-demand XLA profiles ------------------------------------------ #
    def trace_every(self, n_steps: int, log_dir: str):
        """Capture a ``jax.profiler`` trace of every ``n_steps``-th step
        into ``log_dir`` (open with TensorBoard's profile plugin or
        Perfetto).  ``n_steps=0`` disables."""
        self._trace_cfg = (int(n_steps), log_dir) if n_steps else None
        return self

    def _maybe_start_trace(self, step):
        if self._tracing:
            # the previously traced step raised before end_step/
            # abort_step could close the session: stop the stale trace
            # now, or the profiler stays wedged — silently folding every
            # remaining step into one giant capture — for the rest of
            # the run
            self._maybe_stop_trace()
        cfg = self._trace_cfg
        if cfg is None or step is None or step % cfg[0] != 0:
            return
        import jax
        try:
            jax.profiler.start_trace(cfg[1])
            self._tracing = True
        except Exception:
            # start_trace may have opened a session before raising:
            # never let the flag and the profiler disagree
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._tracing = False

    def _maybe_stop_trace(self):
        if not self._tracing:
            return
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass        # profiling must never kill training
        finally:
            self._tracing = False

    # -- introspection / teardown ---------------------------------------- #
    def snapshot(self) -> Dict[str, Dict[str, float]]:
        self._run_gauge_pollers()
        with self._lock:
            return {"counters": dict(self._counters),
                    "gauges": dict(self._gauges)}

    def recent_records(self, n: Optional[int] = None,
                       rec_type: Optional[str] = None
                       ) -> List[Dict[str, Any]]:
        """The last ``n`` records (all kept ones when ``n`` is None) from
        the bounded ring, oldest first; ``rec_type`` filters by the
        record's ``type`` field.  This is the crash flight recorder's
        source and what the /records endpoint serves."""
        with self._lock:
            recs = list(self._ring)
        if rec_type is not None:
            recs = [r for r in recs if r.get("type") == rec_type]
        if n is None:
            return recs
        # n=0 means none (not all); negative/oversized n must not wrap
        n = max(int(n), 0)
        return recs[max(len(recs) - n, 0):] if n else []

    def step_age(self) -> Optional[float]:
        """Seconds since the pending step opened (a step is in flight) or
        since the last step record was cut; ``None`` before any step.
        The liveness signal: a healthy loop keeps this small, a stalled
        one lets it grow without bound."""
        with self._lock:
            started, ended = self._step_started_wall, self._last_step_end
        now = time.time()
        if started is not None:
            return now - started
        if ended is not None:
            return now - ended
        return None

    def step_in_flight(self) -> bool:
        """True between start_step and end_step/abort_step — i.e. the
        current step_age() measures a PENDING step, not idle time."""
        with self._lock:
            return self._step_started_wall is not None

    def last_step(self) -> Optional[int]:
        """Index of the newest completed step (None before the first)."""
        with self._lock:
            return self._last_step_index

    def summary(self) -> str:
        snap = self.snapshot()
        return json.dumps(snap, sort_keys=True)

    def flush(self):
        for s in self.sinks:
            fl = getattr(s, "flush", None)
            if fl is not None:
                fl()
        return self

    def close(self):
        for s in self.sinks:
            close = getattr(s, "close", None)
            if close is not None:
                close()


def _to_float(v):
    try:
        return float(v)
    except (TypeError, ValueError):
        return v


def _quantile(sorted_samples: List[float], q: float) -> float:
    """Linear-interpolated percentile (numpy's default method) over an
    already-sorted list; kept dependency-free so the recorder never
    imports numpy on the hot path."""
    n = len(sorted_samples)
    if n == 0:
        return float("nan")
    if n == 1:
        return sorted_samples[0]
    pos = (q / 100.0) * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_samples[lo] * (1.0 - frac) + sorted_samples[hi] * frac


# -- process-active recorder ---------------------------------------------- #
# Library internals (DeviceLoader, allreduce accounting) report to the
# process-active recorder when one wasn't passed explicitly; the default
# is a disabled instance so un-instrumented runs pay only a bool check.
_null = Recorder(enabled=False, annotate=False)
_active = _null


def null_recorder() -> Recorder:
    """The shared always-disabled recorder."""
    return _null


def get_recorder() -> Recorder:
    """The process-active recorder (a disabled no-op by default)."""
    return _active


def set_recorder(rec: Optional[Recorder]) -> Recorder:
    """Install ``rec`` as the process-active recorder (``None`` resets
    to the disabled default).  Returns the previous one."""
    global _active
    prev = _active
    _active = rec if rec is not None else _null
    return prev
