"""Static cost/memory capture from compiled XLA executables.

XLA already knows, at compile time, exactly what a program will do:
``cost_analysis()`` reports FLOPs and bytes accessed, and
``memory_analysis()`` the peak-HBM budget (argument / output / temp /
generated-code sizes).  The BigDL paper's whole evaluation is "how
close to the roofline do we run" — these numbers ARE the roofline
inputs, so they get harvested once per compile (a trace + analysis
pass, never per step) and attached to the Recorder:

  * :func:`capture_compiled` — harvest one executable into a plain
    dict, with every missing backend capability recorded in an
    ``unavailable`` list instead of raising.
  * :func:`aot_capture` — lower a jitted fn at the given args' avals
    (``ShapeDtypeStruct`` — lowering never touches, let alone donates,
    the real buffers) and capture its compiled form.
  * :class:`StepCostModel` — compiled cost + a
    :class:`~bigdl_tpu.observability.profile.specs.DeviceSpec`;
    ``scalars(dur)`` derives the per-step efficiency ratios
    (``perf/mfu``, ``perf/hbm_bw_util``, ``mem/peak_hbm_bytes``) the
    Recorder folds into every step record.
  * :func:`capture_and_attach` — the one-stop wiring used by
    Optimizer / SpmdTrainer: capture, attach the cost model, set the
    gauges, emit one out-of-band ``profile`` record.  Never raises.
  * :func:`install_device_memory_poller` — live ``mem/device.*``
    gauges from ``jax.local_devices()`` ``memory_stats()``, refreshed
    on every Recorder snapshot (i.e. every /metrics scrape).
"""
from __future__ import annotations

import math
import os
from typing import Any, Dict, Optional

from .specs import DeviceSpec, device_spec

#: memory_analysis attributes worth keeping, recorder-key by XLA name
_MEM_FIELDS = (("argument_size_in_bytes", "argument_bytes"),
               ("output_size_in_bytes", "output_bytes"),
               ("temp_size_in_bytes", "temp_bytes"),
               ("generated_code_size_in_bytes", "generated_code_bytes"),
               ("alias_size_in_bytes", "alias_bytes"))


def capture_enabled() -> bool:
    """``BIGDL_PROFILE_CAPTURE=0`` kills static cost capture for runs
    where even one extra trace+compile per step-build is unwelcome."""
    return os.environ.get("BIGDL_PROFILE_CAPTURE", "1").lower() \
        not in ("0", "false", "off")


def _finite(v) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


def capture_compiled(compiled) -> Dict[str, Any]:
    """Harvest cost/memory analysis from one compiled executable.

    Returns a plain JSON-able dict; capabilities the backend doesn't
    expose land in ``unavailable`` (a list of missing analysis names)
    rather than raising — TPU/CPU expose both today, but a backend
    is allowed to expose neither."""
    out: Dict[str, Any] = {}
    unavailable = []

    ca = None
    try:
        ca = compiled.cost_analysis()
    except Exception:
        ca = None
    # jax returns one properties-dict per device program; all replicas
    # run the same program, so the first entry is THE answer
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        flops = _finite(ca.get("flops"))
        if flops is not None:
            out["flops"] = flops
        bytes_accessed = _finite(ca.get("bytes accessed"))
        if bytes_accessed is not None:
            out["bytes_accessed"] = bytes_accessed
        transcendentals = _finite(ca.get("transcendentals"))
        if transcendentals:
            out["transcendentals"] = transcendentals
    if "flops" not in out:
        unavailable.append("cost_analysis")

    ma = None
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    mem_ok = False
    if ma is not None:
        for attr, key in _MEM_FIELDS:
            v = _finite(getattr(ma, attr, None))
            if v is not None:
                out[key] = v
                mem_ok = True
        if mem_ok:
            # aliased (donated) buffers are counted in both argument and
            # output sizes but occupy HBM once
            out["peak_hbm_bytes"] = (
                out.get("argument_bytes", 0.0)
                + out.get("output_bytes", 0.0)
                + out.get("temp_bytes", 0.0)
                + out.get("generated_code_bytes", 0.0)
                - out.get("alias_bytes", 0.0))
    if not mem_ok:
        unavailable.append("memory_analysis")

    if unavailable:
        out["unavailable"] = unavailable
    return out


def aot_capture(jitted, *args) -> Dict[str, Any]:
    """Lower ``jitted`` at ``args``' avals and capture its compiled
    cost.  Lowering uses ``ShapeDtypeStruct``s so no real buffer is
    read or donated; XLA's compile cache serves the executable when the
    same signature was (or will be) dispatched.  Raises on backends
    without the AOT API — callers that must not fail go through
    :func:`capture_and_attach`."""
    import jax

    def aval(leaf):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)
        return leaf
    sds = jax.tree_util.tree_map(aval, args)
    return capture_compiled(jitted.lower(*sds).compile())


class StepCostModel:
    """Compiled per-step cost + device peaks -> derived per-step ratios.

    ``scalars(dur)`` is called by ``Recorder.end_step`` with the step's
    wall duration and must stay pure arithmetic (it runs under the
    recorder lock).  Every ratio whose numerator or denominator is
    unknown is replaced by an explicit ``*_unavailable`` marker scalar:
    a dashboard that shows nothing is ambiguous, one that shows
    "unavailable" is a statement.
    """

    __slots__ = ("cost", "spec")

    def __init__(self, cost: Dict[str, Any], spec: Optional[DeviceSpec]
                 = None):
        self.cost = dict(cost or {})
        self.spec = spec if spec is not None else DeviceSpec("unknown")

    def scalars(self, dur: Optional[float]) -> Dict[str, float]:
        out: Dict[str, float] = {}
        flops = self.cost.get("flops")
        if flops is not None and dur and self.spec.peak_flops:
            out["perf/mfu"] = flops / dur / self.spec.peak_flops
        elif flops is not None and dur:
            # compiled FLOPs known but no peak for this device: report
            # the achieved rate so the number is still actionable
            out["perf/flops_per_sec"] = flops / dur
            out["perf/mfu_unavailable"] = 1.0
        else:
            out["perf/mfu_unavailable"] = 1.0
        ba = self.cost.get("bytes_accessed")
        if ba is not None and dur and self.spec.peak_hbm_bw:
            out["perf/hbm_bw_util"] = ba / dur / self.spec.peak_hbm_bw
        else:
            out["perf/hbm_bw_util_unavailable"] = 1.0
        peak = self.cost.get("peak_hbm_bytes")
        if peak is not None:
            out["mem/peak_hbm_bytes"] = peak
            if self.spec.hbm_capacity:
                out["mem/peak_hbm_frac"] = peak / self.spec.hbm_capacity
        else:
            out["mem/peak_hbm_bytes_unavailable"] = 1.0
        return out


def attach_cost(recorder, cost: Dict[str, Any],
                kind: str = "train_step", spec: Optional[DeviceSpec]
                = None, **fields) -> StepCostModel:
    """Wire an already-captured cost dict into ``recorder``: attach a
    :class:`StepCostModel` (per-step ``perf/mfu`` etc.), set the
    ``mem/peak_hbm_bytes`` / ``profile/flops_per_step`` gauges /metrics
    renders, and emit one out-of-band ``profile`` record for JSONL
    sinks / ``trace_summary profile``."""
    if spec is None:
        spec = device_spec()
    model = StepCostModel(cost, spec)
    recorder.set_cost_model(model)
    peak = cost.get("peak_hbm_bytes")
    if isinstance(peak, (int, float)):
        recorder.gauge("mem/peak_hbm_bytes", peak)
    flops = cost.get("flops")
    if isinstance(flops, (int, float)):
        recorder.gauge("profile/flops_per_step", flops)
    recorder.emit_record("profile", kind=kind, device=spec.name,
                         peak_flops=spec.peak_flops,
                         peak_hbm_bw=spec.peak_hbm_bw,
                         hbm_capacity=spec.hbm_capacity, cost=cost,
                         **fields)
    return model


def capture_and_attach(recorder, jitted, args, kind: str = "train_step",
                       **fields) -> StepCostModel:
    """Capture ``jitted``'s compiled cost at ``args``' avals and attach
    it (:func:`attach_cost`).  NEVER raises — a backend without the
    analysis APIs yields a record whose cost says so."""
    try:
        with recorder.span("profile.capture"):
            cost = aot_capture(jitted, *args)
    except Exception as e:      # AOT API missing / lowering failed
        cost = {"unavailable": ["capture_failed"], "error": repr(e)}
    return attach_cost(recorder, cost, kind=kind, **fields)


# -- live device-memory gauges --------------------------------------------- #
def poll_device_memory(recorder):
    """One poll: ``mem/device.<id>.{bytes_in_use,peak_bytes_in_use,
    bytes_limit}`` gauges per local device, or a single
    ``mem/device.stats_unavailable`` marker on backends (CPU) whose
    ``memory_stats()`` returns nothing."""
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return
    got_any = False
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        got_any = True
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            v = _finite(stats.get(key))
            if v is not None:
                recorder.gauge(f"mem/device.{d.id}.{key}", v)
    if not got_any:
        recorder.gauge("mem/device.stats_unavailable", 1.0)


def install_device_memory_poller(recorder):
    """Attach :func:`poll_device_memory` as a recorder gauge poller
    (idempotent: repeated ``set_telemetry`` calls install it once)."""
    if poll_device_memory not in getattr(recorder, "_gauge_pollers", ()):
        recorder.add_gauge_poller(poll_device_memory)
    return recorder
