"""bigdl_tpu.observability.profile — cost/memory attribution.

The PR-1/PR-4 telemetry stack measures *what happened* (spans,
counters, latency percentiles).  This package adds *attribution* —
what fraction of the hardware a step uses and where each serving
request's latency went:

  * :mod:`specs` — device peak table (TPU v2–v5p, A100/H100/V100;
    env-overridable) replacing the scripts' magic ``197e12``.
  * :mod:`capture` — XLA ``cost_analysis``/``memory_analysis`` harvest
    from compiled executables, the :class:`StepCostModel` deriving
    per-step ``perf/mfu`` / ``perf/hbm_bw_util`` /
    ``mem/peak_hbm_bytes``, and live ``mem/device.*`` gauges.
  * :mod:`trace` — per-request trace IDs, span timelines and the
    Chrome-trace/Perfetto exporter behind ``ServingEngine.
    dump_chrome_trace()`` and the ``/trace`` endpoint.

Everything degrades gracefully: a backend without the analysis APIs
produces explicit ``unavailable`` markers, never wrong numbers and
never an exception on the training path.
"""
from __future__ import annotations

from .specs import DeviceSpec, device_spec, lookup, peak_flops
from .capture import (StepCostModel, aot_capture, attach_cost,
                      capture_and_attach, capture_compiled,
                      capture_enabled, install_device_memory_poller,
                      poll_device_memory)
from .trace import (RequestTrace, TraceRing, chrome_trace_events,
                    dump_chrome_trace)

__all__ = [
    "DeviceSpec", "device_spec", "lookup", "peak_flops",
    "StepCostModel", "aot_capture", "attach_cost", "capture_and_attach",
    "capture_compiled", "capture_enabled",
    "install_device_memory_poller", "poll_device_memory",
    "RequestTrace", "TraceRing", "chrome_trace_events",
    "dump_chrome_trace",
]
