"""Device peak-spec table: the denominators of every efficiency number.

MFU, HBM-bandwidth utilization and "how close to the memory wall" all
divide a measured quantity by a *hardware peak*.  The perf scripts used
to hardcode one magic constant (``197e12`` — TPU v5e bf16) and silently
report nonsense on any other backend; this table is the single source
of truth, resolved from ``jax.local_devices()[0].device_kind`` and
overridable per run via environment variables:

  ``BIGDL_PEAK_FLOPS``            peak dense FLOP/s (the MFU denominator)
  ``BIGDL_PEAK_HBM_BW``           peak HBM bytes/s
  ``BIGDL_HBM_CAPACITY_BYTES``    HBM capacity in bytes

Peaks are *per jax device* (a TensorCore on v2/v3, a chip on v4+) in
the dtype the MXU actually runs — bf16 for TPUs, bf16/fp16 tensor-core
for GPUs.  Unknown device kinds (including plain CPU) resolve to a
spec with ``None`` peaks: derived ratios are then reported as
explicitly *unavailable* rather than silently wrong.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, replace
from typing import Optional


@dataclass(frozen=True)
class DeviceSpec:
    """Hardware peaks for one jax device.  ``None`` = unknown — callers
    must degrade to an explicit unavailable marker, never guess."""
    name: str
    peak_flops: Optional[float] = None      # dense FLOP/s (MXU dtype)
    peak_hbm_bw: Optional[float] = None     # bytes/s
    hbm_capacity: Optional[float] = None    # bytes

    def complete(self) -> bool:
        return None not in (self.peak_flops, self.peak_hbm_bw,
                            self.hbm_capacity)


_GIB = 1024.0 ** 3

# substring-matched against a lowercased device_kind, FIRST match wins
# (order matters: "tpu v5p" must match before "tpu v5").  Sources:
# published TPU/GPU datasheets; per-core numbers for v2/v3 where a jax
# device is one TensorCore.
_TABLE = (
    ("tpu v5p",    DeviceSpec("TPU v5p", 459e12, 2765e9, 95 * _GIB)),
    ("tpu v5 lite", DeviceSpec("TPU v5e", 197e12, 819e9, 16 * _GIB)),
    ("tpu v5e",    DeviceSpec("TPU v5e", 197e12, 819e9, 16 * _GIB)),
    ("tpu v5",     DeviceSpec("TPU v5p", 459e12, 2765e9, 95 * _GIB)),
    ("tpu v4",     DeviceSpec("TPU v4", 275e12, 1228e9, 32 * _GIB)),
    ("tpu v3",     DeviceSpec("TPU v3 core", 61.5e12, 450e9, 16 * _GIB)),
    ("tpu v2",     DeviceSpec("TPU v2 core", 22.5e12, 350e9, 8 * _GIB)),
    ("h100",       DeviceSpec("H100", 989e12, 3352e9, 80 * _GIB)),
    ("a100",       DeviceSpec("A100", 312e12, 2039e9, 80 * _GIB)),
    ("v100",       DeviceSpec("V100", 125e12, 900e9, 16 * _GIB)),
)

_ENV_FIELDS = (("BIGDL_PEAK_FLOPS", "peak_flops"),
               ("BIGDL_PEAK_HBM_BW", "peak_hbm_bw"),
               ("BIGDL_HBM_CAPACITY_BYTES", "hbm_capacity"))


def lookup(device_kind: str) -> DeviceSpec:
    """Table lookup by device kind; unknown kinds get a no-peaks spec
    named after themselves (so reports still say WHAT was measured)."""
    kind = str(device_kind).lower()
    for needle, spec in _TABLE:
        if needle in kind:
            return spec
    return DeviceSpec(str(device_kind))


def _apply_env(spec: DeviceSpec) -> DeviceSpec:
    for var, field_name in _ENV_FIELDS:
        raw = os.environ.get(var)
        if not raw:
            continue
        try:
            spec = replace(spec, **{field_name: float(raw)})
        except ValueError:
            pass        # a malformed override must not kill training
    return spec


def device_spec(device=None) -> DeviceSpec:
    """The spec for ``device`` (default: first local jax device) with
    env overrides applied.  Never raises: a backend that fails to
    initialize yields an ``unknown`` spec, and env overrides still
    apply (the CPU-CI escape hatch for exercising real MFU numbers)."""
    kind = "unknown"
    try:
        if device is None:
            import jax
            device = jax.local_devices()[0]
        kind = device.device_kind
    except Exception:
        pass
    return _apply_env(lookup(kind))


def peak_flops(default: Optional[float] = None) -> Optional[float]:
    """Resolved peak FLOP/s: env override > device table > ``default``.
    The scripts' one-liner replacement for their hardcoded constants."""
    spec = device_spec()
    return spec.peak_flops if spec.peak_flops is not None else default
