"""Per-request tracing: span timelines + Chrome-trace/Perfetto export.

The serving metrics (PR 2) answer fleet questions — p99, shed rate,
batch fill.  They cannot answer "where did *this* request's latency
go?".  Here every admitted request carries a :class:`RequestTrace`: a
trace ID plus timestamped spans for each pipeline stage

    admit -> queue -> batch_gather -> compute -> reply

(shed requests end in a terminal ``shed`` span carrying the cause
instead), collected into a bounded :class:`TraceRing` and exported as
Chrome trace event format — the JSON that chrome://tracing and
https://ui.perfetto.dev open directly.  ``B``/``E`` begin/end pairs are
emitted (not ``X`` complete events) so nested and zero-length spans
render faithfully; each request gets its own ``tid`` track named after
its trace ID.

Timestamps are :func:`bigdl_tpu.observability.context.trace_now`
seconds — ``time.monotonic()``, the repo's ONE trace clock (the serving
queue's native clock); the exporter rebases them to microseconds from
the earliest event, which is all the trace viewers need.  Because every
subsystem stamps on the same clock, these per-request timelines merge
skew-free with tracing spans from other subsystems via
:func:`bigdl_tpu.observability.tracing.merge_perfetto`.

A request admitted with an upstream :class:`~..context.TraceContext`
(e.g. minted by the ReplicaSet front door) ADOPTS that trace id —
``ring.new_trace(model, ctx=ctx)`` — so the same id names the request
across the failover hop and into the decode slot lifetime.
"""
from __future__ import annotations

import json
import threading
import uuid
from collections import deque
from typing import Any, Dict, List, Optional

from ..context import TraceContext


class RequestTrace:
    """One request's span timeline.  Not thread-safe by itself: a trace
    is only ever touched by the submitting thread (admit/shed spans)
    and then the single batcher thread (queue/gather/compute/reply),
    with the queue handoff ordering the two."""

    __slots__ = ("trace_id", "model", "spans", "meta", "_open", "ctx")

    def __init__(self, trace_id: str, model: str,
                 ctx: Optional[TraceContext] = None):
        self.trace_id = trace_id
        self.model = model
        self.spans: List[tuple] = []     # (name, t0, t1, args|None)
        self.meta: Dict[str, Any] = {}
        self._open: Dict[str, float] = {}
        self.ctx = ctx                   # upstream TraceContext, if any

    def add_span(self, name: str, t0: float, t1: float, **args):
        self.spans.append((name, t0, max(t1, t0), args or None))

    def open(self, name: str, t: float):
        """Begin a span whose end lands on another thread/time."""
        self._open[name] = t

    def close(self, name: str, t: float, **args):
        t0 = self._open.pop(name, None)
        if t0 is not None:
            self.add_span(name, t0, t, **args)

    def discard(self, name: str):
        """Drop an open span that turned out not to happen (e.g. a
        ``queue`` span opened optimistically before a shed put)."""
        self._open.pop(name, None)

    def terminal(self, cause: str, t: float, name: str = "shed"):
        """Record the terminal cause span for a request that will never
        reply — ``shed`` (admission/deadline), ``error`` (batch
        execution failed), ``closed`` (engine shut down first).  Any
        still-open spans are closed at ``t`` so the track shows how far
        the request got."""
        for open_name in list(self._open):
            self.close(open_name, t)
        self.meta["cause"] = cause
        self.add_span(name, t, t, cause=cause)


class TraceRing:
    """Thread-safe bounded ring of *completed* request traces — the
    /trace endpoint's source.  Bounded exactly like the Recorder's
    record ring: tracing a heavy-traffic engine must cost O(capacity)
    memory, not O(requests served)."""

    def __init__(self, capacity: int = 512):
        self._ring: deque = deque(maxlen=max(int(capacity), 1))
        self._lock = threading.Lock()
        self.dropped = 0        # finished traces evicted by the bound

    def new_trace(self, model: str,
                  ctx: Optional[TraceContext] = None) -> RequestTrace:
        """Mint a trace; with ``ctx`` the request adopts the upstream
        trace id so one id spans admission → failover → decode."""
        if ctx is not None:
            return RequestTrace(ctx.trace_id, model, ctx=ctx)
        return RequestTrace(uuid.uuid4().hex[:16], model)

    def finish(self, trace: RequestTrace):
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(trace)

    def traces(self) -> List[RequestTrace]:
        with self._lock:
            return list(self._ring)

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()


def chrome_trace_events(traces, pid: int = 1) -> List[Dict[str, Any]]:
    """Chrome trace event list for ``traces``: one ``tid`` track per
    request (named via ``thread_name`` metadata), ``B``/``E`` pairs per
    span with the trace ID and batch/bucket attribution in ``args``."""
    events: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": "bigdl_tpu serving"}}]
    t_origin = min((t0 for tr in traces for _, t0, _, _ in tr.spans),
                   default=0.0)

    def us(t):
        return round((t - t_origin) * 1e6, 3)

    for tid, tr in enumerate(traces, start=1):
        events.append(
            {"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
             "args": {"name": f"req {tr.trace_id} ({tr.model})"}})
        for name, t0, t1, args in sorted(tr.spans, key=lambda s: s[1]):
            span_args = {"trace_id": tr.trace_id, "model": tr.model}
            span_args.update(tr.meta)
            if args:
                span_args.update(args)
            events.append({"ph": "B", "name": name, "cat": "serving",
                           "pid": pid, "tid": tid, "ts": us(t0),
                           "args": span_args})
            events.append({"ph": "E", "name": name, "cat": "serving",
                           "pid": pid, "tid": tid, "ts": us(t1)})
    return events


def dump_chrome_trace(traces, extra_meta: Optional[Dict[str, Any]]
                      = None) -> str:
    """Serialize ``traces`` as a Chrome-trace JSON document (load in
    chrome://tracing or ui.perfetto.dev)."""
    doc: Dict[str, Any] = {"traceEvents": chrome_trace_events(traces),
                           "displayTimeUnit": "ms"}
    if extra_meta:
        doc["otherData"] = dict(extra_meta)
    return json.dumps(doc)
