"""Fixed-capacity time series for metrics — the time dimension the
Recorder's instantaneous counters/gauges lack.

A :class:`MetricSeries` is a preallocated ``(timestamp, value)`` ring:
O(1) append, bounded memory, and *windowed* reducers (rate, delta,
mean, pXX) computed over a trailing **time** window rather than a
sample count — what SLO math needs ("p99 over the last 5 minutes"),
not "p99 over the last 2048 samples whatever their age".

A :class:`SeriesStore` keys many series by metric name behind one lock
and an **injected clock**, so tests drive virtual time and burn-rate
fixtures reproduce bit-for-bit.  The store is what

  * ``Recorder(keep_series=N)`` feeds from ``end_step`` (scalars,
    counters, gauges, histogram quantiles),
  * :class:`~bigdl_tpu.observability.aggregate.MetricsAggregator`
    feeds from every scrape, and
  * :class:`~bigdl_tpu.observability.slo.SLOEngine` evaluates
    objectives over.

``IntrospectionServer`` serves any attached store at
``/series?name=&window=`` as JSON-safe points.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .recorder import _quantile


class MetricSeries:
    """One metric's ``(t, v)`` ring: O(1) append, windowed reducers.

    The ring is two preallocated float lists; ``append`` overwrites the
    oldest slot once ``capacity`` points exist.  Timestamps are assumed
    non-decreasing (the store's single clock guarantees it); reducers
    never raise on empty/short windows — they return ``None``, so SLO
    evaluation can distinguish "no data" from "zero".
    """

    __slots__ = ("_t", "_v", "_cap", "_n")

    def __init__(self, capacity: int = 512):
        cap = max(int(capacity), 1)
        self._cap = cap
        self._t: List[float] = [0.0] * cap
        self._v: List[float] = [0.0] * cap
        self._n = 0                   # total points ever appended

    @property
    def capacity(self) -> int:
        return self._cap

    def __len__(self) -> int:
        return min(self._n, self._cap)

    def append(self, t: float, v: float):
        i = self._n % self._cap
        self._t[i] = float(t)
        self._v[i] = float(v)
        self._n += 1

    def last(self) -> Optional[Tuple[float, float]]:
        if self._n == 0:
            return None
        i = (self._n - 1) % self._cap
        return (self._t[i], self._v[i])

    def points(self, window: Optional[float] = None,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """Chronological ``[(t, v), ...]``; ``window`` keeps only points
        with ``t >= now - window`` (``now`` defaults to the newest
        timestamp, so a quiesced series still reduces over its tail)."""
        n = len(self)
        if n == 0:
            return []
        start = (self._n - n) % self._cap
        pts = [(self._t[(start + k) % self._cap],
                self._v[(start + k) % self._cap]) for k in range(n)]
        if window is None:
            return pts
        if now is None:
            now = pts[-1][0]
        cutoff = now - float(window)
        return [p for p in pts if p[0] >= cutoff]

    # -- windowed reducers ------------------------------------------------ #
    def mean(self, window: Optional[float] = None,
             now: Optional[float] = None) -> Optional[float]:
        pts = self.points(window, now)
        if not pts:
            return None
        return sum(v for _, v in pts) / len(pts)

    def delta(self, window: Optional[float] = None,
              now: Optional[float] = None) -> Optional[float]:
        """``last - first`` value over the window — a counter's increase
        (None with fewer than two points: one sample has no slope)."""
        pts = self.points(window, now)
        if len(pts) < 2:
            return None
        return pts[-1][1] - pts[0][1]

    def rate(self, window: Optional[float] = None,
             now: Optional[float] = None) -> Optional[float]:
        """Per-second increase over the window (counter semantics);
        ``None`` with fewer than two points or zero elapsed time."""
        pts = self.points(window, now)
        if len(pts) < 2:
            return None
        dt = pts[-1][0] - pts[0][0]
        if dt <= 0:
            return None
        return (pts[-1][1] - pts[0][1]) / dt

    def quantile(self, q: float, window: Optional[float] = None,
                 now: Optional[float] = None) -> Optional[float]:
        """Linear-interpolated percentile (``q`` in [0, 100]) of the
        point VALUES inside the window."""
        pts = self.points(window, now)
        if not pts:
            return None
        return _quantile(sorted(v for _, v in pts), q)

    def vmin(self, window: Optional[float] = None,
             now: Optional[float] = None) -> Optional[float]:
        pts = self.points(window, now)
        return min(v for _, v in pts) if pts else None

    def vmax(self, window: Optional[float] = None,
             now: Optional[float] = None) -> Optional[float]:
        pts = self.points(window, now)
        return max(v for _, v in pts) if pts else None


class SeriesStore:
    """Named :class:`MetricSeries` behind one lock and one clock.

    ``clock`` is any zero-arg callable returning seconds; inject a
    virtual clock in tests so windowed math is deterministic.  Series
    are created on first ``observe`` with the store's per-series
    ``capacity``.
    """

    def __init__(self, capacity: int = 512,
                 clock: Optional[Callable[[], float]] = None):
        self.capacity = max(int(capacity), 1)
        self.clock = clock if clock is not None else time.time
        self._lock = threading.Lock()
        self._series: Dict[str, MetricSeries] = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._series)

    def now(self) -> float:
        return float(self.clock())

    def observe(self, name: str, value: float,
                t: Optional[float] = None):
        """Append one point (``t`` defaults to the store clock)."""
        if t is None:
            t = self.now()
        with self._lock:
            s = self._series.get(name)
            if s is None:
                s = self._series[name] = MetricSeries(self.capacity)
            s.append(t, value)

    def get(self, name: str) -> Optional[MetricSeries]:
        with self._lock:
            return self._series.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def match(self, patterns) -> List[str]:
        """Names matching any fnmatch-style pattern in ``patterns`` (a
        string is one pattern).  A pattern without glob characters also
        matches as an exact name or a ``.../<pattern>`` suffix, so
        objectives can say ``decode/ttft_ms/p99`` without caring which
        source prefix the aggregator added."""
        from fnmatch import fnmatchcase
        if isinstance(patterns, str):
            patterns = (patterns,)
        names = self.names()
        out = []
        for n in names:
            for p in patterns:
                if ("*" in p or "?" in p or "[" in p):
                    if fnmatchcase(n, p):
                        out.append(n)
                        break
                elif n == p or n.endswith("/" + p):
                    out.append(n)
                    break
        return out

    def drop(self, patterns) -> int:
        """Remove every series whose name matches ``patterns`` (same
        semantics as :meth:`match`); returns how many were dropped.
        The deregistration seam: when a fleet member is deliberately
        scaled away its history leaves the store with it, so windowed
        reducers (and the SLO engine on top) stop judging a replica
        that no longer exists — as opposed to a *crashed* member,
        whose series are retained so dashboards see the gap."""
        victims = self.match(patterns)
        dropped = 0
        with self._lock:
            for name in victims:
                if self._series.pop(name, None) is not None:
                    dropped += 1
        return dropped

    def points(self, name: str, window: Optional[float] = None,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        s = self.get(name)
        return s.points(window, now) if s is not None else []

    def summary(self, name: str, window: Optional[float] = None,
                now: Optional[float] = None) -> Optional[Dict[str, float]]:
        """JSON-safe reducer bundle for ``/series``: n/mean/min/max/
        p50/p95/p99/delta/rate over the window; ``None`` for unknown
        names."""
        s = self.get(name)
        if s is None:
            return None
        pts = s.points(window, now)
        if not pts:
            return {"n": 0}
        vals = sorted(v for _, v in pts)
        out = {"n": len(pts), "mean": sum(vals) / len(vals),
               "min": vals[0], "max": vals[-1],
               "p50": _quantile(vals, 50.0),
               "p95": _quantile(vals, 95.0),
               "p99": _quantile(vals, 99.0)}
        d = s.delta(window, now)
        if d is not None:
            out["delta"] = d
        r = s.rate(window, now)
        if r is not None:
            out["rate"] = r
        return out
