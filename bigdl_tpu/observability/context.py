"""One trace clock + one trace context: the causal spine's currency.

Every subsystem that stamps a span — the serving engine's request
timeline, the decode engine's per-token spans, the Recorder's phase
timers, the checkpoint writer, the elastic supervisor, the autoscaler
— uses the SAME two primitives from this module:

  :func:`trace_now`        the repo's single trace clock.  It is
                           ``time.monotonic()`` seconds: the serving
                           queue's native clock (deadlines and the
                           PR-5 TraceRing already live on it), immune
                           to wall-clock steps, and shared across
                           threads of one process — which is exactly
                           what a MERGED timeline needs.  Recorder
                           span timers historically used
                           ``time.perf_counter()``; on CPython both
                           are monotonic but their epochs (and on some
                           platforms their rates) differ, so mixing
                           them skewed any export that put both on one
                           Perfetto track.  Everything now routes
                           through here; see docs/observability.md
                           "Distributed tracing" for the contract.

  :class:`TraceContext`    W3C-traceparent-shaped identity —
                           ``trace_id`` (32 hex), ``span_id``
                           (16 hex), ``parent_span_id`` — that flows
                           admission → failover → decode on the serve
                           side and step → checkpoint writer → elastic
                           transition on the train side.  Instances
                           are IMMUTABLE (``__setattr__`` raises), so
                           cross-thread propagation is just "pass the
                           object through the queue": the handoff
                           orders the reader after the writer and
                           there is no mutable state to race on —
                           GL003/racecheck-clean by construction.
"""
from __future__ import annotations

import time
import uuid
from typing import Any, Dict, Optional

#: the single trace clock (documented above; do not fork per subsystem)
TRACE_CLOCK = time.monotonic


def trace_now() -> float:
    """Seconds on the repo's one trace clock (``time.monotonic``)."""
    return time.monotonic()


class TraceContext:
    """Immutable W3C-shaped trace identity.

    ``new_root()`` mints a fresh trace; ``child()`` mints a new span id
    under the same trace with this context as the parent.  The string
    form round-trips through the ``traceparent`` header grammar
    (``00-<trace_id>-<span_id>-01``) so a future RPC boundary can carry
    it without a new format.
    """

    __slots__ = ("trace_id", "span_id", "parent_span_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_span_id: Optional[str] = None):
        trace_id, span_id = str(trace_id), str(span_id)
        if len(trace_id) != 32 or len(span_id) != 16:
            raise ValueError("trace_id must be 32 hex chars and "
                             f"span_id 16, got {trace_id!r}/{span_id!r}")
        object.__setattr__(self, "trace_id", trace_id)
        object.__setattr__(self, "span_id", span_id)
        object.__setattr__(self, "parent_span_id",
                           None if parent_span_id is None
                           else str(parent_span_id))

    # immutability IS the thread-safety story (see module docstring)
    def __setattr__(self, name, value):
        raise AttributeError("TraceContext is immutable; derive a new "
                             "context with child()")

    def __delattr__(self, name):
        raise AttributeError("TraceContext is immutable")

    # -- construction --------------------------------------------------- #
    @classmethod
    def new_root(cls) -> "TraceContext":
        """A fresh trace: new trace_id, new span_id, no parent."""
        return cls(uuid.uuid4().hex, uuid.uuid4().hex[:16])

    def child(self) -> "TraceContext":
        """A new span under the same trace, parented on this one."""
        return TraceContext(self.trace_id, uuid.uuid4().hex[:16],
                            parent_span_id=self.span_id)

    # -- wire form ------------------------------------------------------ #
    def to_traceparent(self) -> str:
        """``00-<trace_id>-<span_id>-01`` (sampled flag always set:
        nothing in-process is ever head-sampled away)."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, header: str) -> "TraceContext":
        parts = str(header).strip().split("-")
        if len(parts) != 4 or parts[0] != "00":
            raise ValueError(f"not a traceparent header: {header!r}")
        return cls(parts[1], parts[2])

    # -- plumbing ------------------------------------------------------- #
    def as_dict(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_span_id": self.parent_span_id}

    def __eq__(self, other):
        return (isinstance(other, TraceContext)
                and self.trace_id == other.trace_id
                and self.span_id == other.span_id
                and self.parent_span_id == other.parent_span_id)

    def __hash__(self):
        return hash((self.trace_id, self.span_id, self.parent_span_id))

    def __repr__(self):
        return (f"TraceContext({self.trace_id[:8]}…/"
                f"{self.span_id[:8]}…"
                + (f" <- {self.parent_span_id[:8]}…"
                   if self.parent_span_id else "") + ")")
