"""Goodput ledger: device-second accounting with badput attribution.

BigDL's evaluation (arXiv:1804.05839) could only *estimate* where its
scaling ceiling went — per-iteration scheduling and sync overhead lived
in the seams between subsystems, invisible to any one of them.  This
module is the layer that closes that gap for the rebuilt stack: every
second of wall-clock × device a job owns is classified into **goodput**
(productive step compute / decode-slot tokens) or exactly one of a
closed taxonomy of **badput buckets**:

  ===================  ==================================================
  bucket               meaning
  ===================  ==================================================
  goodput              productive step compute / live decode slots
  compile_warmup       XLA compiles, warmup batches, profile captures
  input_stall          waiting on the input pipeline (data_fetch / h2d)
  checkpoint_blocking  device→host snapshot + writer backpressure
  preemption_drain     draining in-flight work before yielding devices
  preemption_replan    planning/rebuilding after a capacity change
  preemption_reshard   resharding state onto the new mesh
  failover             re-dispatching after a replica failure
  probe_readmission    golden-probing an ejected/new replica back in
  queue_wait           capacity idle while admitted work sits queued
  brownout             serving degraded to shed load
  autoscale_transfer   devices in flight between donor and claimant
  idle                 owned but unattributed (the honest remainder)
  ===================  ==================================================

**Conservation by construction.**  The ledger is an *exclusive-bucket
interval accountant*: a monotonic cursor advances through wall time, and
every elapsed interval × current device count lands in exactly one
bucket (or is split across buckets whose shares sum to the interval).
``sum(buckets) == owned`` therefore holds to float rounding — the smoke
scripts assert it within 1%, and the racecheck test proves no
concurrent phase declaration can double-book a device-second (one lock
serialises every advance).

**No new per-step host syncs.**  Like the PR-5 cost model, attribution
folds at ``end_step``/scrape time: the Recorder hands the ledger its
already-collected span totals (``fold_step``), and producers mark
coarse control-plane phases (``phase("failover")``) whose cost is pure
wall-clock bookkeeping.

Wiring::

    rec.set_ledger(GoodputLedger(name="train", devices=8))
    # end_step now folds spans into buckets and stamps goodput/* gauges

    with ledger_phase(rec, "autoscale_transfer"):
        ...actuate...

Pool-level roll-up: each job ledger snapshots independently; a device
claimed by nobody is **pool idle** (the :class:`OwnershipLedger` on the
DevicePool), not job badput — :func:`rollup` keeps the two attributions
separate and computes the fleet goodput fraction over their union.
Metric families ``goodput/*`` are registered in docs/observability.md.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from . import context as _trace_clock

#: the closed taxonomy; "goodput" first, "idle" (unattributed) last
BUCKETS = (
    "goodput",
    "compile_warmup",
    "input_stall",
    "checkpoint_blocking",
    "preemption_drain",
    "preemption_replan",
    "preemption_reshard",
    "failover",
    "probe_readmission",
    "queue_wait",
    "brownout",
    "autoscale_transfer",
    "idle",
)

#: recorder span name -> badput bucket.  Spans not listed here are
#: productive step time (the residual of fold_step is goodput).
SPAN_BUCKETS = {
    "data_fetch": "input_stall",
    "h2d": "input_stall",
    "train_step_compile": "compile_warmup",
    "profile.capture": "compile_warmup",
    "serving.compile": "compile_warmup",
    "serving.warmup": "compile_warmup",
    "decode.compile": "compile_warmup",
    "decode.warmup": "compile_warmup",
    "checkpoint.blocking": "checkpoint_blocking",
    "elastic.reshard": "preemption_reshard",
}

#: ElasticSupervisor lifecycle state -> the background bucket wall time
#: flows into while that state holds (steps re-attribute their own
#: interval through fold_step, so "running" parks the background on
#: idle — only the gaps BETWEEN steps land there).
STATE_BUCKETS = {
    "planning": "preemption_replan",
    "resuming": "preemption_replan",
    "draining": "preemption_drain",
    "running": "idle",
    "idle": "idle",
}


class _Phase:
    """Context manager for one declared badput phase; time elapsing
    while it is the innermost active phase lands in its bucket."""
    __slots__ = ("_led", "_bucket", "_token")

    def __init__(self, led: "GoodputLedger", bucket: str):
        self._led = led
        self._bucket = bucket
        self._token = None

    def __enter__(self):
        self._token = self._led._push_phase(self._bucket)
        return self

    def __exit__(self, *exc):
        self._led._pop_phase(self._token)
        return False


class _NullPhase:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_PHASE = _NullPhase()


def ledger_phase(recorder, bucket: str):
    """``with ledger_phase(rec, "failover"): ...`` — a no-op context
    manager when ``recorder`` carries no ledger, so producers can
    instrument unconditionally (the disabled-recorder discipline)."""
    led = getattr(recorder, "get_ledger", None)
    led = led() if led is not None else None
    if led is None:
        return _NULL_PHASE
    return led.phase(bucket)


class GoodputLedger:
    """Exclusive-bucket device-second accountant for one job.

    Every public method advances the cursor under one lock, so buckets
    are disjoint by construction and ``sum(buckets) == owned`` holds to
    rounding regardless of which threads drive it.
    """

    def __init__(self, name: str = "job", devices: int = 1,
                 clock=None):
        self.name = str(name)
        self._clock = clock if clock is not None else _trace_clock.trace_now
        self._lock = threading.Lock()
        self._devices = max(0, int(devices))
        self._cursor = float(self._clock())
        self._owned = 0.0
        self._acc: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        # declared-phase stack; index 0 is the background phase wall
        # time defaults into, later entries are nested declarations
        # (innermost/newest wins)
        self._phases: List[List[Any]] = [[0, "idle"]]
        self._phase_seq = 0

    # -- core interval engine (callers hold no lock) ---------------------- #
    def _advance_locked(self, now: float, bucket: Optional[str] = None):
        dt = now - self._cursor
        if dt <= 0.0:
            self._cursor = max(self._cursor, now)
            return 0.0
        self._cursor = now
        dev_s = dt * self._devices
        self._owned += dev_s
        b = bucket if bucket is not None else self._phases[-1][1]
        self._acc[b] = self._acc.get(b, 0.0) + dev_s
        return dt

    def _now(self, now: Optional[float]) -> float:
        return float(now) if now is not None else float(self._clock())

    # -- device count ------------------------------------------------------ #
    def set_devices(self, n: int, now: Optional[float] = None):
        """Change the device count this job owns; time up to ``now`` is
        charged at the old count (the transfer instant is the edge)."""
        now = self._now(now)
        with self._lock:
            self._advance_locked(now)
            self._devices = max(0, int(n))
        return self

    @property
    def devices(self) -> int:
        return self._devices

    # -- declared phases --------------------------------------------------- #
    def _push_phase(self, bucket: str):
        now = self._now(None)
        with self._lock:
            self._advance_locked(now)
            self._phase_seq += 1
            token = [self._phase_seq, str(bucket)]
            self._phases.append(token)
            return token

    def _pop_phase(self, token):
        now = self._now(None)
        with self._lock:
            self._advance_locked(now)
            # remove THIS declaration wherever it sits: concurrent
            # phases from different threads unwind in any order, and
            # time always flowed to whichever was newest at the time
            for i in range(len(self._phases) - 1, 0, -1):
                if self._phases[i] is token:
                    del self._phases[i]
                    break

    def phase(self, bucket: str) -> _Phase:
        """Declare a badput phase for a ``with`` region — drain,
        replan, failover, probe, autoscale transfer.  Nested/concurrent
        phases never double-book: elapsed time goes to the newest
        active declaration only."""
        return _Phase(self, bucket)

    def declare(self, bucket: str, now: Optional[float] = None) -> str:
        """Set the *background* phase — what un-folded wall time counts
        as until the next declaration (the ElasticSupervisor state
        machine drives this).  Returns the previous background."""
        now = self._now(now)
        with self._lock:
            self._advance_locked(now)
            prev = self._phases[0][1]
            self._phases[0][1] = str(bucket)
            return prev

    # -- folding ----------------------------------------------------------- #
    def note_step_begin(self, now: Optional[float] = None):
        """Close out the inter-step gap (charged to the background
        phase) so the following ``fold_step`` attributes only the step's
        own interval."""
        now = self._now(now)
        with self._lock:
            self._advance_locked(now)
        return self

    def fold_step(self, dur: Optional[float],
                  spans: Optional[Dict[str, float]] = None,
                  now: Optional[float] = None):
        """Attribute one finished step's interval from its recorded
        span totals — the ``end_step``-time fold (PR-5 cost-model
        discipline: no extra host syncs, pure arithmetic over telemetry
        already collected).

        Of the elapsed interval since the cursor, up to ``dur`` seconds
        are the step: badput spans (``SPAN_BUCKETS``) are carved out
        first (clamped — overlapping spans can't mint time), the
        residual is goodput.  Anything elapsed beyond ``dur`` (a gap
        before the step that ``note_step_begin`` didn't close) goes to
        the background phase."""
        now = self._now(now)
        with self._lock:
            dt = now - self._cursor
            if dt <= 0.0:
                self._cursor = max(self._cursor, now)
                return self
            self._cursor = now
            dev = self._devices
            self._owned += dt * dev
            step = min(float(dur), dt) if dur is not None else dt
            gap = dt - step
            if gap > 0.0:
                bg = self._phases[-1][1]
                self._acc[bg] = self._acc.get(bg, 0.0) + gap * dev
            budget = step
            for sname, secs in (spans or {}).items():
                bucket = SPAN_BUCKETS.get(sname)
                if bucket is None or secs is None:
                    continue
                take = min(max(float(secs), 0.0), budget)
                if take <= 0.0:
                    continue
                self._acc[bucket] = self._acc.get(bucket, 0.0) + take * dev
                budget -= take
            if budget > 0.0:
                self._acc["goodput"] = self._acc.get("goodput", 0.0) \
                    + budget * dev
        return self

    def fold_split(self, weights: Dict[str, float],
                   now: Optional[float] = None):
        """Distribute the elapsed interval across buckets proportionally
        to ``weights`` — the decode engine's per-step attribution
        (``{"goodput": n_live, "queue_wait": waiting, "idle": spare}``).
        Weights summing to zero fall back to the background phase."""
        now = self._now(now)
        with self._lock:
            dt = now - self._cursor
            if dt <= 0.0:
                self._cursor = max(self._cursor, now)
                return self
            self._cursor = now
            dev = self._devices
            self._owned += dt * dev
            total = sum(max(float(w), 0.0) for w in weights.values())
            if total <= 0.0:
                bg = self._phases[-1][1]
                self._acc[bg] = self._acc.get(bg, 0.0) + dt * dev
                return self
            for bucket, w in weights.items():
                w = max(float(w), 0.0)
                if w:
                    self._acc[bucket] = self._acc.get(bucket, 0.0) \
                        + dt * dev * (w / total)
        return self

    # -- reading ------------------------------------------------------------ #
    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Advance to ``now`` and return the ledger as a plain dict:
        per-bucket device-seconds, owned total, goodput fraction, and
        the conservation error (≈0 by construction; asserted ≤1% by
        the chaos smokes)."""
        now = self._now(now)
        with self._lock:
            self._advance_locked(now)
            buckets = {b: self._acc.get(b, 0.0) for b in BUCKETS}
            owned = self._owned
        total = sum(buckets.values())
        return {
            "name": self.name,
            "devices": self._devices,
            "owned_s": owned,
            "buckets": buckets,
            "goodput_fraction": (buckets["goodput"] / owned) if owned
            else 0.0,
            "conservation_error": (abs(total - owned) / owned) if owned
            else 0.0,
        }

    def publish(self, recorder, now: Optional[float] = None
                ) -> Dict[str, Any]:
        """Snapshot and mirror onto ``recorder`` as ``goodput/*``
        gauges (every bucket, plus owned seconds and the fraction) so
        /metrics scrapes and the series store see the ledger without a
        step loop.  Gauges are written OUTSIDE this ledger's lock —
        recorder-lock/ledger-lock never nest in either order."""
        snap = self.snapshot(now)
        for b, v in snap["buckets"].items():
            recorder.gauge(f"goodput/{b}_s", v)
        recorder.gauge("goodput/owned_s", snap["owned_s"])
        recorder.gauge("goodput/fraction", snap["goodput_fraction"])
        recorder.gauge("goodput/devices", snap["devices"])
        return snap


class OwnershipLedger:
    """Pool-side accounting: of the devices a :class:`DevicePool`
    holds, how many device-seconds were claimed by SOME job vs idle in
    the pool.  A device claimed by nobody is **pool idle** — a
    scheduling/capacity question — and must never be booked as any
    job's badput; this ledger is how :func:`rollup` keeps the two
    attributions disjoint."""

    def __init__(self, total: int, clock=None):
        self._clock = clock if clock is not None else _trace_clock.trace_now
        self._lock = threading.Lock()
        self._total = max(0, int(total))
        self._claimed = 0
        self._cursor = float(self._clock())
        self._claimed_s = 0.0
        self._idle_s = 0.0

    def note(self, claimed: int, total: Optional[int] = None,
             now: Optional[float] = None):
        """Advance at the OLD occupancy, then adopt the new one — call
        after every claim/transfer/release/reassign mutation."""
        now = float(now) if now is not None else float(self._clock())
        with self._lock:
            dt = now - self._cursor
            if dt > 0.0:
                self._cursor = now
                c = min(self._claimed, self._total)
                self._claimed_s += dt * c
                self._idle_s += dt * (self._total - c)
            else:
                self._cursor = max(self._cursor, now)
            self._claimed = max(0, int(claimed))
            if total is not None:
                self._total = max(0, int(total))
        return self

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = float(now) if now is not None else float(self._clock())
        with self._lock:
            dt = now - self._cursor
            if dt > 0.0:
                self._cursor = now
                c = min(self._claimed, self._total)
                self._claimed_s += dt * c
                self._idle_s += dt * (self._total - c)
            return {"devices": self._total,
                    "claimed": self._claimed,
                    "claimed_s": self._claimed_s,
                    "pool_idle_s": self._idle_s,
                    "owned_s": self._claimed_s + self._idle_s}


def rollup(jobs: Dict[str, Dict[str, Any]],
           pool: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Fold per-job ledger snapshots (+ an optional pool ownership
    snapshot) into one fleet-level attribution: summed buckets, pool
    idle kept as its own row, and the goodput fraction over everything
    the fleet owned.  This is what ``/goodput`` serves and
    ``trace_summary goodput`` renders."""
    buckets = {b: 0.0 for b in BUCKETS}
    owned = 0.0
    for snap in jobs.values():
        for b, v in snap.get("buckets", {}).items():
            buckets[b] = buckets.get(b, 0.0) + float(v)
        owned += float(snap.get("owned_s", 0.0))
    pool_idle = float(pool.get("pool_idle_s", 0.0)) if pool else 0.0
    total_owned = owned + pool_idle
    out = {
        "jobs": jobs,
        "buckets": buckets,
        "pool_idle_s": pool_idle,
        "owned_s": total_owned,
        "goodput_fraction": (buckets["goodput"] / total_owned)
        if total_owned else 0.0,
        "conservation_error": (
            abs(sum(buckets.values()) + pool_idle - total_owned)
            / total_owned) if total_owned else 0.0,
    }
    if pool:
        out["pool"] = pool
    return out
