"""bigdl_tpu.observability.health — training-health layer.

The PR-1 Recorder is write-only telemetry: sinks you read after the
fact.  This package adds the *operate-a-running-job* half (≙ the
reference BigDL's Spark-UI live metrics and executor health signals):

  * :class:`HealthMonitor` (:mod:`.sentinels`) — numeric-health
    sentinels over each completed step record: NaN/Inf in loss or
    gradients, loss-spike (EWMA z-score), gradient-norm explosion.
    The device-side checks ride the existing jitted step's
    ``health_scalars`` output (``jnp.isfinite`` reductions folded into
    the compiled program), so detection adds **no extra host sync**.
    Policies: ``warn`` / ``record`` / ``raise`` (:class:`DivergenceError`)
    / ``rollback`` (restore the last committed checkpoint via the PR-3
    auto-resume path).
  * :class:`StallWatchdog` (:mod:`.watchdog`) — a daemon thread that
    flags a step exceeding a rolling p99×k budget, and attributes
    per-host step-time skew to name the straggler under
    :class:`~bigdl_tpu.parallel.spmd.SpmdTrainer`.
  * :class:`FlightRecorder` (:mod:`.flight`) — dumps the Recorder's
    bounded ring of recent step records + health events atomically to
    ``flight_<ts>.json`` on unhandled exception, divergence, or
    SIGTERM, so a dead job leaves its last seconds behind.

The live view over all of this is
:class:`~bigdl_tpu.observability.http.IntrospectionServer`
(``/metrics`` ``/healthz`` ``/records``), attachable via
``serve_metrics(port)`` on ``Optimizer``, ``SpmdTrainer`` and
``ServingEngine``.
"""
from __future__ import annotations

from .sentinels import DivergenceError, HealthMonitor
from .watchdog import StallWatchdog, attribute_stragglers
from .flight import FlightRecorder

__all__ = [
    "DivergenceError", "HealthMonitor", "StallWatchdog",
    "attribute_stragglers", "FlightRecorder",
]
