"""Crash flight recorder: dump the telemetry ring when the job dies.

The Recorder keeps a bounded ring of the last N emitted records
(``Recorder.recent_records``).  :class:`FlightRecorder` turns that ring
into a post-mortem artifact: one ``flight_<ts>.json`` written atomically
(tmp + fsync + ``os.replace`` + dir fsync — the same commit discipline
as ``utils/file.py`` and the checkpoint manifest) containing the recent
step records, counter/gauge snapshot, and the trigger reason.

``install()`` chains — never replaces — the process crash paths:

  * ``sys.excepthook``: an unhandled exception dumps first, then the
    previous hook (usually the default traceback printer) runs
  * SIGTERM: the dump happens first, then the *previous* handler runs —
    so the PR-3 :class:`~bigdl_tpu.checkpoint.preemption.PreemptionHandler`
    installed before us still gets its flag set and the final preemption
    checkpoint still commits

Divergence dumps don't come through either hook: the
:class:`~bigdl_tpu.observability.health.sentinels.HealthMonitor` calls
:meth:`FlightRecorder.dump` directly before raising, so the dump exists
even when a ``rollback`` policy swallows the exception.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from ..sinks import _json_default


class FlightRecorder:
    """Dumps ``recorder``'s ring to ``out_dir/flight_<ts>.json``."""

    def __init__(self, recorder, out_dir: str, max_records: Optional[int] = None):
        self.recorder = recorder
        self.out_dir = out_dir
        self.max_records = max_records
        self.dumps: List[str] = []          # paths written, oldest first
        self._dumped_keys = set()           # dedupe one failure's dumps
        self._pending: set = set()          # paths claimed mid-write
        # RLock, not Lock: a signal delivered while dump() holds the
        # lock runs the chained handler on the SAME thread, which dumps
        # again — a plain Lock would self-deadlock through the scheduler
        # grace window
        self._lock = threading.RLock()
        self._installed = False
        self._prev_excepthook = None
        self._hook_fn = None                # our excepthook, for identity
        self._prev_signals: Dict[int, Any] = {}
        self._sig_hooks: Dict[int, Any] = {}    # our handlers, for identity

    # -- the dump --------------------------------------------------------- #
    def dump(self, reason: str, extra: Optional[Dict[str, Any]] = None,
             key=None) -> Optional[str]:
        """Write one atomic flight dump; returns its path.  ``key`` (e.g.
        ``id(exc)``) dedupes: the training driver dumps a propagating
        exception at the loop, and the chained excepthook would dump the
        SAME failure again at process exit — the second call no-ops and
        returns None."""
        if key is not None:
            with self._lock:
                if key in self._dumped_keys:
                    return None
                self._dumped_keys.add(key)
        rec = self.recorder
        snap = rec.snapshot()
        payload: Dict[str, Any] = {
            "type": "flight",
            "reason": str(reason),
            "time": time.time(),
            "last_step": rec.last_step(),
            "step_age_s": rec.step_age(),
            "counters": snap["counters"],
            "gauges": snap["gauges"],
            "records": rec.recent_records(self.max_records),
        }
        if extra:
            payload.update(extra)
        with self._lock:
            os.makedirs(self.out_dir, exist_ok=True)
            base = f"flight_{int(time.time() * 1e3)}"
            path = os.path.join(self.out_dir, base + ".json")
            n = 0
            # two dumps in the same ms — including a re-entrant dump
            # (signal mid-write) whose outer path has no file yet, only
            # a _pending claim; a shared path would mean a shared tmp,
            # and the inner os.replace would consume the outer's tmp
            while os.path.exists(path) or path in self._pending:
                n += 1
                path = os.path.join(self.out_dir, f"{base}_{n}.json")
            self._pending.add(path)
            try:
                tmp = f"{path}.tmp-{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(payload, f, default=_json_default)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            finally:
                self._pending.discard(path)
            try:        # directory entry durable too (same as manifest)
                dfd = os.open(self.out_dir, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:
                pass
            self.dumps.append(path)
        return path

    def _dump_quietly(self, reason: str, extra=None, key=None):
        try:
            self.dump(reason, extra, key=key)
        except Exception as e:      # noqa: BLE001 — crash path
            print(f"[flight] dump failed: {e!r}", file=sys.stderr)

    # -- crash-path hooks -------------------------------------------------- #
    def install(self, signals=(signal.SIGTERM,)) -> "FlightRecorder":
        """Chain onto ``sys.excepthook`` and the given signals."""
        with self._lock:
            if self._installed:
                return self
            prev_hook = sys.excepthook
            self._prev_excepthook = prev_hook

            def hook(exc_type, exc, tb):
                self._dump_quietly(f"unhandled:{exc_type.__name__}",
                                   {"error": repr(exc)}, key=id(exc))
                prev_hook(exc_type, exc, tb)

            sys.excepthook = hook
            self._hook_fn = hook
            try:
                for s in signals:
                    prev = signal.getsignal(s)

                    def handler(signum, frame, _prev=prev):
                        self._dump_quietly(f"signal:{signum}")
                        if callable(_prev):
                            _prev(signum, frame)
                        elif (_prev == signal.SIG_DFL
                              and signal.getsignal(signum) is handler):
                            # the default disposition (terminate) must
                            # still apply: restore it and re-deliver —
                            # dump-and-ignore would eat the scheduler's
                            # grace window.  Only while we are the
                            # ACTIVE handler though: if something
                            # installed over us and chained in (the
                            # preemption handler), THAT owner decides
                            # the disposition — terminating here would
                            # kill its graceful final checkpoint
                            signal.signal(signum, signal.SIG_DFL)
                            signal.raise_signal(signum)
                        # SIG_IGN: stay ignored

                    signal.signal(s, handler)
                    self._prev_signals[s] = prev
                    self._sig_hooks[s] = handler
            except ValueError:
                # signal.signal only works on the main thread; excepthook
                # chaining above still covers unhandled exceptions
                print("[flight] not on main thread; signal hooks skipped")
            self._installed = True
            return self

    def _relink_displaced(self, s, prev):
        try:    # lazy: observability must not hard-depend on checkpoint
            from ...checkpoint.preemption import dispatcher
        except ImportError:
            return
        dispatcher().relink_prev(s, self._sig_hooks.get(s), prev)

    def uninstall(self):
        """Restore the dispositions we displaced — but ONLY where we are
        still the active hook.  A later installer (e.g. the preemption
        dispatcher hooking SIGTERM over us) owns the registration now;
        blindly restoring our saved prev would silently unhook it —
        every PreemptionHandler in the process would miss the
        scheduler's kill grace window (same guard as the dispatcher's
        own unregister)."""
        with self._lock:
            if not self._installed:
                return
            if self._prev_excepthook is not None:
                if sys.excepthook is self._hook_fn:
                    sys.excepthook = self._prev_excepthook
                self._prev_excepthook = None
                self._hook_fn = None
            for s, prev in self._prev_signals.items():
                try:
                    if signal.getsignal(s) is self._sig_hooks.get(s):
                        signal.signal(s, prev)
                    else:
                        # displaced: the preemption dispatcher may have
                        # saved OUR handler as its chained prev — swap
                        # in what we displaced, so the dead closure of
                        # an uninstalled recorder is never called (or
                        # restored to the OS) after teardown
                        self._relink_displaced(s, prev)
                except ValueError:
                    pass
            self._prev_signals.clear()
            self._sig_hooks.clear()
            self._installed = False


def read_flight(path: str) -> Dict[str, Any]:
    """Parse one flight dump back (plain json.load, named for symmetry
    with ``sinks.read_jsonl``)."""
    with open(path) as f:
        return json.load(f)
