"""Numeric-health sentinels: NaN/Inf, loss spikes, gradient explosions.

The detection split is deliberate:

  device side   a handful of reductions *inside the already-jitted
                step* — ``health_scalars`` computes grad/param/update
                norms and a ``jnp.isfinite`` non-finite-element count.
                They travel to the host in the same step record the
                loss does, so sentinels add **zero** extra host syncs.
  host side     :class:`HealthMonitor` inspects each completed step
                record (the floats ``Recorder.end_step`` already
                produced) and trips conditions:

                  ``non_finite_loss``   loss is NaN/Inf
                  ``non_finite_grads``  grad_norm NaN/Inf, or the
                                        in-step isfinite count > 0
                  ``loss_spike``        |loss − EWMA| > z·σ (EWMA
                                        mean/variance, warmup-gated)
                  ``grad_explosion``    grad_norm above an absolute
                                        limit, or > factor × its EWMA

Every tripped condition becomes a ``health_event`` record (ring buffer
+ sinks + ``health/events`` counter).  What happens next is the
*policy*:

  ``warn``      print and keep training (default)
  ``record``    telemetry only
  ``raise``     dump a flight record and raise :class:`DivergenceError`
  ``rollback``  like ``raise`` — the training driver catches the error
                and restores the last committed checkpoint via the
                PR-3 auto-resume path (see ``Optimizer.set_health``)

``loss_spike`` is advisory by default (a warm restart or LR change
spikes loss legitimately); pass ``fatal_conditions`` to promote it.
"""
from __future__ import annotations

import math
import time
from typing import Any, Dict, List, Optional, Sequence

POLICIES = ("warn", "record", "raise", "rollback")

_DEFAULT_FATAL = ("non_finite_loss", "non_finite_grads", "grad_explosion")


class DivergenceError(RuntimeError):
    """Raised by :class:`HealthMonitor` under ``raise``/``rollback``
    policy; carries the tripped events."""

    def __init__(self, events: List[Dict[str, Any]]):
        self.events = list(events)
        conds = ", ".join(f"{e['condition']}@step {e.get('step')}"
                          for e in self.events)
        super().__init__(f"training diverged: {conds}")


class HealthMonitor:
    """Checks step records; owns the policy response.

    ``flight``: an optional
    :class:`~bigdl_tpu.observability.health.flight.FlightRecorder` —
    fatal events dump before the error propagates, so the artifact
    exists even when ``rollback`` swallows the exception.
    """

    def __init__(self, policy: str = "warn", recorder=None, flight=None,
                 spike_zscore: float = 10.0, warmup_steps: int = 20,
                 ewma_alpha: float = 0.05,
                 grad_norm_limit: Optional[float] = None,
                 grad_explosion_factor: Optional[float] = 100.0,
                 fatal_conditions: Sequence[str] = _DEFAULT_FATAL):
        if policy not in POLICIES:
            raise ValueError(f"policy {policy!r} not in {POLICIES}")
        self.policy = policy
        self.recorder = recorder
        self.flight = flight
        self.spike_zscore = float(spike_zscore)
        self.warmup_steps = int(warmup_steps)
        self.ewma_alpha = float(ewma_alpha)
        self.grad_norm_limit = grad_norm_limit
        self.grad_explosion_factor = grad_explosion_factor
        self.fatal_conditions = tuple(fatal_conditions)
        self.events: List[Dict[str, Any]] = []
        self.rollbacks = 0            # incremented by the driver
        self._recovered_upto = 0      # events before this index were
                                      # resolved by a rollback
        # EWMA state (loss mean/var, grad-norm mean), warmup-gated
        self._n = 0
        self._loss_mean: Optional[float] = None
        self._loss_var = 0.0
        self._gn_mean: Optional[float] = None

    # -- checks ----------------------------------------------------------- #
    @staticmethod
    def _num(v) -> Optional[float]:
        return float(v) if isinstance(v, (int, float)) else None

    def check_record(self, record: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Inspect one step record; returns tripped events (possibly
        raising per policy).  Non-step records pass through untouched."""
        if not isinstance(record, dict) or record.get("type") != "step":
            return []
        scalars = record.get("scalars") or {}
        step = record.get("step")
        events: List[Dict[str, Any]] = []

        def trip(condition, metric, value, threshold=None):
            events.append({
                "type": "health_event", "condition": condition,
                "step": step, "metric": metric,
                "value": None if value is None else float(value),
                "threshold": threshold, "action": self.policy,
                "time": time.time(),
            })

        loss = self._num(scalars.get("loss"))
        if loss is not None and not math.isfinite(loss):
            trip("non_finite_loss", "loss", loss)

        gn = self._num(scalars.get("grad_norm"))
        nonfinite = self._num(scalars.get("nonfinite_grads"))
        if (gn is not None and not math.isfinite(gn)) or \
                (nonfinite is not None and nonfinite > 0):
            trip("non_finite_grads",
                 "nonfinite_grads" if nonfinite else "grad_norm",
                 nonfinite if nonfinite else gn)

        if loss is not None and math.isfinite(loss):
            if (self._n >= self.warmup_steps and self._loss_mean is not None
                    and self._loss_var > 0):
                sd = math.sqrt(self._loss_var)
                z = abs(loss - self._loss_mean) / max(sd, 1e-12)
                if z > self.spike_zscore:
                    trip("loss_spike", "loss_zscore", z, self.spike_zscore)
            a = self.ewma_alpha
            if self._loss_mean is None:
                self._loss_mean = loss
            else:
                d = loss - self._loss_mean
                self._loss_mean += a * d
                # EWMA variance (West 1979 incremental form)
                self._loss_var = (1 - a) * (self._loss_var + a * d * d)

        if gn is not None and math.isfinite(gn):
            if self.grad_norm_limit is not None and gn > self.grad_norm_limit:
                trip("grad_explosion", "grad_norm", gn, self.grad_norm_limit)
            elif (self.grad_explosion_factor is not None
                  and self._n >= self.warmup_steps
                  and self._gn_mean is not None and self._gn_mean > 0
                  and gn > self.grad_explosion_factor * self._gn_mean):
                trip("grad_explosion", "grad_norm", gn,
                     self.grad_explosion_factor * self._gn_mean)
            a = self.ewma_alpha
            self._gn_mean = gn if self._gn_mean is None else \
                self._gn_mean + a * (gn - self._gn_mean)

        self._n += 1
        if events:
            self._handle(events)
        return events

    # -- policy ----------------------------------------------------------- #
    def _handle(self, events: List[Dict[str, Any]]):
        self.events.extend(events)
        rec = self.recorder
        if rec is not None:
            for ev in events:
                rec.inc("health/events")
                rec.inc(f"health/{ev['condition']}")
                rec.gauge("health/last_event_step",
                          -1 if ev.get("step") is None else ev["step"])
                rec.emit_record("health_event",
                                **{k: v for k, v in ev.items()
                                   if k != "type"})
        fatal = [e for e in events
                 if e["condition"] in self.fatal_conditions]
        if self.policy == "warn" or (self.policy != "record" and not fatal):
            for ev in events:
                print(f"[health] {ev['condition']} at step {ev['step']}: "
                      f"{ev['metric']}={ev['value']}"
                      + (f" (threshold {ev['threshold']:.4g})"
                         if ev.get("threshold") is not None else ""),
                      flush=True)
        if fatal and self.policy in ("raise", "rollback"):
            err = DivergenceError(fatal)
            if self.flight is not None:
                try:
                    # keyed on the error so the chained excepthook won't
                    # dump the same divergence a second time at exit
                    self.flight.dump("divergence", {"events": fatal},
                                     key=id(err))
                except Exception as e:   # dump failure must not mask
                    print(f"[health] flight dump failed: {e!r}", flush=True)
            raise err

    def reset_statistics(self):
        """Forget the EWMA baselines (kept events stay).  Called after a
        rollback: the restored loss may legitimately sit far from the
        diverged run's statistics, and a stale baseline would re-trip
        the spike sentinel on the first healthy step."""
        self._n = 0
        self._loss_mean = None
        self._loss_var = 0.0
        self._gn_mean = None

    def mark_recovered(self):
        """A rollback restored good state: prior events no longer count
        against :attr:`healthy` (they stay in ``events`` for the log)."""
        self._recovered_upto = len(self.events)

    @property
    def healthy(self) -> bool:
        """False once a fatal condition tripped without a subsequent
        recovery (rollback)."""
        return not any(e["condition"] in self.fatal_conditions
                       for e in self.events[self._recovered_upto:])
