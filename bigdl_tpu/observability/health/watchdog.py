"""Stall & straggler detection.

A wedged training loop is invisible to record-based telemetry — the
step that never finishes never emits.  :class:`StallWatchdog` is a
daemon thread polling ``Recorder.step_age()`` (seconds since the
pending step opened, or since the last one closed) against a **rolling
budget**: p99 of the recent step durations × ``factor`` (floored, so a
cold compile or an empty history can't trip it).  Crossing the budget:

  * ``health/stalled`` gauge flips to 1 (what ``/healthz`` reports)
  * one ``health_event`` record (``condition="stall"``) per episode —
    recovery flips the gauge back and re-arms the event
  * ``health/stall_seconds`` accrues while stalled

**Hang-abort escalation** closes the gap between seeing a wedge and
surviving it: :meth:`StallWatchdog.set_escalation` arms a grace period
past stall *detection* after which the watchdog dumps a flight record
(every counter/gauge/recent record at the moment of the hang — the
post-mortem an operator would otherwise reconstruct from memory),
emits a ``hang_abort`` health event + ``health/hang_aborts`` count,
and invokes an abort callback ONCE per stall episode.  The
``ElasticSupervisor`` wires that callback to raise in its step loop,
turning a wedged step into a replan-and-resume instead of an operator
page; standalone users can wire ``os._exit`` style process abort for
hangs stuck in native code.  The callback and flight dump run OFF the
verdict lock, so a slow dump can't block concurrent /healthz scrapes.

Straggler attribution: step records under a multi-host
:class:`SpmdTrainer` carry a ``host`` scalar; :func:`attribute_stragglers`
groups records per host and names the slowest one and its skew vs the
fleet median — the "which worker is dragging the synchronous step"
question the BigDL paper answers with Spark's straggler metrics.  It
needs records from MORE than one host in one list: a merged ring (one
shared recorder/aggregated JSONL), or ``SpmdTrainer.straggler_report()``
which does the cross-host gather; a single process's own ring yields
None, and the watchdog's inline stall-event attribution is best-effort
on whatever the local ring holds.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional


def _p99(durs: List[float]) -> float:
    s = sorted(durs)
    return s[min(len(s) - 1, int(0.99 * (len(s) - 1) + 0.999999))]


def attribute_stragglers(records: List[Dict[str, Any]]
                         ) -> Optional[Dict[str, Any]]:
    """Per-host mean step time from records carrying a ``host`` scalar.

    Returns ``{"hosts": {host: mean_s}, "straggler": host,
    "skew": slowest/median}`` or None when records aren't per-host
    (single-process runs)."""
    by_host: Dict[int, List[float]] = {}
    for r in records:
        if r.get("type") != "step":
            continue
        host = (r.get("scalars") or {}).get("host")
        dur = r.get("dur")
        if host is None or not isinstance(dur, (int, float)):
            continue
        by_host.setdefault(int(host), []).append(float(dur))
    if len(by_host) < 2:
        return None
    means = {h: sum(v) / len(v) for h, v in by_host.items()}
    ranked = sorted(means.items(), key=lambda kv: kv[1])
    # lower-middle for even host counts: the slowest host must never be
    # its own baseline (a 2-host fleet would always report skew 1.0)
    median = ranked[(len(ranked) - 1) // 2][1]
    slowest, slowest_mean = ranked[-1]
    return {"hosts": means, "straggler": slowest,
            "skew": slowest_mean / max(median, 1e-12)}


class StallWatchdog:
    """Background budget check over ``recorder``'s liveness signal."""

    def __init__(self, recorder, factor: float = 5.0,
                 min_history: int = 8, floor_seconds: float = 2.0,
                 poll_interval: float = 0.25):
        self.recorder = recorder
        self.factor = float(factor)
        self.min_history = int(min_history)
        self.floor_seconds = float(floor_seconds)
        self.poll_interval = float(poll_interval)
        self._stop = threading.Event()
        self._stalled = False
        self._thread: Optional[threading.Thread] = None
        self._stall_started: Optional[float] = None
        self.stall_episodes = 0
        # check_once runs on the polling thread AND every /healthz
        # scrape thread: serialize the verdict state
        self._check_lock = threading.Lock()
        # a stopped watchdog (training finished) must not flag the
        # ever-growing idle step_age as a stall; fresh instances are
        # active so check_once works without a polling thread
        self._active = True
        # legitimate between-step work (validation, a sync checkpoint
        # commit) suspends the verdict; _resumed_at re-baselines the
        # idle age so the suspended interval can't trip the budget
        # right after resume
        self._suspend = 0
        self._resumed_at: Optional[float] = None
        # hang-abort escalation (set_escalation): grace past stall
        # detection, then flight dump + abort callback, once/episode
        self._escalate_after: Optional[float] = None
        self._esc_callback: Optional[Callable] = None
        self._esc_flight = None
        self._escalated = False         # this episode already escalated
        self._esc_fire = False          # check_once: fire outside lock

    # -- budget ------------------------------------------------------------ #
    def budget(self) -> Optional[float]:
        """Current stall budget in seconds: max(p99 × factor, floor);
        None until ``min_history`` completed steps exist."""
        durs = [r["dur"] for r in
                self.recorder.recent_records(rec_type="step")
                if isinstance(r.get("dur"), (int, float))]
        if len(durs) < self.min_history:
            return None
        return max(_p99(durs) * self.factor, self.floor_seconds)

    def set_escalation(self, grace: float, callback: Optional[Callable],
                       flight=None) -> "StallWatchdog":
        """Arm hang-abort escalation: ``grace`` seconds after a stall is
        DETECTED (i.e. budget + grace after the step wedged), dump a
        flight record via ``flight`` (a FlightRecorder, or None) and
        invoke ``callback()`` — once per stall episode; recovery
        re-arms.  ``grace=None`` disarms."""
        with self._check_lock:
            self._escalate_after = None if grace is None else float(grace)
            self._esc_callback = callback
            self._esc_flight = flight
            self._escalated = False
        return self

    def check_once(self) -> bool:
        """One poll; returns the current stalled verdict.  Public so
        tests (and /healthz handlers without a running thread) can
        evaluate the budget synchronously.  Thread-safe: the polling
        thread and concurrent /healthz scrapes share the verdict."""
        with self._check_lock:
            verdict = self._check_locked()
            fire = self._esc_fire
            self._esc_fire = False
        if fire:
            self._escalate()
        return verdict

    def suspended(self):
        """Context manager marking legitimate between-step work (an
        epoch-end validation pass, a synchronous checkpoint commit) so
        a LONG one doesn't read as a wedged step loop.  Re-entrant; the
        trainers wrap their validation/checkpoint blocks in it."""
        @contextlib.contextmanager
        def cm():
            with self._check_lock:
                self._suspend += 1
            try:
                yield
            finally:
                with self._check_lock:
                    self._suspend -= 1
                    self._resumed_at = time.time()
        return cm()

    def _check_locked(self) -> bool:
        rec = self.recorder
        if not self._active or self._suspend:
            self._clear_stall_locked()
            return False
        age = rec.step_age()
        # time spent suspended is not loop inactivity: measure from the
        # resume point until the next step record re-baselines properly
        if (age is not None and self._resumed_at is not None
                and not rec.step_in_flight()):
            age = min(age, time.time() - self._resumed_at)
        b = self.budget()
        if age is not None and b is not None and age > b:
            if not self._stalled:
                self._stalled = True
                self._stall_started = time.time()
                self.stall_episodes += 1
                rec.gauge("health/stalled", 1)
                ev = {"condition": "stall", "step": rec.last_step(),
                      "metric": "step_age_s", "value": age,
                      "threshold": b, "action": "record"}
                stragglers = attribute_stragglers(rec.recent_records())
                if stragglers is not None:
                    ev["straggler"] = stragglers["straggler"]
                    ev["skew"] = stragglers["skew"]
                rec.emit_record("health_event", **ev)
                rec.inc("health/events")
                rec.inc("health/stall")
                print(f"[health] stall: step age {age:.2f}s exceeds "
                      f"budget {b:.2f}s (p99×{self.factor:g})"
                      + (f"; straggler host {ev['straggler']} "
                         f"({ev['skew']:.2f}x median)"
                         if "straggler" in ev else ""), flush=True)
        elif self._stalled:
            self._clear_stall_locked()
        if (self._stalled and self._escalate_after is not None
                and not self._escalated
                and self._stall_started is not None
                and time.time() - self._stall_started
                >= self._escalate_after):
            # mark under the lock (one escalation per episode even with
            # concurrent scrapes), FIRE outside it — the flight dump
            # does real IO and the callback is arbitrary caller code
            self._escalated = True
            self._esc_fire = True
        return self._stalled

    def _escalate(self):
        """The hang-abort action (called OFF the verdict lock): flight
        dump + health event + abort callback.  A failing dump must not
        eat the abort — the callback is the part that un-wedges."""
        rec = self.recorder
        age = rec.step_age()
        rec.inc("health/hang_aborts")
        rec.inc("health/events")
        rec.emit_record("health_event", condition="hang_abort",
                        step=rec.last_step(), metric="step_age_s",
                        value=age, threshold=self._escalate_after,
                        action="abort")
        print(f"[health] hang-abort: stalled past the "
              f"{self._escalate_after:g}s escalation grace (step age "
              f"{age if age is None else round(age, 2)}s); dumping "
              "flight record and invoking the abort callback",
              flush=True)
        if self._esc_flight is not None:
            try:
                self._esc_flight.dump("hang_abort",
                                      extra={"step_age_s": age})
            except Exception as e:
                print(f"[health] hang-abort flight dump failed: {e!r}",
                      flush=True)
        if self._esc_callback is not None:
            try:
                self._esc_callback()
            except Exception as e:
                print(f"[health] hang-abort callback failed: {e!r}",
                      flush=True)

    def _clear_stall_locked(self):
        # *_locked: every caller holds self._check_lock (GL003)
        self._escalated = False     # recovery re-arms the escalation
        if not self._stalled:
            return
        self._stalled = False
        self.recorder.gauge("health/stalled", 0)
        if self._stall_started is not None:
            self.recorder.inc("health/stall_seconds",
                              time.time() - self._stall_started)
            self._stall_started = None

    @property
    def stalled(self) -> bool:
        return self._stalled

    # -- thread lifecycle --------------------------------------------------- #
    def start(self) -> "StallWatchdog":
        # under the lock (GL003): _active and _thread are shared with
        # stop() and the /healthz scrape path; starting the thread
        # while holding it is safe — _run only needs the lock inside
        # check_once, after its first poll sleep
        with self._check_lock:
            self._active = True
            # re-baseline idle age from the moment of arming: with a
            # shared recorder the last step record may predate a long
            # stopped interval (the elastic supervisor's teardown/
            # backoff/rebuild gap between segments), and that gap is
            # not loop inactivity
            self._resumed_at = time.time()
            if self._thread is None or not self._thread.is_alive():
                # a FRESH event per poller thread: reusing one event
                # means a start() racing stop()'s join window could
                # clear the flag before the old thread observed it —
                # leaking a second poller forever.  Each thread only
                # ever watches its own event
                self._stop = threading.Event()
                self._thread = threading.Thread(target=self._run,
                                                args=(self._stop,),
                                                daemon=True,
                                                name="health-watchdog")
                self._thread.start()
        return self

    def _run(self, stop_ev: threading.Event):
        while not stop_ev.wait(self.poll_interval):
            try:
                self.check_once()
            except Exception as e:   # the watchdog must never die silently
                print(f"[health] watchdog check failed: {e!r}", flush=True)

    def stop(self):
        """Stop polling AND deactivate: a finished (or paused) loop is
        not a stalled one, so subsequent direct check_once calls — e.g.
        /healthz scrapes after training completed — report healthy."""
        with self._check_lock:
            self._stop.set()        # the CURRENT thread's event
            t = self._thread
            self._thread = None
        if t is not None:
            # join OUTSIDE the lock: the polling thread takes it in
            # check_once, and joining while holding it would deadlock
            t.join(timeout=5.0)
            if t.is_alive():        # never silent: a leaked poller is
                print("[health] watchdog thread did not stop within "
                      "5s", flush=True)
        with self._check_lock:
            self._active = False
            self._clear_stall_locked()
