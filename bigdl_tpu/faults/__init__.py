"""Repo-wide transient-fault injection plane.

``checkpoint/faults.py`` proved the discipline for crash consistency:
every durability claim is tested by actually injecting the failure.
This package generalizes it from "kill the checkpoint writer" to the
whole transient-fault surface — named injection *sites* across the
checkpoint, data, serving, HTTP, and step-dispatch paths, armed from
one environment variable, so every retry/degrade/abort claim in
``docs/robustness.md`` is provable by a test that (a) asserts the fault
actually fired (``fault/injected_total``) and (b) asserts the system
survived it.

Sites (the stable names tests and operators use)::

    ckpt.shard_write    checkpoint shard payload write
    ckpt.manifest       manifest / manifest-part commit write
    data.shard_open     opening one shard file in a data worker
    data.record_read    reading one record out of an open shard
    serving.swap        registry weight hot-swap (validate + publish)
    serving.compute     one serving batch execution (delay = a wedged
                        replica, err = a failing one — what the
                        replica-set failover chaos legs arm)
    serving.decode_step one continuous-batching decode step (delay = a
                        wedged decode step, err = live requests fail
                        and a ReplicaSet fails them over — the
                        decode-smoke chaos leg arms this)
    serving.publish     the canary publisher's staging step (the
                        swap onto the canary replica)
    http.bind           introspection-server socket bind
    step.dispatch       the supervisor's per-step dispatch
    fleet.place         the fleet scheduler computing/applying a placement
    fleet.preempt       the fleet scheduler delivering a preemption
                        (shrink/displace) to one job's capacity seam

Grammar (``BIGDL_FAULT`` env var or :func:`arm`)::

    "<site>:<mode>[@<nth>]"   one spec; join several with ";"

    modes:   err:<errno>      raise OSError(errno) — number or name
                              (``err:EIO``, ``err:28``)
             delay:<ms>       block for <ms> milliseconds (sleeps in
                              small chunks, so a hang-abort's async
                              exception can land mid-delay — a real
                              wedge is abortable, a test one must be)
             corrupt:<n>      flip <n> bytes of the write payload
                              (write sites only; control sites no-op)
             kill:<offset>    write sites: flush exactly <offset>
                              payload bytes, then ``os._exit`` — the
                              checkpoint/faults torn-write protocol.
                              Control sites: immediate ``os._exit``

    @<nth>:  which match fires.  ``@2`` fires ONLY on the 3rd match of
             that site (0-based), ``@2+`` on every match from the 3rd
             onward; omitted = every match.  Match counting is
             thread-safe, so "fail exactly one shard read across a
             4-worker pool" is expressible.

The legacy ``BIGDL_CKPT_FAULT`` grammar (see
:mod:`bigdl_tpu.checkpoint.faults`) keeps working unchanged — it is the
byte-offset-precise alias for the two ``ckpt.*`` sites, and
``guarded_write`` consults both planes.

Every fired fault increments ``fault/injected_total`` (and the per-site
``fault/injected.<site>``) on the recorder the site passes — or the
process-global recorder when the site has none — plus a process-local
count readable via :func:`injected_total` even with telemetry off.
Tests assert these so "the run survived" can never silently mean "the
fault never fired".
"""
from __future__ import annotations

import errno as _errno
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

ENV_VAR = "BIGDL_FAULT"
#: same exit code as checkpoint/faults — parents of kill tests match it
KILL_EXIT_CODE = 42

SITES = ("ckpt.shard_write", "ckpt.manifest", "data.shard_open",
         "data.record_read", "serving.swap", "serving.compute",
         "serving.decode_step", "serving.publish", "http.bind",
         "step.dispatch", "fleet.place", "fleet.preempt")

_MODES = ("err", "delay", "corrupt", "kill")


class FaultSpec:
    """One armed fault: site, mode, numeric argument, match selector."""

    __slots__ = ("site", "mode", "arg", "nth", "onward", "hits", "fired")

    def __init__(self, site: str, mode: str, arg: int,
                 nth: Optional[int] = None, onward: bool = False):
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r}; "
                             f"sites: {', '.join(SITES)}")
        if mode not in _MODES:
            raise ValueError(f"unknown fault mode {mode!r}; "
                             f"modes: {', '.join(_MODES)}")
        self.site = site
        self.mode = mode
        self.arg = int(arg)
        self.nth = nth              # None = every match
        self.onward = onward        # "@n+": from the nth match onward
        self.hits = 0               # site matches observed
        self.fired = 0              # faults actually injected

    def __repr__(self):
        sel = "" if self.nth is None else \
            f"@{self.nth}{'+' if self.onward else ''}"
        return f"{self.site}:{self.mode}:{self.arg}{sel}"


def _parse_errno(text: str) -> int:
    try:
        return int(text)
    except ValueError:
        num = getattr(_errno, text.strip().upper(), None)
        if isinstance(num, int):
            return num
        raise ValueError(f"unknown errno {text!r} in {ENV_VAR} spec")


def parse(spec: str) -> List[FaultSpec]:
    """Parse one ``BIGDL_FAULT`` value (possibly ``;``-joined) into
    specs; raises ValueError with the offending fragment on bad input."""
    out: List[FaultSpec] = []
    for frag in spec.split(";"):
        frag = frag.strip()
        if not frag:
            continue
        nth, onward = None, False
        body = frag
        if "@" in frag:
            body, sel = frag.rsplit("@", 1)
            if sel.endswith("+"):
                onward, sel = True, sel[:-1]
            try:
                nth = int(sel)
            except ValueError:
                raise ValueError(
                    f"bad match selector {sel!r} in {ENV_VAR} spec "
                    f"{frag!r} (want @<nth> or @<nth>+)") from None
        parts = body.split(":")
        if len(parts) != 3:
            raise ValueError(
                f"bad {ENV_VAR} spec {frag!r}: want "
                "<site>:<mode>:<arg>[@<nth>[+]]")
        site, mode, arg = parts
        if mode == "err":
            out.append(FaultSpec(site, mode, _parse_errno(arg), nth,
                                 onward))
        else:
            try:
                out.append(FaultSpec(site, mode, int(arg), nth, onward))
            except ValueError:
                raise ValueError(
                    f"bad numeric argument {arg!r} in {ENV_VAR} spec "
                    f"{frag!r}") from None
    return out


_lock = threading.Lock()
_specs: Optional[List[FaultSpec]] = None
_env_checked = False
_counts: Dict[str, int] = {}


def arm(spec) -> None:
    """Arm programmatically: a spec string, a list of FaultSpecs, or
    None to disarm.  Overrides the environment."""
    global _specs, _env_checked
    with _lock:
        if spec is None:
            _specs = None
        elif isinstance(spec, str):
            _specs = parse(spec)
        else:
            _specs = list(spec)
        _env_checked = True     # explicit arm/disarm beats the env


def disarm() -> None:
    arm(None)


def reset() -> None:
    """Test seam: drop the plan, counts, and the env-read latch so the
    next site check re-reads ``BIGDL_FAULT``."""
    global _specs, _env_checked
    with _lock:
        _specs = None
        _env_checked = False
        _counts.clear()


def injected_total(site: Optional[str] = None) -> int:
    """Process-local fired-fault count (per site, or all sites) — the
    recorder-free way for a subprocess to assert its fault fired."""
    with _lock:
        if site is not None:
            return _counts.get(site, 0)
        return sum(_counts.values())


def _active() -> List[FaultSpec]:
    global _env_checked, _specs
    with _lock:
        if not _env_checked:
            _env_checked = True
            env = os.environ.get(ENV_VAR)
            if env:
                _specs = parse(env)
        return _specs or []


def _match(site: str, exclude_modes=()) -> Optional[FaultSpec]:
    """Thread-safe match counting; returns the spec that fires for this
    occurrence of ``site``, or None.

    EVERY armed spec for the site observes every occurrence (its
    ``hits`` advances even when another spec fires first), so
    ``"s:err:EIO@0;s:err:EIO@1"`` fires on occurrences 0 AND 1 — not
    0 and 2.  When several specs select the same occurrence the first
    armed one fires.  ``exclude_modes`` makes a spec ineligible to fire
    at this call site (its hits still advance) — e.g. ``corrupt`` at a
    control site has no payload to corrupt, and counting it as fired
    would let a chaos assertion pass vacuously."""
    if _specs is None and _env_checked:
        return None             # fast path: disarmed (benign race)
    _active()
    with _lock:
        if not _specs:
            return None
        fired: Optional[FaultSpec] = None
        for s in _specs:
            if s.site != site:
                continue
            n = s.hits
            s.hits += 1
            if fired is None and s.mode not in exclude_modes and (
                    s.nth is None
                    or (n >= s.nth if s.onward else n == s.nth)):
                s.fired += 1
                fired = s
        if fired is not None:
            _counts[site] = _counts.get(site, 0) + 1
        return fired


def _record(site: str, mode: str, recorder=None) -> None:
    rec = recorder
    if rec is None:
        try:
            from ..observability import get_recorder
            rec = get_recorder()
        except Exception:
            return
    try:
        rec.inc("fault/injected_total")
        rec.inc(f"fault/injected.{site}")
        rec.emit_record("fault_event", site=site, mode=mode)
    except Exception:
        pass                    # telemetry must never mask the fault


def _sleep_chunked(seconds: float) -> None:
    # chunked so PyThreadState_SetAsyncExc (the hang-abort escalation
    # path) can land between sleeps: an async exception raised during
    # one long time.sleep only fires after the whole sleep returns
    deadline = time.monotonic() + seconds
    while True:
        left = deadline - time.monotonic()
        if left <= 0:
            return
        time.sleep(left if left < 0.05 else 0.05)


def _raise_err(spec: FaultSpec, site: str):
    raise OSError(spec.arg, f"injected fault at {site} "
                            f"[{_errno.errorcode.get(spec.arg, spec.arg)}]")


def inject(site: str, recorder=None) -> bool:
    """Control-flow sites: raise ``err``, block ``delay``, die ``kill``
    per the armed plan.  ``corrupt`` has no payload here: the spec is
    ineligible (never fires, never counts — a counted no-op would let
    a chaos assertion pass without any fault happening).  Returns True
    when a (non-raising) fault fired."""
    spec = _match(site, exclude_modes=("corrupt",))
    if spec is None:
        return False
    _record(site, spec.mode, recorder)
    if spec.mode == "err":
        _raise_err(spec, site)
    if spec.mode == "delay":
        _sleep_chunked(spec.arg / 1e3)
    elif spec.mode == "kill":
        os._exit(KILL_EXIT_CODE)
    return True


def filter_write(site: str, data: bytes, recorder=None
                 ) -> Tuple[bytes, Optional[int]]:
    """Write sites: returns ``(payload, kill_offset)``.  ``err`` raises
    before any byte lands, ``delay`` blocks, ``corrupt`` flips the last
    ``n`` bytes (a torn-tail shape CRC verification must catch), and
    ``kill`` hands the caller the offset for its flush-prefix-then-die
    protocol (see ``checkpoint.faults.guarded_write``)."""
    spec = _match(site)
    if spec is None:
        return data, None
    _record(site, spec.mode, recorder)
    if spec.mode == "err":
        _raise_err(spec, site)
    if spec.mode == "delay":
        _sleep_chunked(spec.arg / 1e3)
        return data, None
    if spec.mode == "corrupt":
        n = max(1, min(spec.arg, len(data))) if data else 0
        if n:
            tail = bytes(b ^ 0xFF for b in data[-n:])
            data = data[:-n] + tail
        return data, None
    return data, min(max(spec.arg, 0), len(data))       # kill


__all__ = ["ENV_VAR", "KILL_EXIT_CODE", "SITES", "FaultSpec", "parse",
           "arm", "disarm", "reset", "injected_total", "inject",
           "filter_write"]
