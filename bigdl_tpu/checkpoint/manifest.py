"""Atomic checkpoint manifests (the commit protocol's source of truth).

A checkpoint is a directory ``ckpt_<tag>/`` holding shard files plus one
``MANIFEST.json`` listing every shard with its byte size and masked
CRC32C.  The manifest is written LAST, via tmp + ``os.replace`` +
directory fsync — so a checkpoint either has a valid manifest naming
shards whose checksums verify, or it does not exist.  There is no state
in which a torn shard can be mistaken for committed data (≙ the
reference's reliance on HDFS rename atomicity for checkpoint commits,
made explicit and CRC-verified).

Multi-host writers (parallel/spmd.py) each contribute a
``MANIFEST.partK.json`` covering the shards they own; host 0 merges the
parts into the final ``MANIFEST.json``, which remains the single commit
point for the whole checkpoint.

Format v2 (elastic resume) adds, without breaking v1 readers of v1
files:

  * ``mesh`` — the SAVE-TIME device mesh (ordered axis names/sizes,
    device and process counts, see :func:`..reshard.mesh_info`), so
    restore can tell an identical-topology resume from a reshard and
    name both sides in its errors;
  * per-shard ``kind``/``of`` — ``kind="slices"`` marks a shard holding
    per-device array fragments with index maps (one shard per host per
    logical entry) that restore reassembles into global arrays; the
    default ``kind="tree"`` stays byte-compatible with v1 entries.

v1 manifests (no mesh, tree shards only) remain fully readable and are
treated as "mesh unknown": they restore onto an identical mesh exactly
as before.
"""
from __future__ import annotations

import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..utils.crc32c import mask

FORMAT = "bigdl_tpu.checkpoint"
VERSION = 2        # v2: mesh metadata + sliced-shard entries (elastic)
MANIFEST_NAME = "MANIFEST.json"
PART_PREFIX = "MANIFEST.part"
DIR_PREFIX = "ckpt_"
LATEST_NAME = "latest"


class CheckpointError(RuntimeError):
    """A checkpoint directory is missing, torn, or fails verification."""


def data_crc32c(data: bytes) -> int:
    """Masked CRC32C of a byte string (native fast path when available)."""
    from ..native import crc32c as _crc
    return mask(_crc(data))


def file_crc32c(path: str, chunk: int = 1 << 20) -> int:
    """Masked CRC32C of a file's contents, streamed in chunks."""
    from ..native import crc32c as _crc
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            crc = _crc(block, crc)
    return mask(crc)


def safe_tag(tag: str) -> str:
    """Filesystem-safe checkpoint tag."""
    return re.sub(r"[^A-Za-z0-9_.+-]", "_", str(tag)) or "untagged"


@dataclass
class Shard:
    name: str          # logical shard name ("params/fc1", "opt_state", ...)
    file: str          # file name inside the checkpoint directory
    bytes: int
    crc32c: int        # masked CRC32C of the file contents
    # v2 sliced shards: kind="slices" marks per-device array fragments
    # (with index maps) of the logical entry named by ``of``; restore
    # groups every slice shard with the same ``of`` and reassembles the
    # global arrays.  kind="tree" (default) is the v1 whole-tree payload.
    kind: str = "tree"
    of: Optional[str] = None

    def to_json(self):
        out = {"name": self.name, "file": self.file,
               "bytes": int(self.bytes), "crc32c": int(self.crc32c)}
        if self.kind != "tree":
            out["kind"] = self.kind
        if self.of is not None:
            out["of"] = self.of
        return out

    @staticmethod
    def from_json(d):
        try:
            return Shard(str(d["name"]), str(d["file"]), int(d["bytes"]),
                         int(d["crc32c"]), str(d.get("kind", "tree")),
                         None if d.get("of") is None else str(d["of"]))
        except (KeyError, TypeError, ValueError) as e:
            raise CheckpointError(f"malformed shard entry {d!r}") from e


@dataclass
class Manifest:
    tag: str
    meta: Dict = field(default_factory=dict)
    shards: List[Shard] = field(default_factory=list)
    created: float = 0.0
    # v2: the SAVE-TIME mesh ({"axes": [[name, size], ...], "devices": n,
    # "processes": k}); None on v1 manifests and non-mesh writers
    mesh: Optional[Dict] = None
    # version as READ from disk (None for freshly built manifests);
    # to_json stamps the LOWEST version that can express the content,
    # so plain tree-shard saves without mesh metadata stay readable by
    # pre-v2 libraries in a mixed-version fleet
    version: Optional[int] = None

    def to_json(self):
        v2 = self.mesh is not None or any(s.kind != "tree" or s.of
                                          for s in self.shards)
        out = {"format": FORMAT, "version": VERSION if v2 else 1,
               "tag": self.tag, "created": self.created,
               "meta": self.meta,
               "shards": [s.to_json() for s in self.shards]}
        if self.mesh is not None:
            out["mesh"] = self.mesh
        return out

    @staticmethod
    def from_json(d, where=""):
        if not isinstance(d, dict) or d.get("format") != FORMAT:
            raise CheckpointError(f"{where}: not a checkpoint manifest")
        if d.get("version", 0) > VERSION:
            raise CheckpointError(
                f"{where}: unsupported manifest version {d.get('version')}")
        mesh = d.get("mesh")
        return Manifest(str(d.get("tag", "")), dict(d.get("meta", {})),
                        [Shard.from_json(s) for s in d.get("shards", [])],
                        float(d.get("created", 0.0)),
                        dict(mesh) if isinstance(mesh, dict) else None,
                        int(d.get("version", 0)) or None)

    def sort_key(self) -> Tuple:
        """Newest-checkpoint ordering: training position, then wall time."""
        it = self.meta.get("iteration", self.meta.get("step", -1))
        try:
            it = int(it)
        except (TypeError, ValueError):
            it = -1
        return (it, self.created)


def fsync_dir(path: str):
    """Flush a directory entry (the rename itself) to stable storage."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return      # e.g. platforms without O_RDONLY dirs; best effort
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _write_json_atomic(path: str, obj, kind: str, recorder=None):
    """tmp (fault-injectable, fsync'ed) + os.replace + dir fsync."""
    from . import faults
    data = json.dumps(obj, sort_keys=True).encode()
    tmp = f"{path}.tmp-{os.getpid()}"
    if os.path.exists(tmp):
        os.remove(tmp)
    try:
        faults.guarded_write(tmp, data, kind=kind, recorder=recorder)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    fsync_dir(os.path.dirname(path) or ".")


def write_manifest(ckpt_dir: str, manifest: Manifest, recorder=None):
    """Commit a checkpoint: the manifest write IS the commit point.
    ``recorder`` routes ckpt.manifest fault-injection counters to the
    caller's telemetry (same contract as the shard writes)."""
    _write_json_atomic(os.path.join(ckpt_dir, MANIFEST_NAME),
                       manifest.to_json(), kind="manifest",
                       recorder=recorder)


def write_manifest_part(ckpt_dir: str, part_index: int,
                        manifest: Manifest, recorder=None):
    """One host's contribution (its owned shards); NOT a commit."""
    _write_json_atomic(
        os.path.join(ckpt_dir, f"{PART_PREFIX}{part_index}.json"),
        manifest.to_json(), kind="manifest_part", recorder=recorder)


def merge_manifest_parts(ckpt_dir: str, n_parts: int,
                         timeout: float = 120.0,
                         poll: float = 0.05) -> Manifest:
    """Host 0: wait for every part (shared filesystem), merge shard lists,
    and return the merged manifest (caller commits it via write_manifest).
    """
    paths = [os.path.join(ckpt_dir, f"{PART_PREFIX}{i}.json")
             for i in range(n_parts)]
    deadline = time.monotonic() + timeout
    while any(not os.path.exists(p) for p in paths):
        if time.monotonic() >= deadline:
            missing = [p for p in paths if not os.path.exists(p)]
            raise CheckpointError(
                f"{ckpt_dir}: timed out waiting for manifest parts "
                f"{[os.path.basename(m) for m in missing]}")
        time.sleep(poll)
    merged: Optional[Manifest] = None
    for p in paths:
        with open(p) as f:
            part = Manifest.from_json(json.load(f), where=p)
        if merged is None:
            merged = part
        else:
            merged.shards.extend(part.shards)
    merged.shards.sort(key=lambda s: s.name)
    return merged


def read_manifest(ckpt_dir: str) -> Manifest:
    path = os.path.join(ckpt_dir, MANIFEST_NAME)
    if not os.path.exists(path):
        raise CheckpointError(f"{ckpt_dir}: no manifest (uncommitted or "
                              "torn checkpoint)")
    try:
        with open(path) as f:
            return Manifest.from_json(json.load(f), where=path)
    except (OSError, json.JSONDecodeError) as e:
        raise CheckpointError(f"{ckpt_dir}: unreadable manifest ({e})") from e


def verify(ckpt_dir: str, manifest: Manifest, deep: bool = True) -> List[str]:
    """Return the list of problems (empty == intact).  ``deep`` re-hashes
    every shard file; shallow checks existence + byte size only."""
    problems = []
    for s in manifest.shards:
        p = os.path.join(ckpt_dir, s.file)
        if not os.path.exists(p):
            problems.append(f"missing shard {s.file}")
            continue
        size = os.path.getsize(p)
        if size != s.bytes:
            problems.append(f"shard {s.file}: {size} bytes, manifest says "
                            f"{s.bytes}")
            continue
        if deep and file_crc32c(p) != s.crc32c:
            problems.append(f"shard {s.file}: CRC32C mismatch")
    return problems


def scan(root: str, deep: bool = True) -> List[Tuple[str, Manifest]]:
    """All INTACT checkpoints under ``root``, sorted oldest → newest.

    A directory without a valid manifest, or whose shards fail
    verification, is skipped — it does not exist as a checkpoint.
    """
    if not os.path.isdir(root):
        return []
    out = []
    for d in os.listdir(root):
        full = os.path.join(root, d)
        if not (d.startswith(DIR_PREFIX) and os.path.isdir(full)):
            continue
        try:
            mf = read_manifest(full)
        except CheckpointError:
            continue
        if verify(full, mf, deep=deep):
            continue
        out.append((full, mf))
    out.sort(key=lambda e: e[1].sort_key())
    return out


def read_latest_pointer(root: str) -> Optional[str]:
    """Contents of the ``latest`` pointer file, or None.  The pointer is
    an optimization only — resume falls back to scanning when it is
    dangling or corrupt."""
    path = os.path.join(root, LATEST_NAME)
    try:
        with open(path) as f:
            return f.read().strip() or None
    except (OSError, UnicodeDecodeError):
        return None      # missing or corrupt pointer: caller scans


def write_latest_pointer(root: str, value: str):
    """Atomically update the ``latest`` pointer (tmp + os.replace)."""
    path = os.path.join(root, LATEST_NAME)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            f.write(value)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # no tmp litter on any failure path — a stale latest.tmp-<pid>
        # would otherwise survive until the next save from the same pid
        if os.path.exists(tmp):
            os.remove(tmp)
        raise
    fsync_dir(root)
