"""Elastic resharding: mesh metadata, array fragments, global assembly.

A v1 manifest checkpoint stores each logical entry ("params/fc1",
"opt_state") as one whole-tree shard of GLOBAL host arrays; restoring
it onto a different mesh only needs a re-``device_put`` against the
target shardings.  What it cannot express is a save where no single
host holds a global array — the realistic multi-host fsdp/tp case.

This module provides the v2 representation and the restore-side math:

  * :func:`mesh_info` / :func:`same_mesh` / :func:`describe_delta` —
    the save-time mesh recorded in MANIFEST.json and the actionable
    "saved mesh X, target mesh Y" wording restore errors use;
  * :func:`split_fragments` — per-leaf, per-device **replica-0 slices**
    of a (possibly sharded) jax array tree, each with its global index
    map.  Every distinct slice of every leaf is written by exactly one
    host (jax assigns ``replica_id`` 0 to one device per slice), so the
    union of all hosts' fragment shards is exactly one copy of the
    global state, whatever the mesh looked like;
  * :func:`assemble` — the inverse: merge fragment payloads from
    *whatever shards exist* into global numpy arrays, verifying every
    element is covered (a missing host's slices fail loudly, they do
    not restore as zeros).

Fragments carry owning copies (``np.array``, never ``np.asarray``):
the step loop donates the source buffers, and the async writer must
never serialize a view the next step scribbles over (the PR-3 hazard
class).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .manifest import CheckpointError

FRAGMENT_KEY = "__elastic_fragments__"
FRAGMENT_VERSION = 1
_LEAF = "__leaf__"      # skeleton placeholder (a string: stays a leaf)


# --------------------------------------------------------------------- #
# mesh metadata                                                          #
# --------------------------------------------------------------------- #
def mesh_info(mesh) -> Dict[str, Any]:
    """JSON-able description of a ``jax.sharding.Mesh``: ordered axis
    names/sizes plus device and process counts."""
    import jax
    axes = [[str(a), int(mesh.shape[a])] for a in mesh.axis_names]
    return {"axes": axes,
            "devices": int(np.prod([s for _, s in axes], dtype=np.int64)),
            "processes": int(jax.process_count())}


def mesh_axes(info: Optional[Dict]) -> Dict[str, int]:
    """``{axis: size}`` from a :func:`mesh_info` dict (ordered)."""
    return {str(n): int(s) for n, s in (info or {}).get("axes", [])}


def same_mesh(a: Optional[Dict], b: Optional[Dict]) -> bool:
    """Same topology: identical ordered axes and process count.  An
    unknown side (v1 manifest) never counts as different — legacy
    checkpoints keep restoring without mesh checks."""
    if a is None or b is None:
        return True
    return (list(map(tuple, a.get("axes", [])))
            == list(map(tuple, b.get("axes", [])))
            and a.get("processes") == b.get("processes"))


def fmt_mesh(info: Optional[Dict]) -> str:
    """One shared human rendering of a :func:`mesh_info` dict (restore
    errors, logs, and ckpt_inspect all use this — one schema, one
    wording)."""
    if info is None:
        return "<unknown mesh (v1 manifest)>"
    axes = "×".join(f"{n}={s}" for n, s in info.get("axes", []))
    return (f"{{{axes or 'no axes'}}} ({info.get('devices', '?')} devices, "
            f"{info.get('processes', '?')} process(es))")


def describe_delta(saved: Optional[Dict], target: Optional[Dict]) -> str:
    """Human-readable save→target mesh delta for logs and errors."""
    parts = [f"saved on {fmt_mesh(saved)}, restoring onto "
             f"{fmt_mesh(target)}"]
    if saved is not None and target is not None:
        sa, ta = mesh_axes(saved), mesh_axes(target)
        changed = [f"{n} {sa.get(n, 1)}→{ta.get(n, 1)}"
                   for n in dict.fromkeys(list(sa) + list(ta))
                   if sa.get(n, 1) != ta.get(n, 1)]
        if changed:
            parts.append("axis deltas: " + ", ".join(changed))
        if saved.get("devices") != target.get("devices"):
            parts.append(f"device count {saved.get('devices')}→"
                         f"{target.get('devices')}")
    return "; ".join(parts)


# model-parallel axes RE-PARTITION tensors (a tp shard is a slice of a
# weight, a pp shard a slice of the layer stack, an ep shard a slice of
# the expert dim) — a shape mismatch matching one of these axes means
# per-SHARD arrays were saved where global tensors belong.  Data axes
# (dp/fsdp) replicate or 1-D-reshard the same global tensors.  Kept in
# sync with bigdl_tpu.parallel.mesh.MODEL_AXES (not imported: this
# module must stay usable from jax-free tools like ckpt_inspect).
MODEL_AXES = ("sp", "tp", "pp", "ep")


def explain_shape_delta(got, want, saved: Optional[Dict],
                        target: Optional[Dict]) -> Optional[str]:
    """If a restored leaf's shape mismatch looks like a per-host/LOCAL
    or per-shard array saved where a global one belongs (some dim off
    by exactly a saved-mesh axis size or the device-count ratio), say
    so — the one mismatch class a mesh delta explains, with the
    wording keyed to the KIND of axis: a dp/fsdp factor reads as a
    per-host local batch/shard array, a tp/pp/sp/ep factor as a
    model-parallel partition slice.  Returns None otherwise."""
    got, want = tuple(got), tuple(want)
    if saved is None or len(got) != len(want):
        return None
    factors = {f"saved axis '{n}'": (s, n)
               for n, s in saved.get("axes", []) if s > 1}
    sd = saved.get("devices")
    td = None if target is None else target.get("devices")
    if sd and td and sd != td:
        hi, lo = max(sd, td), min(sd, td)
        if hi % lo == 0 and hi // lo > 1:
            factors[f"device-count ratio {sd}:{td}"] = (hi // lo, None)
    for dim, (g, w) in enumerate(zip(got, want)):
        if g == w:
            continue
        hits = [(why, f, axis) for why, (f, axis) in factors.items()
                if g * f == w or w * f == g]
        if not hits:
            continue
        f = hits[0][1]
        whys = " or ".join(why for why, _, _ in hits)
        model_hits = [a for _, _, a in hits if a in MODEL_AXES]
        data_hits = [a for _, _, a in hits
                     if a is not None and a not in MODEL_AXES]
        local = ("the checkpoint looks like a per-host LOCAL array "
                 "saved where a global one belongs")
        slice_ = ("a model-parallel axis re-partitions tensors, so the "
                  "checkpoint looks like one shard's SLICE of the "
                  "weight saved where the global tensor belongs")
        if model_hits and not data_hits:
            detail = f"'{model_hits[0]}': {slice_}"
        elif model_hits:
            # a composed mesh where several axes share the size: both
            # readings are possible, name both — the fix (re-save with
            # shard_arrays=True, restore reassembles via global index
            # maps) is the same either way
            detail = (f"{local} — or, via '{model_hits[0]}', {slice_}")
        else:
            detail = local
        return f"dim {dim} is off by exactly {f} ({whys}): {detail}"
    return None


# --------------------------------------------------------------------- #
# fragment payloads                                                      #
# --------------------------------------------------------------------- #
def is_fragment_payload(payload) -> bool:
    return isinstance(payload, dict) and FRAGMENT_KEY in payload


def all_array_leaves(tree) -> bool:
    """Fragment saves need numeric/bool array leaves (jax/numpy/python
    scalars); exotic leaves (bytes, strings, objects) stay on the
    whole-tree shard path, whose pickle fallback round-trips them."""
    import jax
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            continue
        try:
            if np.asarray(leaf).dtype.kind not in "biufc":
                return False
        except Exception:
            return False
    return True


def _bounds(index, shape) -> List[List[int]]:
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(dim)
        if step != 1:
            raise CheckpointError(f"non-contiguous shard slice {sl!r}")
        out.append([int(start), int(stop)])
    return out


def split_fragments(tree, process_index: int = 0) -> Dict[str, Any]:
    """This host's replica-0 slices of every leaf, with index maps.

    The payload also carries the tree *skeleton* (leaves replaced by a
    placeholder) so :func:`assemble` can rebuild the exact pytree
    structure without the saver's templates.  Host-side non-jax leaves
    are replicated by construction; process 0 writes them."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    skeleton = jax.tree_util.tree_unflatten(treedef, [_LEAF] * len(leaves))
    frags = []
    for i, leaf in enumerate(leaves):
        if isinstance(leaf, jax.Array):
            shape = tuple(leaf.shape)
            for sh in leaf.addressable_shards:
                if sh.replica_id != 0:
                    continue        # exactly one host owns each slice
                frags.append({
                    "leaf": i, "index": _bounds(sh.index, shape),
                    "shape": list(shape), "dtype": str(leaf.dtype),
                    "data": np.array(sh.data)})       # owning copy
        elif process_index == 0:
            a = np.array(leaf)                        # owning copy
            frags.append({"leaf": i,
                          "index": [[0, s] for s in a.shape],
                          "shape": list(a.shape), "dtype": str(a.dtype),
                          "data": a})
    return {FRAGMENT_KEY: FRAGMENT_VERSION, "skeleton": skeleton,
            "leaves": frags}


def assemble(payloads: List[Dict[str, Any]]):
    """Merge fragment payloads (any number of hosts, any save mesh)
    into one tree of GLOBAL numpy arrays.  Every element of every leaf
    must be covered by some fragment — partial coverage (a lost host's
    shards) raises :class:`CheckpointError` instead of silently
    restoring zeros."""
    import jax
    if not payloads:
        raise CheckpointError("no fragment payloads to assemble")
    for p in payloads:
        if not is_fragment_payload(p):
            raise CheckpointError("not an elastic fragment payload")
        if p[FRAGMENT_KEY] > FRAGMENT_VERSION:
            raise CheckpointError(
                f"unsupported fragment version {p[FRAGMENT_KEY]}")
    skeleton = payloads[0]["skeleton"]
    marks, treedef = jax.tree_util.tree_flatten(skeleton)
    n = len(marks)
    by_leaf: List[List[Dict]] = [[] for _ in range(n)]
    for p in payloads:
        for f in p.get("leaves", []):
            i = int(f["leaf"])
            if not 0 <= i < n:
                raise CheckpointError(f"fragment for unknown leaf {i}")
            by_leaf[i].append(f)
    # leaf-major: one bool coverage mask lives at a time (a full-model
    # list of masks would add +25% of an f32 checkpoint to the restore
    # peak — and restore runs exactly when capacity just shrank)
    out: List[Optional[np.ndarray]] = [None] * n
    for i, frags in enumerate(by_leaf):
        if not frags:
            raise CheckpointError(
                f"leaf {i}: incomplete fragment coverage (entirely "
                "missing) — a host's slice shards are absent")
        shape = tuple(int(s) for s in frags[0]["shape"])
        dtype = np.dtype(frags[0]["dtype"])
        arr = np.zeros(shape, dtype)
        seen = np.zeros(shape, bool)
        for f in frags:
            if tuple(int(s) for s in f["shape"]) != shape \
                    or np.dtype(f["dtype"]) != dtype:
                raise CheckpointError(
                    f"leaf {i}: conflicting fragment metadata "
                    f"{f['shape']}/{f['dtype']} vs {shape}/{dtype}")
            sl = tuple(slice(int(s), int(e)) for s, e in f["index"])
            arr[sl] = f["data"]
            seen[sl] = True
        if not seen.all():
            raise CheckpointError(
                f"leaf {i}: incomplete fragment coverage "
                f"({int((~seen).sum())}/{seen.size} elements missing) "
                "— a host's slice shards are absent")
        out[i] = arr
    return jax.tree_util.tree_unflatten(treedef, out)
