"""bigdl_tpu.checkpoint — fault-tolerant async checkpointing.

The reference BigDL survives executor loss by re-running Spark tasks
from cached state (DistriOptimizer.scala's retry loop); a preempted TPU
VM has no scheduler to do that for it.  This subsystem makes recovery a
property of the checkpoint format instead:

  * **async snapshot pipeline** — the step loop blocks only for the
    device→host copy (``checkpoint.blocking`` span); a background
    writer (:class:`~bigdl_tpu.checkpoint.writer.AsyncCheckpointWriter`)
    serializes sharded, CRC32C-verified files off the critical path
  * **atomic commit** — a per-checkpoint ``MANIFEST.json`` (shards +
    checksums + step/epoch/rng metadata) is written last via
    ``os.replace``: a checkpoint without a valid manifest does not exist
    (:mod:`~bigdl_tpu.checkpoint.manifest`)
  * **retention/GC** — keep-last-N plus keep-every-M-epochs
  * **preemption** — SIGTERM finishes the in-flight write, emits a
    final checkpoint, and exits cleanly
    (:class:`~bigdl_tpu.checkpoint.preemption.PreemptionHandler`)
  * **auto-resume** — scan manifests, verify CRCs, fall back to the
    newest INTACT checkpoint when the latest is torn
    (:meth:`CheckpointManager.restore_latest`)
  * **fault injection** — :mod:`~bigdl_tpu.checkpoint.faults` kills the
    writer at configurable byte offsets so crash consistency is a
    tested property, not a hope
  * **elastic reshard** — v2 manifests record the save-time mesh, and
    :mod:`~bigdl_tpu.checkpoint.reshard` assembles global arrays from
    whatever slice shards exist, so a checkpoint saved on one mesh
    restores onto any other (``bigdl_tpu.elastic`` drives the full
    shrink-on-preemption / regrow-on-capacity loop)

Wired into ``optim.Optimizer.set_checkpoint`` (default) and
``parallel.spmd.SpmdTrainer`` (``layout="manifest"``).  See
``docs/checkpointing.md``.
"""
from __future__ import annotations

from .manifest import (CheckpointError, Manifest, Shard, read_manifest,
                       scan, verify)
from .manager import CheckpointManager, host_snapshot
from .preemption import PreemptionHandler
from .writer import AsyncCheckpointWriter
from . import faults, reshard

__all__ = [
    "CheckpointError", "Manifest", "Shard", "read_manifest", "scan",
    "verify", "CheckpointManager", "host_snapshot", "PreemptionHandler",
    "AsyncCheckpointWriter", "faults", "reshard",
]
