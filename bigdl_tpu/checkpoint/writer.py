"""Background checkpoint writer: the async half of the snapshot pipeline.

The training loop's only blocking work is the device→host copy; the
serialize + CRC + write + commit runs here, on one daemon thread, in
submission order (FIFO — commit order matches training order, so
"newest intact manifest" is always the newest submitted state that
finished).  ``max_pending`` bounds the host-memory footprint: submitting
while that many snapshots are queued/in-flight blocks the caller — the
same backpressure contract as the serving queue.

A failed write must never kill training (≙ the old pickle-fallback
rationale): errors are stored on ``last_error``, counted on the
recorder, and printed; :meth:`wait` returns whether everything flushed.
"""
from __future__ import annotations

import collections
import threading
import traceback
from typing import Callable, Optional


class AsyncCheckpointWriter:
    def __init__(self, max_pending: int = 2, recorder_fn=None,
                 name: str = "bigdl-ckpt-writer"):
        self._jobs = collections.deque()
        self._cv = threading.Condition()
        self._pending = 0           # queued + running
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._name = name
        self.max_pending = max(1, int(max_pending))
        self.last_error: Optional[BaseException] = None
        self._rec_fn = recorder_fn

    def _rec(self):
        if self._rec_fn is None:
            from ..observability import null_recorder
            return null_recorder()
        return self._rec_fn()

    def submit(self, job: Callable[[], None]):
        """Enqueue one checkpoint job; blocks when ``max_pending``
        snapshots are already in flight (backpressure, not data loss)."""
        with self._cv:
            if self._closed:
                raise RuntimeError("checkpoint writer is closed")
            while self._pending >= self.max_pending:
                self._cv.wait()
            self._jobs.append(job)
            self._pending += 1
            self._rec().gauge("checkpoint/in_flight", self._pending)
            if self._thread is None:
                # daemon: a hung filesystem must not block process exit
                self._thread = threading.Thread(target=self._run,
                                                name=self._name, daemon=True)
                self._thread.start()
            self._cv.notify_all()

    def _run(self):
        while True:
            with self._cv:
                while not self._jobs and not self._closed:
                    self._cv.wait()
                if not self._jobs:
                    return          # closed and drained
                job = self._jobs.popleft()
            try:
                job()
            except BaseException as e:       # noqa: BLE001 — must survive
                self.last_error = e
                self._rec().inc("checkpoint/failed")
                print(f"[checkpoint] async write failed: {e!r}")
                traceback.print_exc()
            finally:
                with self._cv:
                    self._pending -= 1
                    self._rec().gauge("checkpoint/in_flight", self._pending)
                    self._cv.notify_all()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted job finished; True when drained."""
        with self._cv:
            self._cv.wait_for(lambda: self._pending == 0, timeout)
            return self._pending == 0

    def close(self, timeout: Optional[float] = None):
        """Drain in-flight writes, then stop the thread (preemption path:
        finish the write, never abandon it)."""
        self.wait(timeout)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)
