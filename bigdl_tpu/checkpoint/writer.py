"""Background checkpoint writer: the async half of the snapshot pipeline.

The training loop's only blocking work is the device→host copy; the
serialize + CRC + write + commit runs here, on one daemon thread, in
submission order (FIFO — commit order matches training order, so
"newest intact manifest" is always the newest submitted state that
finished).  ``max_pending`` bounds the host-memory footprint: submitting
while that many snapshots are queued/in-flight blocks the caller — the
same backpressure contract as the serving queue.

A failed write must never kill training (≙ the old pickle-fallback
rationale): errors are stored on ``last_error``, counted on the
recorder, and printed; :meth:`wait` returns whether everything flushed.

Tracing: a job carrying a ``trace_ctx`` attribute (a
:class:`~bigdl_tpu.observability.context.TraceContext`, attached by
``CheckpointManager.save``) gets two spans on the writer thread —
``ckpt.queue`` (submit → dequeue: backpressure + FIFO wait) and
``ckpt.write`` (the write itself) — under the SUBMITTER's trace id.
The context and submit stamp ride on the job object through the same
deque/Condition that orders the work, so the propagation is
racecheck-clean by the handoff discipline.
"""
from __future__ import annotations

import collections
import threading
import traceback
from typing import Callable, Optional

from ..observability import context as _trace_clock
from ..observability import tracing as trace_spine


class AsyncCheckpointWriter:
    def __init__(self, max_pending: int = 2, recorder_fn=None,
                 name: str = "bigdl-ckpt-writer"):
        self._jobs = collections.deque()
        self._cv = threading.Condition()
        self._pending = 0           # queued + running
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._name = name
        self.max_pending = max(1, int(max_pending))
        self.last_error: Optional[BaseException] = None
        self._rec_fn = recorder_fn

    def _rec(self):
        if self._rec_fn is None:
            from ..observability import null_recorder
            return null_recorder()
        return self._rec_fn()

    def submit(self, job: Callable[[], None]):
        """Enqueue one checkpoint job; blocks when ``max_pending``
        snapshots are already in flight (backpressure, not data loss)."""
        try:
            # stamp BEFORE the enqueue: the writer thread may pop the
            # job the instant it lands, and the cv handoff is the only
            # ordering between submitter and writer
            job._trace_t_submit = _trace_clock.trace_now()
        except AttributeError:
            pass                      # e.g. a bound method; no stamp
        waited = 0.0
        with self._cv:
            if self._closed:
                raise RuntimeError("checkpoint writer is closed")
            while self._pending >= self.max_pending:
                t0 = _trace_clock.trace_now()
                self._cv.wait()
                waited += _trace_clock.trace_now() - t0
            self._jobs.append(job)
            self._pending += 1
            self._rec().gauge("checkpoint/in_flight", self._pending)
            if self._thread is None:
                # daemon: a hung filesystem must not block process exit
                self._thread = threading.Thread(target=self._run,
                                                name=self._name, daemon=True)
                self._thread.start()
            self._cv.notify_all()
        if waited > 0.0:
            # backpressure stalled the TRAINING thread: surface it as
            # checkpoint.blocking span time so the goodput ledger books
            # it as checkpoint_blocking, not silent goodput (outside
            # the cv — recorder locking must not nest under it)
            self._rec().add_span("checkpoint.blocking", waited)

    def _run(self):
        while True:
            with self._cv:
                while not self._jobs and not self._closed:
                    self._cv.wait()
                if not self._jobs:
                    return          # closed and drained
                job = self._jobs.popleft()
            ctx = getattr(job, "trace_ctx", None)
            t_start = _trace_clock.trace_now()
            if ctx is not None:
                t_sub = getattr(job, "_trace_t_submit", t_start)
                trace_spine.get_tracer().record(trace_spine.Span(
                    "ckpt.queue", ctx.child(), t_sub, t_start,
                    subsystem="checkpoint"))
            try:
                job()
                if ctx is not None:
                    trace_spine.get_tracer().record(trace_spine.Span(
                        "ckpt.write", ctx.child(), t_start,
                        _trace_clock.trace_now(),
                        subsystem="checkpoint"))
            except BaseException as e:       # noqa: BLE001 — must survive
                self.last_error = e
                self._rec().inc("checkpoint/failed")
                if ctx is not None:
                    trace_spine.get_tracer().record(trace_spine.Span(
                        "ckpt.write", ctx.child(), t_start,
                        _trace_clock.trace_now(),
                        subsystem="checkpoint",
                        args={"error": repr(e)}))
                print(f"[checkpoint] async write failed: {e!r}")
                traceback.print_exc()
            finally:
                with self._cv:
                    self._pending -= 1
                    self._rec().gauge("checkpoint/in_flight", self._pending)
                    self._cv.notify_all()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted job finished; True when drained."""
        with self._cv:
            self._cv.wait_for(lambda: self._pending == 0, timeout)
            return self._pending == 0

    def close(self, timeout: Optional[float] = None):
        """Drain in-flight writes, then stop the thread (preemption path:
        finish the write, never abandon it)."""
        self.wait(timeout)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)
