"""Preemption handling: SIGTERM → final checkpoint → clean exit.

Cloud TPU VMs (and most schedulers) deliver SIGTERM with a short grace
window before the hard kill.  The handler only sets a flag — the
training loop polls :attr:`requested` at iteration boundaries, emits a
final checkpoint (finishing any in-flight async write first), and stops
cleanly, so the run loses zero completed steps instead of everything
since the last trigger (≙ BigDL's executor-loss recovery, but
proactive).

**Fan-out.**  One process can host several independent training loops
(the fleet scheduler runs N :class:`~bigdl_tpu.elastic.ElasticSupervisor`
jobs on one device pool), and each installs its own handler.  Chaining
raw ``signal.signal`` calls breaks there in two ways: the first handler
to ``uninstall()`` restores the disposition *it* displaced, silently
unhooking everyone who installed after it; and ``signal.signal`` only
works on the main thread, so a supervisor running on a worker thread
could never hear the signal at all.  All handlers therefore register
with one process-wide dispatcher that owns the single OS-level hook per
signal and fans every delivery out to **every** registered handler (then
chains whatever handler the hook displaced).  The OS hook is installed
by the first handler that registers *from the main thread* — a
worker-thread ``install()`` still registers for fan-out and relies on a
main-thread owner (the fleet scheduler, or any handler installed before
the threads started) to hold the hook.  The hook is released only when
the last handler for that signal unregisters, and only when it is still
the active disposition — a later hook (e.g. the observability flight
recorder) that chained us keeps working either way, because an
empty-registry dispatcher is a pure pass-through.
"""
from __future__ import annotations

import os
import signal
import threading
from typing import Dict, Iterable, List


class _SignalDispatcher:
    """Process-wide fan-out owner of the OS-level signal hooks.

    RLock, not Lock: the handler body runs on the main thread between
    bytecodes, so a signal landing while the main thread is inside
    register()/unregister() re-enters the lock on the same thread.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._handlers: List["PreemptionHandler"] = []   # delivery order
        self._os_prev: Dict[int, object] = {}   # signum -> displaced handler
        # ONE bound-method object for the OS hook: attribute access mints
        # a fresh bound method each time, so identity checks against
        # signal.getsignal() would never match a re-accessed method
        self._hook = self._on_signal

    def register(self, handler: "PreemptionHandler") -> bool:
        """Add ``handler`` to the fan-out set and make sure the OS hook
        exists for each of its signals.  Returns False when a needed OS
        hook could not be installed (worker thread) AND no main-thread
        owner holds it yet — delivery is pending a main-thread install."""
        with self._lock:
            if handler not in self._handlers:
                self._handlers.append(handler)
            missing = [s for s in handler._signals
                       if s not in self._os_prev]
        ok = True
        for s in missing:
            try:
                prev = signal.signal(s, self._hook)
            except ValueError:
                # signal.signal only works on the main thread; the
                # registration above still counts — a main-thread owner
                # (fleet scheduler / earlier handler) delivers to us
                ok = False
                continue
            with self._lock:
                self._os_prev[s] = prev
        return ok

    def unregister(self, handler: "PreemptionHandler"):
        with self._lock:
            if handler in self._handlers:
                self._handlers.remove(handler)
            # release a signal's OS hook only when NO remaining handler
            # wants it — this is the fan-out fix: one supervisor leaving
            # must not unhook the others
            dead = {s: self._os_prev[s] for s in handler._signals
                    if s in self._os_prev
                    and not any(s in h._signals for h in self._handlers)}
        for s, prev in dead.items():
            try:
                if signal.getsignal(s) is not self._hook:
                    # someone hooked in above us and chains our hook:
                    # leave the hook AND its saved prev — with an empty
                    # registry we are a pure pass-through, and a later
                    # register() must see the hook as already owned
                    # (re-hooking would save the chainer as prev and
                    # chain the dispatcher into itself)
                    continue
                signal.signal(s, prev)
            except ValueError:
                continue        # worker thread: leave the hook in place
            with self._lock:
                self._os_prev.pop(s, None)

    def has_hook(self, signum: int) -> bool:
        with self._lock:
            return signum in self._os_prev

    def relink_prev(self, signum: int, old, new) -> bool:
        """Unlink a handler we displaced that is being uninstalled: swap
        the saved prev for ``signum`` from ``old`` (its handler) to
        ``new`` (what IT had displaced).  Without this, the dispatcher
        would keep chaining — or on its own release, restore to the
        OS — a torn-down component's dead closure.  Returns False when
        ``old`` is not the saved prev (nothing to unlink)."""
        with self._lock:
            if self._os_prev.get(signum) is old:
                self._os_prev[signum] = new
                return True
        return False

    def _on_signal(self, signum, frame):
        with self._lock:
            handlers = [h for h in self._handlers
                        if signum in h._signals]
            prev = self._os_prev.get(signum)
        for h in handlers:
            h._on_signal(signum, frame)
        # chain the handler the OS hook displaced (e.g. the flight
        # recorder installed before us) — it must still see the signal;
        # default/ignore dispositions are deliberately NOT re-applied
        # while a handler consumed the signal, intercepting them is the
        # preemption handler's whole point
        if callable(prev):
            prev(signum, frame)
        elif not handlers:
            # an empty-registry dispatcher whose hook outlived its
            # handlers (worker-thread unregister cannot drop the OS
            # hook) must be a PASS-THROUGH, not a signal sink: restore
            # the displaced default/ignore disposition and re-raise, so
            # a plain `kill <pid>` still kills the process instead of
            # silently disappearing into a handler-less hook
            if signal.getsignal(signum) is not self._hook:
                # invoked as a chained callee — a later hook displaced
                # us and owns the OS registration now; restoring `prev`
                # here would clobber the CHAINER, and re-raising would
                # loop chainer→us forever.  Stay inert and keep the
                # saved prev so a later register() sees the hook as
                # still owned (same guard as unregister()).
                return
            with self._lock:
                self._os_prev.pop(signum, None)
            signal.signal(signum,
                          prev if prev is not None else signal.SIG_DFL)
            os.kill(os.getpid(), signum)


_dispatcher = _SignalDispatcher()


def dispatcher() -> _SignalDispatcher:
    """The process-wide signal dispatcher (fleet scheduler introspection
    and tests; handlers go through :meth:`PreemptionHandler.install`)."""
    return _dispatcher


class PreemptionHandler:
    """Install with :meth:`install`; poll :attr:`requested` in the loop."""

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,)):
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._installed = False

    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        self._installed = True
        if not _dispatcher.register(self):
            # registration succeeded but the OS hook needs a main-thread
            # owner — the fleet scheduler (or any main-thread handler)
            # provides it; say so instead of silently not firing
            print("[preemption] not on main thread; registered for "
                  "fan-out but the OS signal hook needs a main-thread "
                  "install (e.g. the fleet scheduler's)")
        return self

    def uninstall(self):
        if not self._installed:
            return
        _dispatcher.unregister(self)
        self._installed = False

    def _on_signal(self, signum, frame):
        if not self._event.is_set():
            print(f"[preemption] signal {signum} received; will write a "
                  "final checkpoint and stop", flush=True)
        self._event.set()

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def reset(self):
        self._event.clear()
