"""Preemption handling: SIGTERM → final checkpoint → clean exit.

Cloud TPU VMs (and most schedulers) deliver SIGTERM with a short grace
window before the hard kill.  The handler only sets a flag — the
training loop polls :attr:`requested` at iteration boundaries, emits a
final checkpoint (finishing any in-flight async write first), and stops
cleanly, so the run loses zero completed steps instead of everything
since the last trigger (≙ BigDL's executor-loss recovery, but
proactive).
"""
from __future__ import annotations

import signal
import threading
from typing import Dict, Iterable


class PreemptionHandler:
    """Install with :meth:`install`; poll :attr:`requested` in the loop."""

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,)):
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._prev: Dict[int, object] = {}
        self._installed = False

    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        try:
            for s in self._signals:
                self._prev[s] = signal.signal(s, self._on_signal)
            self._installed = True
        except ValueError:
            # signal.signal only works on the main thread; a worker-thread
            # training loop keeps running, just without preemption capture
            print("[preemption] not on main thread; handler not installed")
        return self

    def uninstall(self):
        if not self._installed:
            return
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
        self._installed = False

    def _on_signal(self, signum, frame):
        if not self._event.is_set():
            print(f"[preemption] signal {signum} received; will write a "
                  "final checkpoint and stop", flush=True)
        self._event.set()
        # chain a handler we displaced (e.g. the observability flight
        # recorder installed before us) — it must still see the signal;
        # default/ignore dispositions are deliberately NOT re-applied,
        # intercepting them is this handler's whole point
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def reset(self):
        self._event.clear()
