"""Crash-consistency fault injection for the checkpoint writer.

Every byte the checkpoint subsystem puts on disk goes through
:func:`guarded_write`, which can be armed — programmatically via
:func:`set_plan` or from the ``BIGDL_CKPT_FAULT`` environment variable
(for subprocess kill tests) — to hard-kill the process (``os._exit``)
at a configurable byte offset.  That makes "a checkpoint without a
valid manifest does not exist" a TESTED property: tests kill the writer
mid-shard, mid-manifest, or between the two, then assert resume lands
on the newest intact checkpoint.

Spec grammar (env var or :func:`set_plan` string):

    "<save>:bytes:<offset>"     kill after <offset> cumulative shard
                                payload bytes of the <save>-th checkpoint
                                save in this process (0-based)
    "<save>:manifest:<offset>"  kill <offset> bytes into that save's
                                manifest write
    "<save>:pre_manifest"       kill after all shards, before the
                                manifest (shards durable, commit absent)
    "sleep:<ms>"                no kill; delay every shard write by
                                <ms> — used to prove async writes stay
                                off the step loop

The kill is a real ``os._exit(KILL_EXIT_CODE)``: no atexit handlers, no
flushing beyond the bytes already written — the closest a test can get
to a power cut or an OOM kill without root.
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

ENV_VAR = "BIGDL_CKPT_FAULT"
KILL_EXIT_CODE = 42


@dataclass
class FaultPlan:
    save_index: int = 0          # which checkpoint save to fault (0-based)
    point: str = "bytes"         # "bytes" | "manifest" | "pre_manifest"
    offset: int = 0              # byte offset within the faulted region
    sleep_s: float = 0.0         # per-shard-write delay (no kill)

    @staticmethod
    def parse(spec: str) -> "FaultPlan":
        parts = spec.strip().split(":")
        try:
            if parts[0] == "sleep":
                return FaultPlan(save_index=-1, point="sleep",
                                 sleep_s=float(parts[1]) / 1e3)
            save = int(parts[0])
            point = parts[1]
            if point == "pre_manifest":
                return FaultPlan(save_index=save, point=point)
            if point in ("bytes", "manifest"):
                return FaultPlan(save_index=save, point=point,
                                 offset=int(parts[2]))
        except (IndexError, ValueError) as e:
            raise ValueError(f"bad {ENV_VAR} spec {spec!r}") from e
        raise ValueError(f"bad {ENV_VAR} spec {spec!r}")


_lock = threading.Lock()
_plan: Optional[FaultPlan] = None
_env_loaded = False
_save_idx = -1            # index of the save currently in progress
_shard_bytes = 0          # cumulative shard payload bytes of this save


def set_plan(plan):
    """Arm (FaultPlan or spec string) or disarm (None) fault injection."""
    global _plan, _env_loaded
    with _lock:
        _plan = FaultPlan.parse(plan) if isinstance(plan, str) else plan
        _env_loaded = True      # explicit plan overrides the environment


def active_plan() -> Optional[FaultPlan]:
    global _plan, _env_loaded
    with _lock:
        if not _env_loaded:
            _env_loaded = True
            spec = os.environ.get(ENV_VAR)
            if spec:
                _plan = FaultPlan.parse(spec)
        return _plan


def begin_save() -> int:
    """Called by the writer at the start of each checkpoint save; returns
    the save index faults are matched against."""
    global _save_idx, _shard_bytes
    active_plan()
    with _lock:
        _save_idx += 1
        _shard_bytes = 0
        return _save_idx


def _die():
    # hard kill: simulate a preemption/power-cut mid-write.  os._exit
    # skips atexit, GC, and pending buffers — only fsync'ed bytes survive.
    os._exit(KILL_EXIT_CODE)


def on_pre_manifest():
    """Kill point between the last shard and the manifest write."""
    plan = active_plan()
    if (plan is not None and plan.point == "pre_manifest"
            and plan.save_index == _save_idx):
        _die()


def _kill_offset_within(kind: str, nbytes: int) -> Optional[int]:
    """Offset inside this write at which to kill, or None."""
    global _shard_bytes
    plan = active_plan()
    if plan is None:
        return None
    if plan.point == "sleep" and kind == "shard":
        time.sleep(plan.sleep_s)
        return None
    if plan.save_index != _save_idx:
        return None
    if plan.point == "bytes" and kind == "shard":
        start = _shard_bytes
        _shard_bytes += nbytes
        if start <= plan.offset < start + nbytes:
            return plan.offset - start
        return None
    if plan.point == "manifest" and kind == "manifest":
        if plan.offset < nbytes:
            return plan.offset
        return None
    if kind == "shard":
        _shard_bytes += nbytes
    return None


def guarded_write(path: str, data: bytes, kind: str = "shard",
                  recorder=None):
    """Write ``data`` to a FRESH file at ``path`` (O_EXCL) with fsync,
    honoring the active fault plan.  On a planned kill, exactly the
    prefix up to the configured offset is flushed to disk before
    ``os._exit`` — a maximally-torn file for resume to reject.

    Both fault planes apply: the legacy ``BIGDL_CKPT_FAULT`` byte-offset
    kill grammar above, and the repo-wide ``BIGDL_FAULT`` sites
    ``ckpt.shard_write`` / ``ckpt.manifest`` (:mod:`bigdl_tpu.faults`),
    whose ``err:``/``delay:``/``corrupt:`` modes model the *transient*
    failures the retry layer must survive — an err raises before any
    byte lands, so a retried write starts clean."""
    from .. import faults as _plane
    site = "ckpt.manifest" if "manifest" in kind else "ckpt.shard_write"
    data, plane_kill = _plane.filter_write(site, data, recorder)
    kill_at = _kill_offset_within(kind, len(data))
    if kill_at is None:
        kill_at = plane_kill
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o666)
    try:
        if kill_at is not None:
            os.write(fd, data[:kill_at])
            os.fsync(fd)
            _die()
        os.write(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)
