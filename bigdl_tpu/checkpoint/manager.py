"""CheckpointManager: async snapshot pipeline + atomic commit + resume.

One manager owns one checkpoint root directory and provides:

  save()            hand a HOST-side snapshot to the background writer
                    (the caller does the device→host copy under its own
                    ``checkpoint.blocking`` span; everything after —
                    serialize, CRC, write, commit, GC — is off the step
                    loop)
  restore_latest()  newest INTACT checkpoint: manifests are scanned and
                    every shard CRC-verified, falling back past torn or
                    corrupt checkpoints; the ``latest`` pointer is only
                    a hint and a dangling/corrupt pointer is tolerated
  retention         keep-last-N plus keep-every-M-epochs GC after each
                    commit

Two layouts:

  "manifest" (default)  sharded files + atomic ``MANIFEST.json`` commit
                        (see :mod:`.manifest`); supports multi-host
                        part-manifests via ``process_index``/``count``
  "file"                the legacy single-file-per-checkpoint layout
                        (``checkpoint_<tag>.bin`` + a ``latest`` pointer
                        holding the file path) — kept so old tooling and
                        old checkpoints keep working, now with an atomic
                        pointer update and scan-based pointer recovery
"""
from __future__ import annotations

import glob
import os
import pickle
import re
import shutil
import time
from typing import Any, Callable, Dict, Optional, Tuple

from . import faults, manifest as mlib, reshard
from .manifest import DIR_PREFIX, Manifest, Shard, data_crc32c, safe_tag
from .writer import AsyncCheckpointWriter
from ..utils.retry import RetryPolicy


def host_snapshot(tree):
    """Device→host copy that OWNS its memory.

    ``np.asarray(jax_array)`` may return a zero-copy VIEW of the device
    buffer (CPU backend); with donated step buffers a later training
    step would mutate the "snapshot" while the async writer is still
    serializing it — the torn state would even pass its own CRC.  This
    is the blocking half of the pipeline: call it under the
    ``checkpoint.blocking`` span, then hand the result to save().
    """
    import jax
    import numpy as np

    def leaf(v):
        if isinstance(v, jax.Array):
            return np.array(v)              # materialize + own
        if isinstance(v, (np.ndarray, np.generic)):
            return np.array(v)
        return v
    return jax.tree_util.tree_map(leaf, tree)


def _serialize_tree(tree) -> bytes:
    """Serializer-format bytes, falling back to pickle for exotic leaves
    (a checkpoint trigger must never kill the run — same contract as the
    old in-optimizer fallback)."""
    from ..utils.serializer import SerializationError, state_file_bytes
    try:
        return state_file_bytes(tree)
    except SerializationError:
        return pickle.dumps(tree, protocol=pickle.HIGHEST_PROTOCOL)


def _load_payload_file(path: str):
    """Magic-byte routed load (same rationale as utils/file.load)."""
    from ..utils.serializer import load_state_file
    with open(path, "rb") as f:
        head = f.read(2)
    if head == b"PK":
        return load_state_file(path)
    with open(path, "rb") as f:
        return pickle.load(f)


class CheckpointManager:
    def __init__(self, root: str, layout: str = "manifest",
                 async_write: bool = True, keep_last: Optional[int] = None,
                 keep_every_epochs: Optional[int] = None,
                 recorder_fn: Optional[Callable] = None,
                 max_pending: int = 2,
                 process_index: int = 0, process_count: int = 1,
                 part_timeout: float = 120.0, write_retries: int = 3):
        if layout not in ("manifest", "file"):
            raise ValueError(f"unknown checkpoint layout {layout!r}")
        if keep_last is not None and keep_last < 1:
            raise ValueError("keep_last must be >= 1")
        if keep_every_epochs is not None and keep_every_epochs < 1:
            raise ValueError("keep_every_epochs must be >= 1")
        self.root = root
        self.layout = layout
        self.async_write = bool(async_write)
        self.keep_last = keep_last
        self.keep_every_epochs = keep_every_epochs
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.part_timeout = part_timeout
        self._rec_fn = recorder_fn
        os.makedirs(root, exist_ok=True)
        # one writer even for sync saves: every write runs on the same
        # thread, so writes+GC are serialized and FIFO-ordered
        self.writer = AsyncCheckpointWriter(max_pending=max_pending,
                                            recorder_fn=recorder_fn)
        # transient write errors (EIO/ENOSPC blips) retry before the
        # checkpoint counts as failed; EROFS/EACCES stay fatal — a
        # read-only filesystem does not heal within a backoff budget
        self._retry = RetryPolicy(max_attempts=max(1, int(write_retries)),
                                  base=0.05, max_delay=1.0,
                                  recorder_fn=recorder_fn, name="ckpt")

    def _rec(self):
        if self._rec_fn is None:
            from ..observability import null_recorder
            return null_recorder()
        return self._rec_fn()

    # -- save ------------------------------------------------------------ #
    def save(self, payload, meta: Dict[str, Any], tag: str,
             sync: bool = False, mesh: Optional[Dict] = None,
             owned=None, trace_ctx=None):
        """Queue one checkpoint.  ``payload`` must already be HOST data
        (numpy leaves): for the "manifest" layout a ``{shard_name: tree}``
        dict, for "file" an arbitrary state tree.  ``sync=True`` (or a
        manager built with ``async_write=False``) blocks until the
        checkpoint is committed.

        ``mesh`` (a :func:`..reshard.mesh_info` dict) is recorded in the
        v2 manifest so restore can tell resume from reshard.  ``owned``
        optionally names the shards THIS process writes (elastic sliced
        saves, where each host owns its own fragment entries); the
        default keeps the round-robin-by-sorted-name assignment.

        ``trace_ctx`` (a
        :class:`~bigdl_tpu.observability.context.TraceContext`) rides
        on the job object to the writer thread, which records the
        queue-wait and write there under the submitting step's trace
        id — the step → async-writer half of the causal spine."""
        if self.layout == "manifest":
            if not isinstance(payload, dict):
                raise TypeError("manifest layout expects {shard_name: tree}")
            trees = dict(payload)
            owned = None if owned is None else frozenset(owned)
            job = lambda: self._write_manifest_ckpt(trees, dict(meta), tag,
                                                    mesh=mesh, owned=owned)
        else:
            job = lambda: self._write_file_ckpt(payload, dict(meta), tag)
        if trace_ctx is not None:
            job.trace_ctx = trace_ctx
        if sync or not self.async_write:
            # raise THIS job's failure only — an earlier async write may
            # have failed (by design without killing training) and its
            # stale last_error must not poison an unrelated sync commit
            box = {}

            def tracked(job=job):
                try:
                    job()
                except BaseException as e:
                    box["err"] = e
                    raise
            if trace_ctx is not None:
                tracked.trace_ctx = trace_ctx
            self.writer.submit(tracked)
            self.writer.wait()
            if "err" in box:
                raise box["err"]
        else:
            self.writer.submit(job)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Drain in-flight writes (the preemption handler's 'finish the
        write' step and the pre-restore barrier)."""
        return self.writer.wait(timeout)

    def close(self, timeout: Optional[float] = None):
        self.writer.close(timeout)

    def _span_name(self) -> str:
        return "checkpoint.async_write" if self.async_write \
            else "checkpoint.write"

    def _write_shard_retrying(self, fpath: str, data: bytes):
        """One shard write with transient-error retry.  Each attempt
        starts clean: a failed earlier attempt (or a stale same-tag
        leftover) may have left a partial O_EXCL file behind."""
        def attempt():
            if os.path.exists(fpath):
                os.remove(fpath)
            faults.guarded_write(fpath, data, kind="shard",
                                 recorder=self._rec())
        self._retry.run(attempt)

    def _write_manifest_ckpt(self, trees, meta, tag, mesh=None, owned=None):
        rec = self._rec()
        t0 = time.perf_counter()
        faults.begin_save()
        d = os.path.join(self.root, DIR_PREFIX + safe_tag(tag))
        if self.process_count == 1 and os.path.isdir(d):
            shutil.rmtree(d)        # stale torn leftover with the same tag
        os.makedirs(d, exist_ok=True)
        if self.process_count > 1:
            # same-tag retry after a multi-host crash: remove THIS host's
            # stale part FIRST, so host 0's merge cannot see a part until
            # its owner has rewritten every shard it names (the part is
            # re-written only after the shard loop below)
            stale = os.path.join(d, f"{mlib.PART_PREFIX}"
                                    f"{self.process_index}.json")
            if os.path.exists(stale):
                os.remove(stale)
        names = sorted(trees)
        shards, total = [], 0
        for i, name in enumerate(names):
            if owned is not None:
                if name not in owned:
                    continue    # caller-decided ownership (elastic saves)
            elif i % self.process_count != self.process_index:
                continue        # per-host shard ownership
            payload = trees[name]
            data = _serialize_tree(payload)
            fname = f"shard{i:04d}.bin"
            fpath = os.path.join(d, fname)
            self._write_shard_retrying(fpath, data)
            if reshard.is_fragment_payload(payload):
                shards.append(Shard(name, fname, len(data),
                                    data_crc32c(data), kind="slices",
                                    of=payload.get("of", name)))
            else:
                shards.append(Shard(name, fname, len(data),
                                    data_crc32c(data)))
            total += len(data)
        if total:
            rec.inc("checkpoint/bytes_written", total)
        faults.on_pre_manifest()
        mf = Manifest(tag=str(tag), meta=meta, shards=shards,
                      created=time.time(), mesh=mesh)
        # manifest commits retry transient errors too: _write_json_atomic
        # cleans up its tmp on failure, so every attempt starts fresh
        if self.process_count > 1:
            self._retry.run(mlib.write_manifest_part, d,
                            self.process_index, mf, recorder=rec)
            if self.process_index != 0:
                return      # host 0 owns the commit + pointer + GC
            mf = mlib.merge_manifest_parts(d, self.process_count,
                                           timeout=self.part_timeout)
            self._retry.run(mlib.write_manifest, d, mf, recorder=rec)
        else:
            self._retry.run(mlib.write_manifest, d, mf, recorder=rec)
        self._write_pointer_safely(os.path.basename(d))
        dt = time.perf_counter() - t0
        rec.inc("checkpoint/committed")
        rec.inc("checkpoint/write_seconds", dt)
        rec.add_span(self._span_name(), dt)
        self._gc_safely(self._gc_manifest, current=os.path.basename(d))

    def _write_file_ckpt(self, state, meta, tag):
        rec = self._rec()
        t0 = time.perf_counter()
        faults.begin_save()
        path = os.path.join(self.root, f"checkpoint_{safe_tag(tag)}.bin")
        data = _serialize_tree({"state": state, "meta": meta})
        tmp = f"{path}.tmp-{os.getpid()}"

        def attempt():
            if os.path.exists(tmp):
                os.remove(tmp)
            faults.guarded_write(tmp, data, kind="shard",
                                 recorder=self._rec())
            os.replace(tmp, path)
        try:
            self._retry.run(attempt)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        mlib.fsync_dir(self.root)
        # legacy pointer: the checkpoint FILE path (old tools read this)
        self._write_pointer_safely(path)
        dt = time.perf_counter() - t0
        rec.inc("checkpoint/bytes_written", len(data))
        rec.inc("checkpoint/committed")
        rec.inc("checkpoint/write_seconds", dt)
        rec.add_span(self._span_name(), dt)
        self._gc_safely(self._gc_file, current=path)

    def _write_pointer_safely(self, value: str):
        """The ``latest`` pointer is an optimization only — resume
        falls back to scanning when it is missing or stale.  It is
        written AFTER the manifest (the commit point) is durable, so a
        pointer failure must not mark a complete, restorable checkpoint
        failed: transient errors retry through the unified policy, and
        an exhausted or fatal failure is logged + counted
        (``checkpoint/pointer_skipped``) — the next commit rewrites the
        pointer and resume scans in the meantime."""
        try:
            self._retry.run(mlib.write_latest_pointer, self.root, value)
        except OSError as e:
            self._rec().inc("checkpoint/pointer_skipped")
            # best effort: drop the now-STALE pointer so resume scans
            # newest-first instead of preferring the older checkpoint
            # the un-updated pointer still names
            try:
                os.remove(os.path.join(self.root, mlib.LATEST_NAME))
                stale = "stale pointer dropped"
            except OSError:
                stale = "stale pointer not removable either"
            print(f"[checkpoint] latest-pointer update failed ({e!r}); "
                  f"{stale}; the commit stands — resume scans "
                  "manifests, the next commit rewrites the pointer",
                  flush=True)

    # -- retention ------------------------------------------------------- #
    def _gc_enabled(self) -> bool:
        return (self.keep_last is not None
                or self.keep_every_epochs is not None)

    def _gc_remove(self, path: str, rmdir: bool = True):
        """Remove one retention candidate; an un-deletable entry
        (permission, ENOENT race with a concurrent cleaner) is logged
        and counted — never silently ignored, never aborts the sweep.
        The next sweep retries it."""
        try:
            if rmdir:
                shutil.rmtree(path)
            else:
                os.remove(path)
        except OSError as e:
            self._rec().inc("checkpoint/gc_skipped")
            print(f"[checkpoint] gc: could not remove {path} ({e!r}); "
                  "skipped — the next sweep retries it", flush=True)

    def _gc_safely(self, fn, current: str):
        """The sweep runs after a successful commit: a GC failure must
        not mark the checkpoint failed (or kill the writer job), only
        announce itself."""
        try:
            fn(current=current)
        except OSError as e:
            self._rec().inc("checkpoint/gc_skipped")
            print(f"[checkpoint] gc sweep failed ({e!r}); the commit "
                  "stands, the next sweep retries", flush=True)

    def _gc_manifest(self, current: str):
        if not self._gc_enabled():
            return
        cands = mlib.scan(self.root, deep=False)
        names = [os.path.basename(d) for d, _ in cands]
        protect = {current}
        ptr = mlib.read_latest_pointer(self.root)
        if ptr:
            protect.add(os.path.basename(ptr.rstrip("/")))
        if self.keep_last:
            protect.update(names[-self.keep_last:])
        if self.keep_every_epochs:
            for d, mf in cands:
                ep = mf.meta.get("epoch")
                if (mf.meta.get("epoch_boundary") and isinstance(ep, int)
                        and ep % self.keep_every_epochs == 0):
                    protect.add(os.path.basename(d))
        for d, _ in cands:
            if os.path.basename(d) not in protect:
                self._gc_remove(d)
        # torn leftovers (no valid manifest) from crashed writers.  Only
        # single-writer roots: with multiple hosts, a manifest-less dir
        # may be another host's save IN PROGRESS, not garbage
        if self.process_count == 1:
            intact = set(names)
            for d in os.listdir(self.root):
                full = os.path.join(self.root, d)
                if (d.startswith(DIR_PREFIX) and os.path.isdir(full)
                        and d not in intact and d not in protect):
                    self._gc_remove(full)

    def _gc_file(self, current: str):
        if not self._gc_enabled() or not self.keep_last:
            return
        files = sorted(glob.glob(os.path.join(self.root,
                                              "checkpoint_*.bin")),
                       key=os.path.getmtime)
        protect = {os.path.abspath(current)}
        ptr = mlib.read_latest_pointer(self.root)
        if ptr:
            protect.add(os.path.abspath(ptr))
        if self.keep_every_epochs:
            for p in files:
                m = re.search(r"checkpoint_epoch_(\d+)\.bin$", p)
                if m and int(m.group(1)) % self.keep_every_epochs == 0:
                    protect.add(os.path.abspath(p))
        for p in files[:-self.keep_last]:
            if os.path.abspath(p) not in protect:
                self._gc_remove(p, rmdir=False)

    # -- restore --------------------------------------------------------- #
    @staticmethod
    def _assemble_entries(trees, mf: Manifest):
        """Collapse v2 sliced shards into their logical entries: group
        every ``kind="slices"`` shard by its ``of`` name and reassemble
        the global arrays; whole-tree shards pass through untouched."""
        merged, groups = {}, {}
        for s in mf.shards:
            payload = trees[s.name]
            if s.kind == "slices" or reshard.is_fragment_payload(payload):
                logical = s.of or (payload.get("of")
                                   if isinstance(payload, dict) else None)
                groups.setdefault(logical or s.name, []).append(payload)
            else:
                merged[s.name] = payload
        for logical, parts in groups.items():
            merged[logical] = reshard.assemble(parts)
        return merged

    def restore_latest(self, with_manifest: bool = False
                       ) -> Optional[Tuple]:
        """``("manifest", {shard: tree}, meta)`` or ``("file", state,
        meta)`` for the newest intact checkpoint, else None.  Waits for
        in-flight writes first, prefers the ``latest`` pointer's target
        when it verifies, and otherwise scans — a torn newest checkpoint
        falls back to the next intact one.  Sliced (elastic) shards are
        reassembled into global arrays, whatever mesh wrote them.

        ``with_manifest=True`` appends the restored checkpoint's
        :class:`Manifest` (None for the legacy file layout) — the
        save-time mesh restorers reshard against."""
        self.wait()
        # shallow scan for ordering; the expensive full-CRC pass runs
        # per candidate below, so resume cost is O(restored checkpoint),
        # not O(every checkpoint ever retained)
        cands = mlib.scan(self.root, deep=False)
        by_name = {os.path.basename(d): (d, mf) for d, mf in cands}
        order = []
        ptr = mlib.read_latest_pointer(self.root)
        if ptr:
            hit = by_name.get(os.path.basename(ptr.rstrip("/")))
            if hit is not None:
                order.append(hit)
        order.extend(c for c in reversed(cands)
                     if not order or c[0] != order[0][0])
        rec = self._rec()
        for d, mf in order:
            problems = mlib.verify(d, mf, deep=True)
            if problems:
                # one re-read before falling back a whole checkpoint:
                # a deep-CRC mismatch can be a transient read blip
                # (NFS/page-cache), and the next-older checkpoint costs
                # real training progress.  A genuinely torn file fails
                # the second pass identically.
                rec.inc("retry/attempts")
                rec.inc("checkpoint/verify_retries")
                problems = mlib.verify(d, mf, deep=True)
            if problems:
                print(f"[checkpoint] {d}: {problems[0]}; trying older "
                      "checkpoints")
                continue
            try:
                trees = {s.name: _load_payload_file(os.path.join(d, s.file))
                         for s in mf.shards}
                trees = self._assemble_entries(trees, mf)
            except Exception as e:      # CRC passed but decode failed
                print(f"[checkpoint] {d}: unreadable despite manifest "
                      f"({e!r}); trying older checkpoints")
                continue
            out = ("manifest", trees, dict(mf.meta))
            return out + (mf,) if with_manifest else out
        legacy = self._restore_legacy_file()
        if legacy is not None and with_manifest:
            return legacy + (None,)
        return legacy

    def _restore_legacy_file(self):
        paths = []
        ptr = mlib.read_latest_pointer(self.root)
        if ptr and not ptr.startswith(DIR_PREFIX):
            for cand in (ptr, os.path.join(self.root,
                                           os.path.basename(ptr))):
                if os.path.isfile(cand):
                    paths.append(os.path.abspath(cand))
                    break
        # dangling/corrupt pointer (or none): newest intact file wins
        scanned = sorted(glob.glob(os.path.join(self.root,
                                                "checkpoint_*.bin")),
                         key=os.path.getmtime, reverse=True)
        paths.extend(p for p in (os.path.abspath(s) for s in scanned)
                     if p not in paths)
        for p in paths:
            try:
                blob = _load_payload_file(p)
                state, meta = blob["state"], blob["meta"]
            except Exception as e:
                print(f"[checkpoint] {p}: torn or corrupt ({e!r}); "
                      "trying older checkpoints")
                continue
            return ("file", state, dict(meta))
        return None
