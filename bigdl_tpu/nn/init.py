"""Weight initialization methods (≙ nn/InitializationMethod.scala).

Each init method is a callable ``(rng, shape, fan_in, fan_out) -> array``.
Layers consult ``module.weight_init`` / ``module.bias_init`` overrides set via
``set_init_method`` and otherwise use their reference default.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class InitializationMethod:
    """Base of weight/bias initializers (nn/InitializationMethod.scala)."""
    def __call__(self, rng, shape, fan_in, fan_out):
        raise NotImplementedError


class Zeros(InitializationMethod):
    """Fill with zeros (nn/InitializationMethod.scala Zeros)."""
    def __call__(self, rng, shape, fan_in, fan_out):
        return jnp.zeros(shape, jnp.float32)


class Ones(InitializationMethod):
    """Fill with ones (nn/InitializationMethod.scala Ones)."""
    def __call__(self, rng, shape, fan_in, fan_out):
        return jnp.ones(shape, jnp.float32)


class ConstInit(InitializationMethod):
    """Fill with a constant value (nn/InitializationMethod.scala ConstInitMethod)."""
    def __init__(self, value):
        self.value = value

    def __call__(self, rng, shape, fan_in, fan_out):
        return jnp.full(shape, self.value, jnp.float32)


#: pyspark spelling (bigdl/nn/initialization_method.py ConstInitMethod)
ConstInitMethod = ConstInit


class RandomUniform(InitializationMethod):
    """U(lower, upper); parameterless variant uses +/- 1/sqrt(fan_in)."""

    def __init__(self, lower=None, upper=None):
        self.lower, self.upper = lower, upper

    def __call__(self, rng, shape, fan_in, fan_out):
        if self.lower is None:
            bound = 1.0 / np.sqrt(max(fan_in, 1))
            lo, hi = -bound, bound
        else:
            lo, hi = self.lower, self.upper
        return jax.random.uniform(rng, shape, jnp.float32, lo, hi)


class RandomNormal(InitializationMethod):
    """N(mean, stdv) init (nn/InitializationMethod.scala RandomNormal)."""
    def __init__(self, mean=0.0, stdv=1.0):
        self.mean, self.stdv = mean, stdv

    def __call__(self, rng, shape, fan_in, fan_out):
        return self.mean + self.stdv * jax.random.normal(rng, shape, jnp.float32)


class Xavier(InitializationMethod):
    """Glorot uniform: U(+/- sqrt(6/(fan_in+fan_out))) — reference default
    for Linear/SpatialConvolution (InitializationMethod.scala:138)."""

    def __call__(self, rng, shape, fan_in, fan_out):
        bound = np.sqrt(6.0 / max(fan_in + fan_out, 1))
        return jax.random.uniform(rng, shape, jnp.float32, -bound, bound)


class MsraFiller(InitializationMethod):
    """Kaiming/He init (InitializationMethod.scala:182)."""

    def __init__(self, var_in_count=True):
        self.var_in_count = var_in_count

    def __call__(self, rng, shape, fan_in, fan_out):
        n = fan_in if self.var_in_count else fan_out
        std = np.sqrt(2.0 / max(n, 1))
        return std * jax.random.normal(rng, shape, jnp.float32)


class BilinearFiller(InitializationMethod):
    """Bilinear upsampling weights for transposed conv (InitializationMethod.scala:215).

    Expects shape (..., kh, kw); fills each kh x kw slice with the bilinear kernel.
    """

    def __call__(self, rng, shape, fan_in, fan_out):
        kh, kw = shape[-2], shape[-1]
        f_h, f_w = np.ceil(kh / 2.0), np.ceil(kw / 2.0)
        c_h, c_w = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h), (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        ys = np.arange(kh)[:, None]
        xs = np.arange(kw)[None, :]
        kern = (1 - np.abs(ys / f_h - c_h)) * (1 - np.abs(xs / f_w - c_w))
        out = np.broadcast_to(kern, shape).astype(np.float32)
        return jnp.asarray(out)


def init_tensor(module, rng, shape, fan_in, fan_out, default, kind="weight"):
    """Pick the override (if set via set_init_method) or the layer default."""
    override = module.weight_init if kind == "weight" else module.bias_init
    method = override if override is not None else default
    return method(rng, shape, fan_in, fan_out)
