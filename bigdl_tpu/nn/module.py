"""Core module abstraction.

Reference: nn/abstractnn/AbstractModule.scala — stateful Torch-style modules
with hand-written ``updateOutput`` / ``updateGradInput`` / ``accGradParameters``.

TPU-native redesign: every module is a *functional core* plus a *Torch shell*.

Functional core (what XLA sees):
  - ``init(rng) -> params``: build this module's (and children's) parameters
    as a flat dict keyed by globally-unique module name -> {'weight': ..., ...}.
  - ``apply(params, x, ctx) -> y``: pure function of the full flat param dict
    and the input activity.  Mutable extras (batch-norm running stats, dropout
    RNG) ride on ``ctx``: persistent state is read from ``ctx.state`` and
    written to ``ctx.new_state``; per-module RNG keys are derived by folding
    the module's uid into ``ctx.rng_key``.  Because state flows through the
    ctx dicts (trace-time python mutation of traced values), the whole model —
    containers included — stays a pure, jittable function
    ``(params, state, rng, x) -> (y, new_state)`` via :meth:`run`.

Torch shell (API parity with the reference):
  - ``forward(x)`` lazily initializes parameters and caches ``self.output``.
  - ``backward(x, grad_output)`` uses ``jax.vjp`` w.r.t. (params, input),
    accumulating into ``self.grad_params`` and returning ``grad_input`` —
    replacing the reference's hand-written backward passes with JAX AD.

There is no hand-scheduled kernel work here: convs/matmuls lower to the MXU
through ``lax``; XLA fuses the elementwise neighbourhoods.
"""
from __future__ import annotations

import functools
import inspect
import itertools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

_uid_counter = itertools.count()


def _fresh_uid():
    return next(_uid_counter)


def _capture_config(cls):
    """Wrap ``cls.__init__`` so constructing an instance records the bound
    constructor arguments on ``self._serde`` (outermost class wins).

    This is what makes module serialization *topology-as-data* (≙ the
    reference's utils/serializer/ModuleSerializer.scala SerializeContext,
    which persists each layer as class name + attribute protobuf): a saved
    model is "class + config + children", re-buildable by calling the
    constructor — never a pickle of the live object graph.
    """
    orig = cls.__init__
    if getattr(orig, "_captures_config", False):
        return
    try:
        sig = inspect.signature(orig)
    except (ValueError, TypeError):  # C-level or exotic signature
        return
    varargs = next((p.name for p in sig.parameters.values()
                    if p.kind is p.VAR_POSITIONAL), None)

    @functools.wraps(orig)
    def __init__(self, *args, **kwargs):
        if not hasattr(self, "_serde"):
            rec = {"class": type(self), "varargs": varargs, "config": None}
            self._serde = rec
            try:
                bound = sig.bind(self, *args, **kwargs)
                bound.apply_defaults()
                cfg = {}
                for pname, p in sig.parameters.items():
                    if pname == "self" or pname not in bound.arguments:
                        continue
                    v = bound.arguments[pname]
                    if p.kind is p.VAR_POSITIONAL:
                        cfg[pname] = list(v)
                    elif p.kind is p.VAR_KEYWORD:
                        cfg.update(v)
                    else:
                        cfg[pname] = v
                rec["config"] = cfg
            except TypeError:
                pass
        orig(self, *args, **kwargs)

    __init__._captures_config = True
    cls.__init__ = __init__


def migrate_legacy_names(tree, module):
    """Rename dict keys written before auto-names were zero-padded
    ('Linear_12' -> 'Linear_00000012') wherever the padded form matches one
    of `module`'s expected param/state names.  Cheap no-op when every key is
    already in the current format."""
    import re

    def has_legacy(t):
        if isinstance(t, dict):
            return any(re.fullmatch(r".*_\d{1,7}", k) or has_legacy(v)
                       for k, v in t.items())
        if isinstance(t, (list, tuple)):
            return any(has_legacy(v) for v in t)
        return False

    if not has_legacy(tree):
        return tree

    expected = set()

    def collect(t):
        if isinstance(t, dict):
            expected.update(t.keys())
            for v in t.values():
                collect(v)
    collect(jax.eval_shape(module.init, jax.random.PRNGKey(0)))
    collect(module.initial_state())

    def pad(k):
        m = re.fullmatch(r"(.*_)(\d{1,7})", k)
        return f"{m.group(1)}{int(m.group(2)):08d}" if m else k

    def migrate(t):
        if isinstance(t, dict):
            return {k if k in expected or pad(k) not in expected
                    else pad(k): migrate(v) for k, v in t.items()}
        if isinstance(t, (list, tuple)):
            return type(t)(migrate(v) for v in t)
        return t

    return migrate(tree)


class Ctx:
    """Per-call context threaded through ``apply``.

    Carries the training flag, the base RNG key, persistent state in/out
    dicts, and a scratch list for side losses (e.g. ActivityRegularization).
    """

    __slots__ = ("training", "rng_key", "state", "new_state",
                 "side_losses", "step_rng")

    def __init__(self, state=None, training=False, rng_key=None):
        self.training = training
        self.rng_key = rng_key
        self.state = state or {}
        self.new_state: Dict[str, Any] = {}
        self.side_losses = []
        # per-timestep key a Recurrent scan threads through its carry so
        # stochastic cells (LSTM/GRU p>0) draw fresh masks each step
        self.step_rng = None

    def rng(self, module) -> jax.Array:
        if self.rng_key is None:
            raise ValueError(
                f"{module.name}: this module needs an RNG key in training mode; "
                "pass rng= to run()/forward()")
        return jax.random.fold_in(self.rng_key, module._uid % (2 ** 31))

    def get_state(self, module):
        return self.state.get(module.name)

    def put_state(self, module, value):
        self.new_state[module.name] = value

    def add_loss(self, value):
        self.side_losses.append(value)


class Module:
    """Base class of all layers and containers."""

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if "__init__" in cls.__dict__:
            _capture_config(cls)

    def __init__(self, name: Optional[str] = None):
        self._uid = _fresh_uid()
        # zero-pad so lexicographic dict-key order (JAX pytree flatten order)
        # matches creation order even across uid digit-count boundaries
        self.name = name or f"{type(self).__name__}_{self._uid:08d}"
        # Torch-shell mutable state
        self.output = None
        self.grad_input = None
        self._params: Optional[Dict[str, Any]] = None
        self._state: Dict[str, Any] = {}
        self.grad_params: Optional[Dict[str, Any]] = None
        self.train_mode = False
        self._forward_rng = np.random.randint(0, 2 ** 31)
        # init-method overrides (nn/abstractnn/Initializable.scala)
        self.weight_init = None
        self.bias_init = None
        # per-layer regularizers (optim/Regularizer.scala)
        self.w_regularizer = None
        self.b_regularizer = None
        self.scale_w = 1.0  # gradient scale factors (AbstractModule.setScaleW)
        self.scale_b = 1.0

    # ------------------------------------------------------------------ #
    # functional core — subclasses override these two                    #
    # ------------------------------------------------------------------ #
    def init(self, rng) -> Dict[str, Any]:
        """Return the flat params dict for this module (and children)."""
        return {}

    def apply(self, params: Dict[str, Any], x, ctx: Ctx):
        """Pure forward. Subclasses must implement."""
        raise NotImplementedError(type(self).__name__)

    def initial_state(self) -> Dict[str, Any]:
        """Flat dict of persistent non-trainable state (e.g. BN stats)."""
        return {}

    # convenience for leaf layers
    def own(self, params):
        return params.get(self.name, {})

    # ------------------------------------------------------------------ #
    # functional entry point                                             #
    # ------------------------------------------------------------------ #
    def run(self, params, x, state=None, training=False, rng=None):
        """(params, x[, state, rng]) -> (y, new_state). Pure; safe under jit."""
        ctx = Ctx(state=state, training=training, rng_key=rng)
        y = self.apply(params, x, ctx)
        out_state = dict(state or {})
        out_state.update(ctx.new_state)
        return y, out_state

    def init_params(self, seed: int = 0):
        """Initialize and return (params, state)."""
        rng = jax.random.PRNGKey(seed)
        return self.init(rng), self.initial_state()

    # ------------------------------------------------------------------ #
    # Torch shell — API parity with the reference AbstractModule         #
    # ------------------------------------------------------------------ #
    def ensure_initialized(self, seed: int = 0):
        if self._params is None:
            self._params, self._state = self.init_params(
                getattr(self, "_init_seed", seed))
        return self._params

    @property
    def parameters_(self):
        return self.ensure_initialized()

    def forward(self, x, rng=None):
        self.ensure_initialized()
        if rng is None:
            self._forward_rng += 1
            rng = jax.random.PRNGKey(self._forward_rng)
        self._last_rng = rng  # backward must replay the same stochastic pass
        y, new_state = self.run(self._params, x, state=self._state,
                                training=self.train_mode, rng=rng)
        if self.train_mode:
            self._state = new_state
        self.output = y
        return y

    def __call__(self, x, rng=None):
        return self.forward(x, rng=rng)

    def backward(self, x, grad_output, rng=None):
        """grad_input via jax.vjp; accumulates param grads into grad_params."""
        self.ensure_initialized()
        if rng is None:
            rng = getattr(self, "_last_rng", None)
            if rng is None:
                rng = jax.random.PRNGKey(self._forward_rng)

        def f(params, inp):
            y, _ = self.run(params, inp, state=self._state,
                            training=self.train_mode, rng=rng)
            return y

        y, vjp_fn = jax.vjp(f, self._params, x)
        gparams, ginput = vjp_fn(grad_output)
        if self.grad_params is None:
            self.grad_params = gparams
        else:
            self.grad_params = jax.tree_util.tree_map(
                jnp.add, self.grad_params, gparams)
        self.grad_input = ginput
        self.output = y
        return ginput

    def update_output(self, x):
        return self.forward(x)

    def update_grad_input(self, x, grad_output):
        return self.backward(x, grad_output)

    def zero_grad_parameters(self):
        self.grad_params = None

    def update_parameters(self, learning_rate):
        """One manual SGD step from the Torch shell's accumulated
        grad_params; frozen modules stay untouched
        (≙ Layer.update_parameters)."""
        if self.grad_params is None:
            raise ValueError("no accumulated gradients; call backward first")
        frozen = self.frozen_param_names()
        self._params = {
            name: (sub if name in frozen else jax.tree_util.tree_map(
                lambda p, g: p - learning_rate * g, sub,
                self.grad_params[name]))
            for name, sub in self._params.items()}
        return self

    def get_parameters(self):
        """Return (params, grad_params) flat dicts (≙ reference getParameters)."""
        self.ensure_initialized()
        if self.grad_params is None:
            self.grad_params = jax.tree_util.tree_map(
                jnp.zeros_like, self._params)
        return self._params, self.grad_params

    def set_params(self, params, state=None):
        self._params = params
        if state is not None:
            self._state = state
        return self

    # -- pyspark Layer-method parity (bigdl/nn/layer.py) ---------------- #
    @staticmethod
    def _weights_order(sub):
        """Per-module key order for get/set_weights: weight* first, bias*
        second, the rest alphabetically — matching the reference
        Layer.get_weights [weight, bias] convention."""
        def rank(k):
            if k.startswith("weight"):
                return (0, k)
            if k.startswith("bias"):
                return (1, k)
            return (2, k)
        return sorted(sub, key=rank)

    def get_weights(self):
        """Flat list of this model's weight arrays, module-traversal order
        with per-module keys weight-first (≙ Layer.get_weights)."""
        self.ensure_initialized()
        out = []
        for m in self.modules():
            sub = self._params.get(m.name)
            if sub:
                for k in self._weights_order(sub):
                    out.append(np.asarray(sub[k]))
        return out

    def set_weights(self, weights):
        """Inverse of :meth:`get_weights`; shapes are validated."""
        self.ensure_initialized()
        ws = list(weights)
        new = dict(self._params)
        i = 0
        for m in self.modules():
            sub = self._params.get(m.name)
            if not sub:
                continue
            cur = {}
            for k in self._weights_order(sub):
                if i >= len(ws):
                    raise ValueError(
                        f"set_weights: {len(ws)} arrays given, more needed "
                        f"(stopped at {m.name}.{k})")
                arr = jnp.asarray(ws[i])
                i += 1
                if tuple(arr.shape) != tuple(np.shape(sub[k])):
                    raise ValueError(
                        f"set_weights: {m.name}.{k} expects "
                        f"{np.shape(sub[k])}, got {arr.shape}")
                cur[k] = arr
            new[m.name] = cur
        if i != len(ws):
            raise ValueError(f"set_weights: {len(ws)} arrays given, "
                             f"only {i} consumed")
        self._params = new
        return self

    def parameters(self):
        """{module_name: {param_name: ndarray}} (≙ Layer.parameters)."""
        self.ensure_initialized()
        return {name: {k: np.asarray(v) for k, v in sub.items()}
                for name, sub in self._params.items()}

    def freeze(self, names=None):
        """Mark this module — or the named submodules — non-trainable;
        training drivers zero their gradients (≙ Layer.freeze, the
        fine-tuning workflow).  Per-layer regularizers are masked with
        the gradients; an OptimMethod-level ``weight_decay`` still
        applies to every parameter, so prefer layer regularizers when
        freezing."""
        if names is None:
            for m in self.modules():
                m._frozen = True
        else:
            wanted = set(names)
            hit = set()
            for m in self.modules():
                if m.name in wanted:
                    hit.add(m.name)
                    for sub in m.modules():
                        sub._frozen = True
            missing = wanted - hit
            if missing:
                raise ValueError(f"freeze: no submodule named {missing}")
        return self

    def unfreeze(self, names=None):
        """Undo :meth:`freeze` (≙ Layer.unfreeze)."""
        if names is None:
            for m in self.modules():
                m._frozen = False
        else:
            for m in self.modules():
                if m.name in set(names):
                    for sub in m.modules():
                        sub._frozen = False
        return self

    def frozen_param_names(self):
        """Names of modules whose params must not update."""
        return {m.name for m in self.modules()
                if getattr(m, "_frozen", False)}

    def quantize(self, calibration_data=None):
        """Post-training int8 rewrite (≙ Layer.quantize);
        ``calibration_data`` bakes static activation scales."""
        from ..quantized import quantize as _q
        return _q(self, calibration_data=calibration_data)

    def _predictor(self, batch_size):
        # one long-lived Predictor per batch size: its jitted eval step
        # must be reused across predict calls, not recompiled each time
        cache = getattr(self, "_predictors", None)
        if cache is None:
            cache = self._predictors = {}
        if batch_size not in cache:
            from ..optim.predictor import Predictor
            cache[batch_size] = Predictor(self, batch_size=batch_size)
        return cache[batch_size]

    def predict(self, x, batch_size=128):
        """Batched jitted inference (≙ Layer.predict_local)."""
        return self._predictor(batch_size).predict(x)

    def predict_class(self, x, batch_size=128):
        """1-based class predictions (≙ Layer.predict_class)."""
        return self._predictor(batch_size).predict_class(x)

    # pyspark layer.py spellings (predict_distributed ≙ mesh-sharded
    # evaluation — route through DistriOptimizer/Predictor for that)
    predict_local = predict
    predict_class_local = predict_class

    def is_with_weights(self):
        """≙ Layer.is_with_weights: does this module (or any descendant —
        the reference's parameters() aggregates children) carry weights?"""
        p = self.ensure_initialized()
        return any(p.get(m.name) for m in self.modules())

    def set_seed(self, seed=123):
        """Seed FUTURE lazy parameter init (≙ Layer.set_seed).  Never
        re-initializes an already-built module — trained or loaded
        weights must not be silently destroyed; call
        ``reset(seed)`` explicitly for a fresh init."""
        self._init_seed = int(seed)
        return self

    def setWRegularizer(self, w_regularizer):              # noqa: N802
        """≙ Layer.setWRegularizer."""
        self.w_regularizer = w_regularizer
        return self

    def setBRegularizer(self, b_regularizer):              # noqa: N802
        """≙ Layer.setBRegularizer."""
        self.b_regularizer = b_regularizer
        return self

    def _sub_model_to(self, output_layer):
        """Model that ends at the named submodule — Sequential prefix or
        Graph re-outputting at that node (predict_image output_layer)."""
        from .graph import Graph as _Graph
        if type(self).__name__ == "Sequential":
            kids = self.children()
            for i, m in enumerate(kids):
                if m.name == output_layer:
                    from .containers import Sequential as _Seq
                    sub = _Seq(*kids[:i + 1])
                    return sub
            raise ValueError(f"no child named {output_layer!r}")
        if isinstance(self, _Graph):
            for node in self._topo:
                if node.module is not None \
                        and node.module.name == output_layer:
                    return _Graph(self.input_nodes, [node])
            raise ValueError(f"no graph node named {output_layer!r}")
        raise ValueError(
            "output_layer= needs a Sequential or Graph model")

    def predict_image(self, image_frame, output_layer=None,
                      share_buffer=False, batch_per_partition=4,
                      predict_key="predict"):
        """Predict every image of an ImageFrame, storing each result
        under ``predict_key`` on its ImageFeature (≙ Layer.predict_image
        / images/Utils.scala modelPredictImage).  Uses the prepared
        ``sample`` feature when a to-sample transform ran, else the raw
        CHW image.  ``share_buffer=True`` skips the defensive copy."""
        import numpy as np
        from ..data.imageframe import ImageFeature
        self.ensure_initialized()
        model = self
        if output_layer is not None:
            # cache sub-models per output layer: each owns a jitted
            # Predictor that must be reused, not recompiled per call
            cache = getattr(self, "_sub_models", None)
            if cache is None:
                cache = self._sub_models = {}
            if output_layer not in cache:
                cache[output_layer] = self._sub_model_to(output_layer)
            model = cache[output_layer]
            # re-sync EVERY call, not once at cache fill: set_weights /
            # load_weights / a training loop replace self._params, and a
            # one-time snapshot would keep predicting with stale weights
            model._params, model._state = self._params, self._state
        feats = list(image_frame)
        xs = []
        for f in feats:
            if ImageFeature.SAMPLE in f:
                xs.append(np.asarray(f[ImageFeature.SAMPLE].feature()))
            else:
                img = np.asarray(f[ImageFeature.IMAGE], np.float32)
                if img.ndim == 2:          # grayscale HW -> (1, H, W)
                    img = img[None]
                else:                      # HWC -> CHW
                    img = np.transpose(img, (2, 0, 1))
                xs.append(img)
        shapes = {x.shape for x in xs}
        if len(shapes) > 1:
            raise ValueError(
                f"predict_image: images have mixed shapes {sorted(shapes)} "
                "— add a Resize / to-sample transform to the ImageFrame "
                "first (≙ the reference's transform-before-predict "
                "pipeline)")
        preds = np.asarray(model.predict(np.stack(xs),
                                         batch_size=max(1,
                                                        batch_per_partition)))
        for f, p in zip(feats, preds):
            f[predict_key] = p if share_buffer else np.array(p, copy=True)
        return image_frame

    def saveModel(self, path, over_write=True):          # noqa: N802
        """pyspark spelling of :meth:`save`."""
        return self.save(path, overwrite=over_write)

    def save_caffe(self, prototxt_path, model_path, **kw):
        """≙ Layer.save_caffe (utils/caffe.save_caffe)."""
        from ..utils.caffe import save_caffe as _sc
        return _sc(self, prototxt_path, model_path, **kw)

    def save_tensorflow(self, path, input_shape, **kw):
        """≙ Layer.save_tensorflow (utils/tf_import.save_tf_graph)."""
        from ..utils.tf_import import save_tf_graph as _stf
        return _stf(self, path, input_shape, **kw)

    def set_running_mean(self, mean):
        """Overwrite this module's BN running mean (≙ Layer.set_running_mean).
        For a BN layer inside a container, call
        ``model.set_running_stats(bn_name, mean=...)`` on the model that
        owns the state instead."""
        return self._set_running(self.name, "running_mean", mean)

    def set_running_std(self, std):
        """Overwrite this module's BN running variance
        (≙ Layer.set_running_std; the reference stores variance).  See
        :meth:`set_running_mean` for layers inside containers."""
        return self._set_running(self.name, "running_var", std)

    def set_running_stats(self, module_name, mean=None, std=None):
        """Overwrite a named submodule's BN running statistics in THIS
        model's state (the container owns its children's state — calling
        set_running_mean on the child would touch a private copy)."""
        if mean is not None:
            self._set_running(module_name, "running_mean", mean)
        if std is not None:
            self._set_running(module_name, "running_var", std)
        return self

    def _set_running(self, module_name, key, value):
        if self._state is None and module_name != self.name:
            raise ValueError(
                "model state not initialized; run forward/init first")
        self.ensure_initialized()
        own = self._state.get(module_name)
        if not isinstance(own, dict) or key not in own:
            if module_name == self.name:
                raise ValueError(
                    f"{type(self).__name__} has no {key} state (not a "
                    "batch-normalization layer, or inside a container — "
                    "use model.set_running_stats(name, ...) there)")
            raise ValueError(f"no submodule state {module_name!r} with "
                             f"{key} in this model")
        value = jnp.asarray(value)
        if value.shape != own[key].shape:
            raise ValueError(f"{key} expects shape {own[key].shape}, "
                             f"got {value.shape}")
        new_state = dict(self._state)
        new_state[module_name] = dict(own, **{key: value})
        self._state = new_state
        return self

    def training(self):
        self.train_mode = True
        for m in self.children():
            m.training()
        return self

    def evaluate(self, *args):
        """No arguments: switch to eval mode (returns self).

        ``evaluate(dataset, batch_size, val_methods)``: benchmark model
        quality — the pyspark 3-arg form (`bigdl/nn/layer.py
        Layer.evaluate`); returns ``[(method, result), ...]`` like
        `optim.Evaluator.test`."""
        if args:
            if len(args) != 3:
                raise TypeError(
                    "evaluate() takes either no arguments (set eval "
                    "mode) or (dataset, batch_size, val_methods)")
            dataset, batch_size, val_methods = args
            from ..optim.predictor import Evaluator
            # cache the Evaluator (its jitted eval step) per batch size:
            # a per-epoch validation loop must not retrace every call
            cached = getattr(self, "_evaluator_cache", None)
            if cached is None or cached[0] != batch_size:
                cached = (batch_size, Evaluator(self,
                                                batch_size=batch_size))
                self._evaluator_cache = cached
            return cached[1].test(dataset, val_methods)
        self.train_mode = False
        for m in self.children():
            m.evaluate()
        return self

    def is_training(self):
        return self.train_mode

    # ------------------------------------------------------------------ #
    # structure & introspection                                          #
    # ------------------------------------------------------------------ #
    def children(self):
        return []

    # -- serde hooks (utils/serializer.py v2 format) -------------------- #
    # extra instance attributes to persist alongside the ctor config
    _serde_extra_attrs = ()

    def _serde_children(self):
        """Children to persist (None entries allowed as placeholders)."""
        return self.children()

    def _serde_restore_children(self, children):
        """Re-attach deserialized children after config reconstruction.

        Default: no-op — right for leaf modules and for modules whose
        constructor deterministically rebuilds its children from the
        replayed config (their persisted children list is then redundant).
        Classes that accept children post-construction (``add``/attribute
        assignment) must override this, or a reloaded model silently loses
        the added children.
        """

    def _serde_config(self):
        """Ctor config to persist; None = 'not reconstructible from
        config' (the class must then override ``_serde_build``)."""
        serde = getattr(self, "_serde", None)
        return dict(serde["config"]) if serde and serde.get("config") \
            is not None else None

    @classmethod
    def _serde_build(cls, config, children):
        """Construct from decoded config+children when plain ctor replay
        can't work.  Return None to use ctor replay (the default)."""
        return None

    def modules(self):
        """Depth-first list of this module and all descendants."""
        out = [self]
        for c in self.children():
            out.extend(c.modules())
        return out

    def named_modules(self):
        return {m.name: m for m in self.modules()}

    def set_name(self, name):
        self.name = name
        return self

    def get_name(self):
        return self.name

    def set_init_method(self, weight_init=None, bias_init=None):
        self.weight_init = weight_init
        self.bias_init = bias_init
        return self

    def set_scale_w(self, s):
        self.scale_w = s
        return self

    def set_scale_b(self, s):
        self.scale_b = s
        return self

    def parameter_count(self):
        params = self.ensure_initialized()
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))

    def get_output_shape(self, input_shape, dtype=jnp.float32):
        """Shape inference via jax.eval_shape (≙ nn/abstractnn/InferShape.scala)."""
        params, state = self.init_params(0)
        if isinstance(input_shape[0], (tuple, list)):
            x = [jax.ShapeDtypeStruct(tuple(s), dtype) for s in input_shape]
        else:
            x = jax.ShapeDtypeStruct(tuple(input_shape), dtype)
        out = jax.eval_shape(
            lambda p, i: self.run(p, i, state=state,
                                  rng=jax.random.PRNGKey(0))[0], params, x)
        return jax.tree_util.tree_map(lambda s: s.shape, out)

    # regularization support: collect per-layer penalties over a params dict
    def regularization_loss(self, params):
        loss = 0.0
        for m in self.modules():
            p = params.get(m.name)
            if not p:
                continue
            if m.w_regularizer is not None and "weight" in p:
                loss = loss + m.w_regularizer(p["weight"])
            if m.b_regularizer is not None and "bias" in p:
                loss = loss + m.b_regularizer(p["bias"])
        return loss

    # ------------------------------------------------------------------ #
    # persistence (≙ AbstractModule.save / Module.load)                  #
    # ------------------------------------------------------------------ #
    def save(self, path, overwrite=True):
        from ..utils import serializer
        serializer.save_module(self, path, overwrite=overwrite)
        return self

    @staticmethod
    def load(path):
        from ..utils import serializer
        return serializer.load_module(path)

    def save_weights(self, path, overwrite=True):
        from ..utils import serializer
        self.ensure_initialized()
        serializer.save_weights_file(self, path)
        return self

    def load_weights(self, path):
        from ..utils import serializer
        params, state = serializer.load_weights_file(path)
        params, state = migrate_legacy_names((params, state), self)
        # jnp.array(copy=True), NOT jnp.asarray: asarray can zero-copy
        # ADOPT an aligned np.load buffer, and a later donated train
        # step would scribble over memory numpy still owns (GL001, the
        # PR-3 restore corruption shape)
        own = lambda v: jnp.array(v, copy=True)
        self._params = jax.tree_util.tree_map(own, params)
        self._state = jax.tree_util.tree_map(own, state)
        return self

    def __repr__(self):
        return f"{type(self).__name__}({self.name})"

    # reference API aliases -------------------------------------------- #
    def reset(self, seed: int = 0):
        self._params, self._state = self.init_params(seed)
        return self

    def clear_state(self):
        self.output = None
        self.grad_input = None
        return self


# classes that don't define their own __init__ fall through to the base
# ctor; wrap it too so every instance gets its ctor config captured
_capture_config(Module)


class Criterion:
    """Base of all losses (nn/abstractnn/AbstractCriterion.scala):
    ``loss(output, target)`` pure fn + Torch-style forward/backward shell."""
    """Base loss (≙ nn/abstractnn/AbstractCriterion.scala).

    Subclasses implement ``loss(output, target) -> scalar``.  ``forward``
    caches the value; ``backward`` returns d loss / d output via JAX AD,
    replacing the reference's hand-written updateGradInput.
    """

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if "__init__" in cls.__dict__:
            _capture_config(cls)

    def __init__(self, name: Optional[str] = None):
        self._uid = _fresh_uid()
        # zero-pad so lexicographic dict-key order (JAX pytree flatten order)
        # matches creation order even across uid digit-count boundaries
        self.name = name or f"{type(self).__name__}_{self._uid:08d}"
        self.output = None
        self.grad_input = None

    def loss(self, output, target):
        raise NotImplementedError

    def forward(self, output, target):
        self.output = self.loss(output, target)
        return self.output

    def __call__(self, output, target):
        return self.forward(output, target)

    def backward(self, output, target):
        self.grad_input = jax.grad(lambda o: self.loss(o, target))(output)
        return self.grad_input

    def __repr__(self):
        return f"{type(self).__name__}()"


_capture_config(Criterion)
