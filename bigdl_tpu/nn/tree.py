"""Tree-structured LSTMs (≙ nn/TreeLSTM.scala, BinaryTreeLSTM.scala).

The reference walks the tree recursively on the JVM (BinaryTreeLSTM.scala:
recursiveForward), which cannot compile to a single XLA graph.  Here the
tree is encoded as index tensors and the whole composition runs as ONE
``lax.scan`` over nodes in topological (children-first) order, reading and
writing a (maxNodes, hidden) state buffer with dynamic gathers — fixed
shapes, no host round-trips, batched over B via vmap inside the scan body.

Tree encoding (per batch element):
  ``tree``: (nNodes, 3) int32 — [left_child, right_child, leaf_word_index],
  1-based, 0 = absent.  Internal nodes have children; leaves have a word
  index into the embedding sequence.  Nodes must be ordered so children
  precede parents (standard post-order numbering); the root is the last
  node with any entry.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .module import Module
from .init import Xavier, Zeros, init_tensor
from ..utils.table import as_list


class TreeLSTM(Module):
    """Base for tree-composed LSTMs (nn/TreeLSTM.scala:30): holds sizes and
    the (embeddings, tree) Table input convention."""

    def __init__(self, input_size, hidden_size, name=None):
        super().__init__(name=name)
        self.input_size = input_size
        self.hidden_size = hidden_size


class BinaryTreeLSTM(TreeLSTM):
    """Constituency (binary) Tree-LSTM (nn/BinaryTreeLSTM.scala:44,
    after Tai et al. 2015 eq. 9-14).

    Input: Table(embeddings (B, seqLen, inputSize), tree (B, nNodes, 3)).
    Output: (B, nNodes, hiddenSize) hidden state per node (zeros for absent
    nodes), root last — callers select the root with Select/Index like the
    reference's TreeNNAccuracy harness.
    """

    def __init__(self, input_size, hidden_size, gate_output=True, name=None):
        super().__init__(input_size, hidden_size, name=name)
        self.gate_output = gate_output

    def init(self, rng):
        ks = jax.random.split(rng, 6)
        H, D = self.hidden_size, self.input_size
        def mat(k, shape, fi, fo):
            return init_tensor(self, k, shape, fi, fo, Xavier())
        p = {
            # leaf transform: i, o, u gates from word embedding
            "leaf_w": mat(ks[0], (D, 3 * H), D, 3 * H),
            "leaf_b": jnp.zeros((3 * H,), jnp.float32),
            # composer: i, lf, rf, u, o gates from (h_l, h_r)
            "comp_wl": mat(ks[1], (H, 5 * H), H, 5 * H),
            "comp_wr": mat(ks[2], (H, 5 * H), H, 5 * H),
            "comp_b": jnp.zeros((5 * H,), jnp.float32),
        }
        return {self.name: p}

    def apply(self, params, x, ctx):
        p = self.own(params)
        emb, tree = as_list(x)[:2]
        tree = tree.astype(jnp.int32)
        B, n_nodes = tree.shape[0], tree.shape[1]
        H = self.hidden_size

        def leaf(word_vec):
            z = word_vec @ p["leaf_w"] + p["leaf_b"]
            i, o, u = jnp.split(z, 3, axis=-1)
            c = jax.nn.sigmoid(i) * jnp.tanh(u)
            o = jax.nn.sigmoid(o) if self.gate_output else jnp.ones_like(o)
            return o * jnp.tanh(c), c

        def compose(hl, cl, hr, cr):
            z = hl @ p["comp_wl"] + hr @ p["comp_wr"] + p["comp_b"]
            i, lf, rf, u, o = jnp.split(z, 5, axis=-1)
            c = (jax.nn.sigmoid(i) * jnp.tanh(u)
                 + jax.nn.sigmoid(lf) * cl + jax.nn.sigmoid(rf) * cr)
            o = jax.nn.sigmoid(o) if self.gate_output else jnp.ones_like(o)
            return o * jnp.tanh(c), c

        # state buffers indexed 1..nNodes (slot 0 = absent child → zeros)
        h_buf = jnp.zeros((B, n_nodes + 1, H), emb.dtype)
        c_buf = jnp.zeros((B, n_nodes + 1, H), emb.dtype)

        def body(bufs, node_ix):
            h_buf, c_buf = bufs
            node = tree[:, node_ix]               # (B, 3)
            left, right, word = node[:, 0], node[:, 1], node[:, 2]
            is_leaf = (word > 0) & (left == 0)
            is_absent = (word == 0) & (left == 0) & (right == 0)
            wv = jnp.take_along_axis(
                emb, jnp.maximum(word - 1, 0)[:, None, None], axis=1)[:, 0]
            lh, lc = leaf(wv)
            hl = jnp.take_along_axis(h_buf, left[:, None, None], axis=1)[:, 0]
            cl = jnp.take_along_axis(c_buf, left[:, None, None], axis=1)[:, 0]
            hr = jnp.take_along_axis(h_buf, right[:, None, None],
                                     axis=1)[:, 0]
            cr = jnp.take_along_axis(c_buf, right[:, None, None],
                                     axis=1)[:, 0]
            ch, cc = compose(hl, cl, hr, cr)
            h = jnp.where(is_leaf[:, None], lh, ch)
            c = jnp.where(is_leaf[:, None], lc, cc)
            h = jnp.where(is_absent[:, None], 0.0, h)
            c = jnp.where(is_absent[:, None], 0.0, c)
            slot = jnp.full((B,), node_ix + 1)
            h_buf = _scatter_rows(h_buf, slot, h)
            c_buf = _scatter_rows(c_buf, slot, c)
            return (h_buf, c_buf), None

        (h_buf, _), _ = lax.scan(body, (h_buf, c_buf),
                                 jnp.arange(n_nodes))
        return h_buf[:, 1:]


def _scatter_rows(buf, slots, rows):
    """buf[b, slots[b]] = rows[b] for each batch element."""
    b_idx = jnp.arange(buf.shape[0])
    return buf.at[b_idx, slots].set(rows)
